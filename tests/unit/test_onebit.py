"""1-bit optimizer family tests (reference
``tests/unit/runtime/half_precision/onebit/test_onebit.py`` strategy:
convergence parity vs the uncompressed twin + wire-format checks)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                           error_shapes, pack_signs,
                                           unpack_signs)
from deepspeed_tpu.runtime.onebit import (scale_by_onebit_adam,
                                          scale_by_onebit_lamb,
                                          scale_by_zero_one_adam)

try:
    from jax import shard_map as _sm

    def shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
except ImportError:  # older jax spelling
    from jax.experimental.shard_map import shard_map as _sme

    def shard_map(f, mesh, in_specs, out_specs):
        return _sme(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False)


@pytest.fixture(scope="module")
def topo():
    return dist.initialize_mesh(dp=8)


class TestPackUnpack:
    def test_roundtrip(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(64,)),
                        jnp.float32)
        s = jnp.sign(x)
        s = jnp.where(s == 0, 1.0, s)
        assert np.array_equal(np.asarray(unpack_signs(pack_signs(s))),
                              np.asarray(s))

    def test_packed_size(self):
        assert pack_signs(jnp.ones((80,))).shape == (10,)


class TestCompressedAllreduce:
    def test_error_feedback_reduces_bias(self, topo):
        """Over repeated reductions of the SAME tensor, error feedback
        makes the time-average converge to the true mean (the 1-bit Adam
        lemma); a single shot is heavily quantized."""
        n = 8
        numel = 1024
        rng = np.random.default_rng(1)
        xs = rng.normal(size=(n, numel)).astype(np.float32)
        true_mean = xs.mean(axis=0)
        wn, sn = error_shapes(numel, n)

        @jax.jit
        @functools.partial(
            shard_map, mesh=topo.mesh,
            in_specs=(P(("data", "data_sub")), P(("data", "data_sub")),
                      P(("data", "data_sub"))),
            out_specs=(P(("data", "data_sub")), P(("data", "data_sub")),
                       P(("data", "data_sub"))))
        def reduce_once(x, we, se):
            out, nwe, nse = compressed_allreduce(
                x[0], we[0], se[0], group="data")
            return out[None], nwe[None], nse[None]

        we = jnp.zeros((n, wn), jnp.float32)
        se = jnp.zeros((n, sn), jnp.float32)
        x = jnp.asarray(xs)
        outs = []
        for _ in range(30):
            out, we, se = reduce_once(x, we, se)
            outs.append(np.asarray(out[0]))
        single = np.abs(outs[0] - true_mean).mean()
        averaged = np.abs(np.mean(outs, axis=0) - true_mean).mean()
        assert averaged < single * 0.35, (single, averaged)

    def test_identity_when_group_of_one(self, topo):
        x = jnp.arange(32, dtype=jnp.float32)
        wn, sn = error_shapes(32, 1)
        out, we, se = compressed_allreduce(
            x, jnp.zeros((wn,)), jnp.zeros((sn,)), group=None)
        assert np.array_equal(np.asarray(out), np.asarray(x))


def _quadratic_problem(n_members, dim, seed=0):
    """Members hold different quadratic losses; the consensus minimum is
    the mean target."""
    rng = np.random.default_rng(seed)
    targets = rng.normal(size=(n_members, dim)).astype(np.float32)
    return targets, targets.mean(axis=0)


class TestOnebitAdamConvergence:
    def test_matches_adam_during_warmup(self):
        """group=None, freeze far away: identical to optax adam scaling."""
        tx = scale_by_onebit_adam(freeze_step=1000)
        ref = optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8)
        params = {"w": jnp.asarray([1.0, -2.0, 3.0])}
        s1, s2 = tx.init(params), ref.init(params)
        g = {"w": jnp.asarray([0.1, 0.2, -0.3])}
        for _ in range(5):
            u1, s1 = tx.update(g, s1)
            u2, s2 = ref.update(g, s2)
        np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                                   rtol=1e-5)

    def test_weight_decay_decoupled(self):
        tx = scale_by_onebit_adam(freeze_step=1000, weight_decay=0.1)
        tx0 = scale_by_onebit_adam(freeze_step=1000)
        params = {"w": jnp.asarray([2.0, -4.0])}
        g = {"w": jnp.asarray([0.1, 0.1])}
        u, _ = tx.update(g, tx.init(params), params)
        u0, _ = tx0.update(g, tx0.init(params), params)
        np.testing.assert_allclose(
            np.asarray(u["w"] - u0["w"]),
            0.1 * np.asarray(params["w"]), rtol=1e-6)

    def test_frozen_variance_after_freeze(self):
        tx = scale_by_onebit_adam(freeze_step=3)
        params = {"w": jnp.zeros((4,))}
        s = tx.init(params)
        g = {"w": jnp.asarray([0.5, -0.5, 0.25, 1.0])}
        for _ in range(3):
            _, s = tx.update(g, s)
        nu_frozen = np.asarray(s.nu["w"])
        for _ in range(4):
            _, s = tx.update(
                {"w": jnp.asarray([5.0, 5.0, 5.0, 5.0])}, s)
        np.testing.assert_array_equal(np.asarray(s.nu["w"]), nu_frozen)

    def test_dp_training_tracks_uncompressed(self, topo):
        """Manual-DP loop: 1-bit Adam with compressed momentum sync
        converges to the consensus optimum like full-precision Adam."""
        n, dim = 8, 256
        targets, opt_point = _quadratic_problem(n, dim)
        freeze = 10
        tx = scale_by_onebit_adam(freeze_step=freeze, group="data")
        # noise floor of the compressed stage scales with lr (sign*scale
        # reconstruction error); small lr + enough steps isolates bias
        lr = 0.02

        params0 = jnp.zeros((dim,), jnp.float32)
        t = jnp.asarray(targets)

        def member_step(params, target, state):
            grads = params - target          # d/dp 0.5||p - t||^2
            updates, state = tx.update({"w": grads}, state,
                                       {"w": params})
            return params - lr * updates["w"], state

        @functools.partial(
            shard_map, mesh=topo.mesh,
            in_specs=(P(), P(("data", "data_sub"))),
            out_specs=P())
        def run(params, targets_shard):
            state = tx.init({"w": params})

            def body(carry, _):
                p, s = carry
                p, s = member_step(p, targets_shard[0], s)
                return (p, s), None

            (p, _), _ = jax.lax.scan(body, (params, state), None,
                                     length=400)
            # members end in consensus (momentum synced); average for
            # reporting
            return jax.lax.pmean(p, ("data", "data_sub"))

        final = np.asarray(run(params0, t))
        err = np.abs(final - opt_point).mean() / (
            np.abs(opt_point).mean() + 1e-9)
        assert err < 0.25, err


class TestZeroOneAdam:
    def test_variance_update_interval(self):
        tx = scale_by_zero_one_adam(var_freeze_step=100,
                                    var_update_scaler=4)
        params = {"w": jnp.zeros((4,))}
        s = tx.init(params)
        g = {"w": jnp.ones((4,))}
        nus = []
        for _ in range(8):
            _, s = tx.update(g, s)
            nus.append(np.asarray(s.nu["w"]).copy())
        # updates at steps 1, 4, 8 only
        assert np.array_equal(nus[1], nus[2])        # 2 == 3 (no update)
        assert not np.array_equal(nus[2], nus[3])    # 4 updates
        assert np.array_equal(nus[4], nus[6])        # 5..7 frozen
        assert not np.array_equal(nus[6], nus[7])    # 8 updates

    def test_local_steps_defer_sync(self, topo):
        """After var freeze, sync happens at exponentially spaced steps;
        in between, members drift (pure local steps)."""
        n, dim = 8, 64
        targets, _ = _quadratic_problem(n, dim, seed=3)
        tx = scale_by_zero_one_adam(var_freeze_step=2, group="data",
                                    local_step_clipper=3)

        t = jnp.asarray(targets)

        @functools.partial(
            shard_map, mesh=topo.mesh,
            in_specs=(P(), P(("data", "data_sub"))),
            out_specs=P(("data", "data_sub")))
        def run(params, targets_shard):
            state = tx.init({"w": params})

            def body(carry, _):
                p, s = carry
                grads = p - targets_shard[0]
                u, s = tx.update({"w": grads}, s, {"w": p})
                return (p - 0.05 * u["w"], s), None

            (p, _), _ = jax.lax.scan(body, (params, state), None,
                                     length=20)
            return p[None]

        finals = np.asarray(run(jnp.zeros((dim,), jnp.float32), t))
        # members hold different local params between syncs -> not all equal
        spread = np.abs(finals - finals.mean(axis=0)).max()
        assert np.isfinite(finals).all()
        assert spread >= 0  # smoke: drift allowed, must stay finite


class TestOnebitLamb:
    def test_trust_ratio_scales_updates(self):
        tx = scale_by_onebit_lamb(freeze_step=100)
        big = {"w": jnp.full((8,), 100.0)}
        small = {"w": jnp.full((8,), 0.01)}
        g = {"w": jnp.full((8,), 0.1)}
        sb, ss = tx.init(big), tx.init(small)
        ub, _ = tx.update(g, sb, big)
        us, _ = tx.update(g, ss, small)
        # same gradient; larger params -> larger trusted step
        assert np.abs(ub["w"]).mean() > np.abs(us["w"]).mean()


class TestEngineIntegration:
    @pytest.mark.slow
    def test_onebit_adam_engine_stage0(self, topo):
        """Engine accepts OneBitAdam at stage 0 and trains (compressed
        momentum path inside the jitted step)."""
        import deepspeed_tpu
        from tests.unit.simple_model import random_tokens, tiny_gpt2

        ds = {
            "train_batch_size": 8,
            "zero_optimization": {"stage": 0},
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 3}},
            "steps_per_print": 1000,
        }
        batch = random_tokens(8)
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_gpt2(), config=ds, topology=topo,
            example_batch=batch, rng=jax.random.PRNGKey(0))
        losses = [float(jax.device_get(engine.train_batch(batch=batch)))
                  for _ in range(6)]
        assert losses[-1] < losses[0]
        assert all(np.isfinite(l) for l in losses)

    def test_onebit_adam_zero_stage_falls_back(self, topo):
        """ZeRO >= 1 is incompatible (reference restriction): warn and use
        the uncompressed base optimizer."""
        import deepspeed_tpu
        from tests.unit.simple_model import random_tokens, tiny_gpt2

        ds = {
            "train_batch_size": 8,
            "zero_optimization": {"stage": 2},
            "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
        }
        batch = random_tokens(8)
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_gpt2(), config=ds, topology=topo,
            example_batch=batch, rng=jax.random.PRNGKey(0))
        loss = float(jax.device_get(engine.train_batch(batch=batch)))
        assert np.isfinite(loss)
