"""Test bootstrap: run every test on a virtual 8-device CPU mesh.

Mirrors the reference test strategy (SURVEY §4): distributed logic is
exercised single-node with fake devices — here via
``--xla_force_host_platform_device_count=8`` instead of forked torch
processes, since one JAX controller drives all 8 virtual devices.
"""
import os

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may already be imported by site customization before this file runs, so
# env vars alone are not enough — use jax.config (valid until backends
# initialize). The real-TPU path is exercised by bench.py / __graft_entry__.py.
if not os.environ.get("DSTPU_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    # The suite is XLA-compile-bound (a long tail of 5-20 s tests, each
    # building unique tiny-model programs whose execution takes
    # milliseconds) — skip the backend optimization passes on the CPU
    # test path: measured 46% off the heaviest file, same results.
    # Anything timing-sensitive runs on real hardware via bench.py, not
    # here.  An explicit user setting of the flag wins.
    if "--xla_backend_optimization_level" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_backend_optimization_level=0")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # pre-0.5 jax: the option doesn't exist; the XLA flag does the
        # same as long as backends haven't initialized yet
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_count=8")
        # modern jax defaults this on; without it, params initialized
        # under different shardings draw different random values, which
        # breaks every dp-vs-tp parity test
        jax.config.update("jax_threefry_partitionable", True)
    # opt-in persistent XLA compile cache (DSTPU_XLA_CACHE=<dir>): warm
    # runs halve suite time, but this jaxlib's cache path is not stable
    # enough for the gate — a full-suite run with the cache enabled
    # segfaulted mid-suite (2026-08, cache writes + old-jaxlib
    # deserialization), so never on by default
    if os.environ.get("DSTPU_XLA_CACHE"):
        try:
            jax.config.update("jax_compilation_cache_dir",
                              os.environ["DSTPU_XLA_CACHE"])
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
        except AttributeError:  # pragma: no cover - jax without the cache
            pass


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_comm_state():
    """Fresh topology per test (tests install their own meshes)."""
    yield
    from deepspeed_tpu.comm import comm as _comm
    _comm._state.topology = None
    _comm.comms_logger.reset()
