"""Headline benchmark: GPT-2 training throughput + MFU on one chip.

Run by the driver on real TPU hardware at the end of every round; prints ONE
JSON line ``{"metric", "value", "unit", "vs_baseline"}``.  The metric is
model FLOPs utilization (MFU) for a bf16 GPT-2 train step — the BASELINE.md
north star is ZeRO-3 Llama-2-7B at >=45% MFU on v5p-128, so ``vs_baseline``
reports value/45.

MFU is computed from *device* step time (jax.profiler XPlane events): this
benchmark may run through a remote-device tunnel whose per-dispatch host
latency (hundreds of ms) is an artifact of the harness, not of the
framework or the chip.  Wall-clock throughput is reported alongside in
``detail`` for transparency.
"""
from __future__ import annotations

import glob
import json
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOPs per chip by device kind substring
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v6": 918e12,  # trillium
    "cpu": 1e12,       # nominal, for smoke runs
}

NORTH_STAR_MFU = 45.0


def peak_flops(kind: str) -> float:
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()) or k.lower() in kind.lower():
            return v
    return 197e12


def device_seconds_per_call(fn, n: int = 10):
    """(device_seconds, wall_seconds) per fn() call.  Device time comes from
    profiler XPlane events (jit_* entries), averaged over the TPU planes so
    multi-chip hosts aren't overcounted; wall time brackets only the call
    loop + sync.  Device time falls back to wall when no device events are
    captured (CPU smoke runs)."""
    trace_dir = "/tmp/dstpu_bench_trace"
    shutil.rmtree(trace_dir, ignore_errors=True)
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.device_get(jax.tree_util.tree_map(jnp.sum, out))
    wall = (time.perf_counter() - t0) / n
    jax.profiler.stop_trace()
    try:
        from jax.profiler import ProfileData

        path = sorted(glob.glob(trace_dir + "/**/*.xplane.pb",
                                recursive=True))[-1]
        pdata = ProfileData.from_file(path)
        total_ns = 0
        n_planes = 0
        for plane in pdata.planes:
            if "TPU" not in plane.name:
                continue
            plane_ns = 0
            for line in plane.lines:
                for ev in line.events:
                    if ev.name.startswith("jit_"):
                        plane_ns += ev.duration_ns
            if plane_ns > 0:
                n_planes += 1
                total_ns += plane_ns
        if total_ns > 0:
            return total_ns / 1e9 / n / n_planes, wall
    except Exception:
        pass
    return wall, wall


def main() -> None:
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.gpt2 import (GPT2LMLoss, count_params,
                                           get_config)

    if on_tpu:
        cfg_model = get_config("gpt2-125m", n_positions=1024,
                               dtype=jnp.bfloat16, remat=False,
                               remat_policy="none", scan_layers=True,
                               use_flash_attention=True)
        micro, seq, steps = 8, 1024, 20
    else:  # CPU smoke: tiny shapes so the line still prints
        cfg_model = get_config("gpt2-125m", n_positions=128, n_embd=256,
                               n_layer=4, n_head=4, dtype=jnp.float32,
                               remat=False)
        micro, seq, steps = 2, 128, 3

    topo = dist.initialize_mesh()  # all visible devices on the data axis
    dp = topo.zero_partition_count()
    ds_config = {
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": bool(on_tpu)},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.01}},
        "steps_per_print": 1000000,
    }
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg_model.vocab_size, size=(micro * dp, seq), dtype=np.int32)}

    engine, *_ = deepspeed_tpu.initialize(
        model=GPT2LMLoss(cfg_model), config=ds_config, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))

    n_params = count_params(engine.state.params)

    # stage the batch on device once: steady-state training streams batches
    # ahead of the step, so per-step host->device time is not what we measure
    dbatch = engine.put_batch(batch)

    # warmup (compile)
    loss = engine.train_batch(batch=dbatch)
    float(jax.device_get(loss))

    dev_dt, wall_dt = device_seconds_per_call(
        lambda: engine.train_batch(batch=dbatch), n=steps)
    loss = engine.train_batch(batch=dbatch)

    samples_per_sec = micro * dp / dev_dt
    tokens_per_sec = samples_per_sec * seq
    from deepspeed_tpu.models.gpt2 import flops_per_token
    model_flops = tokens_per_sec * flops_per_token(cfg_model, seq)
    n_chips = len(jax.devices())
    mfu = 100.0 * model_flops / (peak_flops(dev.device_kind) * n_chips)

    result = {
        "metric": "gpt2_125m_bf16_train_mfu",
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 3),
        "detail": {
            "samples_per_sec_per_chip": round(samples_per_sec / n_chips, 2),
            "tokens_per_sec": round(tokens_per_sec),
            "device_step_ms": round(dev_dt * 1e3, 1),
            "wall_step_ms": round(wall_dt * 1e3, 1),
            "wall_tokens_per_sec": round(micro * dp * seq / wall_dt),
            "params": n_params,
            "device": dev.device_kind,
            "n_chips": n_chips,
            "final_loss": float(jax.device_get(loss)),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
