"""Benchmark matrix: the BASELINE.md target configs, one JSON line each.

Default (no args) runs config 1 — the driver's headline number — and
prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline"}``.

The full matrix (``--config N``) mirrors BASELINE.md's target list:

1. GPT-2 125M, ZeRO-0 DDP           — headline train MFU (north star 45%)
2. GPT-2 1.3B, ZeRO-2 + fused Adam  — train MFU, bf16
3. Llama-2-7B-class, ZeRO-3         — train MFU (``--size`` to shrink)
4. Long-context Ulysses SP          — attention-heavy train MFU @ 32k seq
5. Mixtral-class MoE + EP           — train MFU (active-params FLOPs)
6. (``--config infer``) KV-cache decode — tokens/s/chip

Configs 2-5 size to a single v5p chip by default; ``--size`` swaps the
model preset (e.g. ``--size gpt2-350m``) and ``--smoke`` shrinks shapes
for CPU runs.  On multi-chip hosts every config shards over all visible
chips (data axis; config 4 prefers the seq axis, 5 the expert axis).

MFU is computed from *device* step time (jax.profiler XPlane events): this
benchmark may run through a remote-device tunnel whose per-dispatch host
latency (hundreds of ms) is an artifact of the harness, not of the
framework or the chip.  Wall-clock throughput is reported alongside in
``detail`` for transparency.
"""
from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOPs per chip by device kind substring
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v6": 918e12,  # trillium
    "cpu": 1e12,       # nominal, for smoke runs
}

NORTH_STAR_MFU = 45.0


def peak_flops(kind: str) -> float:
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()) or k.lower() in kind.lower():
            return v
    return 197e12


def _device_seconds_from_trace(trace_dir: str):
    """Total jit_* device seconds from a profiler trace, averaged over
    the TPU planes so multi-chip hosts aren't overcounted.  None when no
    device events were captured (CPU smoke runs)."""
    try:
        from jax.profiler import ProfileData

        path = sorted(glob.glob(trace_dir + "/**/*.xplane.pb",
                                recursive=True))[-1]
        pdata = ProfileData.from_file(path)
        total_ns = 0
        n_planes = 0
        for plane in pdata.planes:
            if "TPU" not in plane.name:
                continue
            plane_ns = 0
            for line in plane.lines:
                for ev in line.events:
                    if ev.name.startswith("jit_"):
                        plane_ns += ev.duration_ns
            if plane_ns > 0:
                n_planes += 1
                total_ns += plane_ns
        if total_ns > 0:
            return total_ns / 1e9 / n_planes
    except Exception:
        pass
    return None


def device_seconds_per_call(fn, n: int = 10):
    """(device_seconds, wall_seconds) per fn() call.  Device time comes from
    profiler XPlane events (jit_* entries); wall time brackets only the call
    loop + sync.  Device time falls back to wall when no device events are
    captured (CPU smoke runs)."""
    trace_dir = "/tmp/dstpu_bench_trace"
    shutil.rmtree(trace_dir, ignore_errors=True)
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.device_get(jax.tree_util.tree_map(jnp.sum, out))
    wall = (time.perf_counter() - t0) / n
    jax.profiler.stop_trace()
    dev = _device_seconds_from_trace(trace_dir)
    if dev is not None:
        return dev / n, wall
    return wall, wall


def _measure_train(engine, batch, *, steps, micro_global, seq,
                   flops_per_tok, metric, vs=NORTH_STAR_MFU,
                   extra_detail=None):
    """Shared harness: warm up, time the step, print the JSON line."""
    dev = jax.devices()[0]
    dbatch = engine.put_batch(batch)
    loss = engine.train_batch(batch=dbatch)          # compile
    float(jax.device_get(loss))

    dev_dt, wall_dt = device_seconds_per_call(
        lambda: engine.train_batch(batch=dbatch), n=steps)
    loss = engine.train_batch(batch=dbatch)

    n_chips = len(jax.devices())
    samples_per_sec = micro_global / dev_dt
    tokens_per_sec = samples_per_sec * seq
    mfu = 100.0 * tokens_per_sec * flops_per_tok / (
        peak_flops(dev.device_kind) * n_chips)
    detail = {
        "samples_per_sec_per_chip": round(samples_per_sec / n_chips, 2),
        "tokens_per_sec": round(tokens_per_sec),
        "device_step_ms": round(dev_dt * 1e3, 1),
        "wall_step_ms": round(wall_dt * 1e3, 1),
        "wall_tokens_per_sec": round(micro_global * seq / wall_dt),
        "device": dev.device_kind,
        "n_chips": n_chips,
        "final_loss": float(jax.device_get(loss)),
    }
    detail.update(extra_detail or {})
    print(json.dumps({
        "metric": metric,
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / vs, 3),
        "detail": detail,
    }))


def _tokens(vocab, batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(batch, seq),
                                      dtype=np.int32)}


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------

def bench_gpt2_ddp(args) -> None:
    """Config 1 (headline): GPT-2 125M, ZeRO-0 DDP."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.gpt2 import (GPT2LMLoss, count_params,
                                           flops_per_token, get_config)

    on_tpu = not args.smoke
    size = args.size or "gpt2-125m"
    if on_tpu:
        # unrolled blocks (scan_layers=False) let XLA pipeline across layer
        # boundaries — measured 49.5% vs 39.5% MFU on v5e for the 12-block
        # 125M model.  Validated for small models only: larger --size
        # presets keep the scan default (compile time and program size
        # grow with unrolled depth).
        cfg = get_config(size, n_positions=1024,
                         dtype=jnp.bfloat16, remat=False,
                         remat_policy="none",
                         scan_layers=size not in ("gpt2-125m", "gpt2-350m"),
                         use_flash_attention=True)
        # micro=12 measured best on v5e (52.98% vs 52.34 at micro=8,
        # 50.7 at 16 — the r5 sweep)
        micro, seq, steps = 12, 1024, args.steps
    else:
        cfg = get_config("gpt2-125m", n_positions=128, n_embd=256,
                         n_layer=4, n_head=4, dtype=jnp.float32,
                         remat=False)
        micro, seq, steps = 2, 128, 3

    topo = dist.initialize_mesh()
    dp = topo.zero_partition_count()
    ds = {
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": on_tpu},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.01}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro * dp, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=GPT2LMLoss(cfg), config=ds, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))
    _measure_train(
        engine, batch, steps=steps, micro_global=micro * dp, seq=seq,
        flops_per_tok=flops_per_token(cfg, seq),
        metric="gpt2_125m_bf16_train_mfu",
        extra_detail={"params": count_params(engine.state.params)})


def bench_gpt2_zero2_fused(args) -> None:
    """Config 2: GPT-2 1.3B, ZeRO-2, fused (Pallas) Adam, bf16."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.gpt2 import (GPT2LMLoss, count_params,
                                           flops_per_token, get_config)

    on_tpu = not args.smoke
    # 1.3B needs ~18GB of state (bf16 params + fp32 master + moments):
    # ZeRO-2 shards the optimizer over dp, so >=4 chips fit it; a single
    # chip benches the 760M shape (measured: 1.3B OOMs at 23.3G/15.75G)
    default_size = "gpt2-1.3b" if len(jax.devices()) >= 4 else "gpt2-760m"
    size = args.size or (default_size if on_tpu else "gpt2-125m")
    if on_tpu:
        cfg = get_config(size, n_positions=1024, dtype=jnp.bfloat16,
                         remat=True, remat_policy="dots_saveable",
                         scan_layers=True, use_flash_attention=True)
        # micro=6 measured best for the 760M single-chip shape on v5e
        # (53.2% vs 52.9 at micro=4; micro=8 OOMs its fp32-grads step);
        # other sizes (1.3b multi-chip default) keep the validated 4
        micro = 6 if size == "gpt2-760m" else 4
        seq, steps = 1024, args.steps
    else:
        cfg = get_config(size, n_positions=128, n_embd=256, n_layer=4,
                         n_head=4, dtype=jnp.float32, remat=False)
        micro, seq, steps = 2, 128, 3

    topo = dist.initialize_mesh()
    dp = topo.zero_partition_count()
    ds = {
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": on_tpu},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro * dp, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=GPT2LMLoss(cfg), config=ds, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))
    _measure_train(
        engine, batch, steps=steps, micro_global=micro * dp, seq=seq,
        flops_per_tok=flops_per_token(cfg, seq),
        metric=f"{size.replace('-', '_').replace('.', '_')}"
               "_zero2_fused_adam_train_mfu",
        extra_detail={"params": count_params(engine.state.params),
                      "zero_stage": 2})


def bench_llama_zero3(args) -> None:
    """Config 3: Llama-2-7B-class, ZeRO-3 (sharded params + optimizer)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.llama import (LlamaLMLoss, count_params,
                                            flops_per_token, get_config)

    on_tpu = not args.smoke
    # the 7B target (BASELINE.md config 3) needs >=8 chips for its ~98GB
    # of bf16 params + fp32 master state; a single chip benches the
    # TinyLlama-1.1B shape instead
    default_size = "llama2-7b" if len(jax.devices()) >= 8 else "llama-1b"
    size = args.size or (default_size if on_tpu else "tinyllama")
    if on_tpu:
        # unrolled blocks let XLA pipeline across layer boundaries
        # (measured 55.9% vs 43.2% MFU for the 22-layer 1.1B shape on
        # v5e); gated to the 1B default — the 7B/32-layer preset keeps
        # scan for compile time and program size
        cfg = get_config(size, max_position_embeddings=2048,
                         dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots_saveable",
                         scan_layers=size != "llama-1b",
                         use_flash_attention=True)
        micro, seq, steps = 1, 2048, args.steps
    else:
        cfg = get_config(size, dtype=jnp.float32, remat=False)
        micro, seq, steps = 2, 32, 3

    topo = dist.initialize_mesh()
    dp = topo.zero_partition_count()
    # single chip: ZeRO-3 shards nothing, so the fp32 master+moments of
    # the 1.1B model exceed HBM (measured 17.6G/15.75G) — run the
    # documented pure-bf16 mode there (moments in bf16, no fp32 master);
    # >=8 chips run the reference-style bf16-compute/fp32-state scheme
    pure_bf16 = on_tpu and dp < 8
    ds = {
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": on_tpu, "master_weights": not pure_bf16},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 10000},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro * dp, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=LlamaLMLoss(cfg), config=ds, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))
    _measure_train(
        engine, batch, steps=steps, micro_global=micro * dp, seq=seq,
        flops_per_tok=flops_per_token(cfg, seq),
        metric=f"{size.replace('-', '_')}_zero3_train_mfu",
        extra_detail={"params": count_params(engine.state.params),
                      "zero_stage": 3, "pure_bf16": pure_bf16})


def bench_ulysses_longctx(args) -> None:
    """Config 4: long-context Ulysses SP (all-to-all attention heads)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.llama import (LlamaLMLoss, count_params,
                                            flops_per_token, get_config)

    on_tpu = not args.smoke
    n_dev = len(jax.devices())
    sp = n_dev  # whole mesh on the sequence axis
    if on_tpu:
        # single chip: a ~500M shape + full remat — the 1.1B model's
        # bf16 state + fp32 grads + fp32 CE temporaries exhaust HBM at
        # runtime even with full remat (measured)
        single = n_dev < 8
        size = args.size or ("llama2-7b" if not single else "llama-1b")
        seq = 32768 if not single else 8192
        shrink = dict(hidden_size=1536, intermediate_size=4096,
                      num_hidden_layers=16, num_attention_heads=12,
                      num_key_value_heads=4) \
            if single and args.size is None else {}
        cfg = get_config(size, max_position_embeddings=seq,
                         dtype=jnp.bfloat16, remat=True,
                         remat_policy="full" if single else "dots_saveable",
                         scan_layers=True,
                         use_flash_attention=True,
                         sequence_parallel="ulysses" if sp > 1 else "none",
                         **shrink)
        micro, steps = 1, max(args.steps // 2, 3)
    else:
        size = args.size or "tinyllama"
        seq = 64
        cfg = get_config(size, dtype=jnp.float32, remat=False,
                         max_position_embeddings=seq,
                         sequence_parallel="ulysses" if sp > 1 else "none")
        micro, steps = 1, 3

    topo = dist.initialize_mesh(sp=sp) if sp > 1 else dist.initialize_mesh()
    pure_bf16 = on_tpu and n_dev < 8    # see bench_llama_zero3
    ds = {
        "train_batch_size": micro,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": on_tpu, "master_weights": not pure_bf16},
        "zero_optimization": {"stage": 1 if sp > 1 else 0},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=LlamaLMLoss(cfg), config=ds, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))
    _measure_train(
        engine, batch, steps=steps, micro_global=micro, seq=seq,
        flops_per_tok=flops_per_token(cfg, seq),
        metric=f"ulysses_seq{seq}_train_mfu",
        extra_detail={"params": count_params(engine.state.params),
                      "seq_parallel": sp, "seqlen": seq})


def bench_moe_ep(args) -> None:
    """Config 5: Mixtral-class MoE, expert parallel + ZeRO.  MFU counts
    ACTIVE params only (top-k routing), the MoE convention."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.mixtral import (MixtralLMLoss, count_params,
                                              flops_per_token, get_config)

    on_tpu = not args.smoke
    n_dev = len(jax.devices())
    if on_tpu:
        # sized to the mesh: ~1B total on >=4 chips, ~0.65B on one chip
        # (bf16 state + fp32 grads of the 1B shape exhaust one chip's HBM).
        # Single chip: micro=12 + unrolled blocks measured best (45.3% vs
        # 24.6% at micro=2/scan) — the MoE optimizer+grads touch ALL
        # expert params each step, so small micro-batches leave MFU
        # memory-bound on optimizer traffic, and the sorted dispatch keeps
        # the dispatch cost linear in tokens where the dense einsum is
        # quadratic.  Multi-chip (EP) keeps scan + the GSPMD einsum path.
        single = n_dev < 4
        # single chip: ~0.78B total sized to HBM (bf16 state + fp32
        # grads+moments), 128-dim heads — the r5 shape sweep measured
        # hidden 1024/Dh=128/8 layers at 57.4% vs 47.7% for the old
        # hidden 768/Dh=64/12 layers at identical micro/gas (Dh=64
        # starves the flash kernel's MXU tiles; wider hidden feeds the
        # expert GEMMs better at the same active-param count)
        dims = (dict(hidden_size=1024, intermediate_size=3584,
                     num_attention_heads=16, num_key_value_heads=8)
                if not single else
                dict(hidden_size=1024, intermediate_size=3584,
                     num_attention_heads=8, num_key_value_heads=4))
        n_layers = 12 if not single else 8
        import os as _os

        if _os.environ.get("DSTPU_MOE_DIMS"):
            assert args.size is None, (
                "DSTPU_MOE_DIMS and --size both set: the preset branch "
                "would silently discard the env dims — pick one")
            parts = _os.environ["DSTPU_MOE_DIMS"].split(",")
            assert len(parts) == 5, (
                "DSTPU_MOE_DIMS=hidden,intermediate,heads,kv_heads,layers")
            h, i_, a, kv, n_layers = map(int, parts)
            dims = dict(hidden_size=h, intermediate_size=i_,
                        num_attention_heads=a, num_key_value_heads=kv)
        cfg = get_config("tinymixtral", vocab_size=32000,
                         num_hidden_layers=n_layers,
                         num_local_experts=8, num_experts_per_tok=2,
                         max_position_embeddings=1024,
                         capacity_factor=1.0,   # reference train default
                         dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots_saveable",
                         scan_layers=not single,
                         use_flash_attention=True, **dims) \
            if args.size is None else get_config(
                args.size, dtype=jnp.bfloat16, remat=True,
                scan_layers=True, use_flash_attention=True)
        # the tuned micro=12 was measured against the default dims only;
        # user --size presets keep the conservative micro
        micro = 4 if not single else (12 if args.size is None else 2)
        # single chip: gas=4 amortizes the optimizer's all-expert-params
        # HBM traffic (gas=1 measured ~1% lower; gas=8 adds nothing at
        # the r5 shape — fwd+bwd dominates once Dh=128 feeds the MXU)
        gas = 4 if single and args.size is None else 1
        micro = int(_os.environ.get("DSTPU_MOE_MICRO", micro))
        gas = int(_os.environ.get("DSTPU_MOE_GAS", gas))
        if _os.environ.get("DSTPU_MOE_REMAT"):
            cfg = dataclasses.replace(
                cfg, remat=_os.environ["DSTPU_MOE_REMAT"] != "none",
                remat_policy=_os.environ["DSTPU_MOE_REMAT"])
        seq, steps = 1024, max(args.steps // (2 if gas > 1 else 1), 3)
    else:
        cfg = get_config("tinymixtral", dtype=jnp.float32, remat=False)
        micro, seq, steps = 2, 32, 3
        gas = 1

    ep = min(n_dev, cfg.num_local_experts)
    topo = dist.initialize_mesh(dp=n_dev // ep, ep=ep) if ep > 1 \
        else dist.initialize_mesh()
    dp = topo.zero_partition_count()
    pure_bf16 = on_tpu and n_dev < 4    # see bench_llama_zero3
    ds = {
        "train_batch_size": micro * max(dp, 1) * gas,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": on_tpu, "master_weights": not pure_bf16},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro * max(dp, 1) * gas, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=MixtralLMLoss(cfg), config=ds, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))
    _measure_train(
        engine, batch, steps=steps,
        micro_global=micro * max(dp, 1) * gas,
        seq=seq, flops_per_tok=flops_per_token(cfg, seq),
        metric="mixtral_ep_train_mfu",
        extra_detail={"params": count_params(engine.state.params),
                      "experts": cfg.num_local_experts,
                      "gas": gas,
                      "expert_parallel": ep})


def bench_inference(args) -> None:
    """KV-cache decode throughput (tokens/s/chip), greedy sampling."""
    import os

    import deepspeed_tpu

    on_tpu = not args.smoke
    from deepspeed_tpu.models.gpt2 import get_config

    if on_tpu:
        cfg = get_config(args.size or "gpt2-125m", n_positions=1024,
                         dtype=jnp.bfloat16, scan_layers=True, remat=False,
                         use_flash_attention=True, decode=True)
        # bs=64 measured 20.6k vs 19.3k tok/s at bs=32 on v5e (decode
        # tick cost is nearly flat in batch — concurrency is pure win)
        bsz, prompt, new = 64, 128, 128
    else:
        cfg = get_config("gpt2-125m", n_positions=128, n_embd=256,
                         n_layer=4, n_head=4, dtype=jnp.float32,
                         remat=False, decode=True)
        bsz, prompt, new = 2, 16, 8

    from deepspeed_tpu.models.gpt2 import GPT2Model
    engine = deepspeed_tpu.init_inference(
        model=GPT2Model(cfg), max_batch_size=bsz,
        max_out_tokens=prompt + new, rng=jax.random.PRNGKey(0))
    ids = _tokens(cfg.vocab_size, bsz, prompt)["input_ids"]

    jax.block_until_ready(engine.generate(ids, max_new_tokens=new))  # compile
    # device time via profiler (the tunnel's per-dispatch host latency is
    # a harness artifact, like the train configs); wall reported alongside.
    # The timed loop uses the DEFERRED-HARVEST path (generate_async): call
    # k+1's host work overlaps call k's device work, and the harness's one
    # final sync harvests everything — the serving host-path pipeline's v1
    # treatment.
    engine.host_stats.reset()
    dev_dt, wall_dt = device_seconds_per_call(
        lambda: engine.generate_async(ids, max_new_tokens=new)
        .device_array(), n=3)
    serving_stages = engine.serving_stages()
    n_chips = len(jax.devices())
    tps = bsz * new / dev_dt
    # Two floors, both FIXED (VERDICT Weak #5: a floor re-based to the
    # current round's result makes vs_baseline 1.0 by construction and
    # measures nothing).  The ORIGINAL floor (19305.7, the r4 batch-32
    # result this config first regressed against) is the headline
    # vs_baseline; the r5 batch-64 re-measure (20552.8) is reported
    # alongside as vs_baseline_current for the like-for-like batch-64
    # comparison.  Neither may ever move with the round's own result.
    floor_orig = 19305.7                  # r4, batch 32
    floor_batch64 = 20552.8               # r5, batch 64
    print(json.dumps({
        "metric": "gpt2_125m_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps / floor_orig, 3) if on_tpu else 0.0,
        "vs_baseline_orig": round(tps / floor_orig, 3) if on_tpu else 0.0,
        "vs_baseline_current": (round(tps / floor_batch64, 3)
                                if on_tpu else 0.0),
        "detail": {"batch": bsz, "prompt": prompt, "new_tokens": new,
                   "floor_orig_batch32": floor_orig,
                   "floor_current_batch64": floor_batch64,
                   "tokens_per_sec_per_chip": round(tps / n_chips, 1),
                   "wall_tokens_per_sec": round(bsz * new / wall_dt, 1),
                   "wall_vs_device_ratio": round(wall_dt / dev_dt, 2),
                   "device_call_ms": round(dev_dt * 1e3, 1),
                   "serving_stages": serving_stages,
                   # wall time NOT covered by device work — the async
                   # dispatch path never blocks inside the engine, so
                   # the wall/device gap is the authoritative view here
                   "host_bound_fraction": round(
                       max(0.0, 1.0 - dev_dt / wall_dt), 4),
                   "host_cores": os.cpu_count(),
                   "device": jax.devices()[0].device_kind},
    }))


def _ragged_run(model, params, *, max_seqs, max_len, chunk, prompt_lens,
                new, vocab, decode_block=8, topology=None, **eng_kw):
    """One ragged-serving run; returns (gen_tokens, dispatches, wall,
    dev_s, engine)."""
    from deepspeed_tpu.inference.v2.ragged_engine import (
        RaggedInferenceEngineV2)

    eng = RaggedInferenceEngineV2(model, params, max_seqs=max_seqs,
                                  max_seq_len=max_len, prefill_chunk=chunk,
                                  decode_block_size=decode_block,
                                  topology=topology, **eng_kw)
    rng = np.random.default_rng(0)
    for plen in prompt_lens:
        eng.put_request(rng.integers(0, vocab, int(plen), dtype=np.int32),
                        max_new_tokens=new)
    # warm up: compile the SplitFuse tick AND the decode-block program.
    # Long prompts span many SplitFuse ticks, so step until every live
    # slot is past prefill (the first decode block has dispatched), then
    # one more block — otherwise the decode program compiles inside the
    # timed region
    eng.step()
    while eng.has_work() and any(
            s is not None and s.prefill_done < s.ctx_len
            for s in eng.slots):
        eng.step()
    if eng.has_work():
        eng.step()
    eng.sync()          # fold pipelined in-flight warmup tokens first
    warmup_tokens = (sum(len(s.generated) for s in eng.slots
                         if s is not None) +
                     sum(len(r.generated) for r in eng.finished))
    eng.host_stats.reset()          # stage breakdown covers the timed loop

    # device time via profiler: the host-driven scheduler pays one tunnel
    # round-trip per DISPATCH under this harness (wall is an artifact
    # there; decode blocks amortize it 1/K)
    trace_dir = "/tmp/dstpu_bench_ragged_trace"
    shutil.rmtree(trace_dir, ignore_errors=True)
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    dispatches = 0
    while eng.has_work():
        eng.step()
        dispatches += 1
    wall = time.perf_counter() - t0
    jax.profiler.stop_trace()
    dev_s = _device_seconds_from_trace(trace_dir)
    outs = eng.get_outputs()
    gen_tokens = sum(len(toks) - plen
                     for (_, toks), plen in zip(sorted(outs), prompt_lens))
    gen_tokens -= warmup_tokens           # untimed warmup steps' output
    return gen_tokens, dispatches, wall, dev_s, eng


def _validate_chrome_trace(path):
    """Minimal schema check of a tracer export; returns (ok, n_events).
    The full validator lives in scripts/trace_summarize.py — this keeps
    the bench row honest without importing from scripts/."""
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents")
    ok = isinstance(evs, list) and len(evs) > 0 and all(
        isinstance(ev, dict) and isinstance(ev.get("name"), str)
        and ev.get("ph") in ("X", "i", "M")
        and (ev["ph"] == "M"
             or (isinstance(ev.get("ts"), (int, float))
                 and (ev["ph"] != "X"
                      or isinstance(ev.get("dur"), (int, float)))))
        for ev in evs)
    return ok, len(evs or [])


def bench_ragged(args) -> None:
    """Config ragged: continuous-batching effective throughput — mixed
    prompt lengths share one decode batch (FastGen-style serving, the
    reference's `effective throughput` metric family).  Decode runs in
    on-device multi-tick blocks (K tokens per host dispatch); a second
    run reports quantized serving (fp8 KV pool + int8 weights)."""
    from deepspeed_tpu.models.llama import LlamaModel, get_config

    on_tpu = not args.smoke
    if on_tpu:
        # 128-dim heads: the Pallas ragged paged kernel's supported head
        # dim (H*Dh = hidden, same param count as the 12x64 shape)
        cfg = get_config("llama-1b", hidden_size=768,
                         intermediate_size=2048, num_hidden_layers=12,
                         num_attention_heads=6, num_key_value_heads=2,
                         max_position_embeddings=512,
                         dtype=jnp.bfloat16, scan_layers=False,
                         remat=False, use_flash_attention=False,
                         decode=True)
        # 32 slots matches the static decode loop's batch size (config
        # "infer" bs=32) so the two throughput numbers compare directly;
        # measured 19.4k tok/s vs 9.4k at 8 slots (tick cost is nearly
        # flat in slot count, so concurrency is pure win)
        max_seqs = 32
        max_len, chunk, n_req, new = 512, 256, 2 * max_seqs, 64
    else:
        cfg = get_config("tinyllama", dtype=jnp.float32, remat=False,
                         max_position_embeddings=64, decode=True)
        max_seqs, max_len, chunk, n_req, new = 4, 64, 16, 6, 8

    model = LlamaModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), np.ones((1, 2), np.int32),
        positions=np.zeros((1, 2), np.int32))["params"]
    rng = np.random.default_rng(0)
    prompt_lens = rng.integers(16 if on_tpu else 4,
                               (max_len - new) if on_tpu else 16,
                               size=n_req)
    run_kw = dict(max_seqs=max_seqs, max_len=max_len, chunk=chunk,
                  prompt_lens=prompt_lens, new=new, vocab=cfg.vocab_size)
    decode_block = 8
    import os

    # fresh metrics registry so the request/stage histograms cover
    # exactly the base run (the nearest-rank cross-check below compares
    # against this engine's tracker, not a process-lifetime blur)
    from deepspeed_tpu.telemetry.metrics import metrics as _registry
    _registry.reset()
    _registry.configure(enabled=True)
    gen_tokens, dispatches, wall, dev_s, base_eng = _ragged_run(
        model, {"params": params}, decode_block=decode_block, **run_kw)
    serving_stages = base_eng.serving_stages()
    # histogram-derived latency percentiles (linear interpolation inside
    # the crossing exponential bucket) next to the tracker's exact
    # nearest-rank values; `agrees` flags the one-bucket-width contract
    # serve_smoke --metrics hard-gates
    hist_latency = {}
    for _mname in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
        _fam = _registry.get(f"dstpu_request_{_mname}")
        if _fam is None:
            continue
        _child = _fam.labels(replica="")
        _entry = {"count": _child.merged()[2]}
        for _q in (50, 99):
            _hq = _child.quantile(_q)
            _nr = serving_stages["requests"].get(f"{_mname}_p{_q}")
            _entry[f"p{_q}"] = round(_hq, 3) if _hq is not None else None
            if _hq is not None and _nr is not None:
                _tol = max(_child.bucket_width_at(_nr),
                           _child.bucket_width_at(_hq)) + 1e-9
                _entry[f"p{_q}_agrees_nearest_rank"] = bool(
                    abs(_hq - _nr) <= _tol)
        hist_latency[_mname] = _entry
    n_chips = len(jax.devices())
    best_s = dev_s if dev_s else wall
    detail = {"requests": int(n_req), "max_seqs": max_seqs,
              "new_tokens_per_req": new, "dispatches": dispatches,
              "generated_tokens": int(gen_tokens),
              "tokens_per_dispatch": round(
                  gen_tokens / max(dispatches, 1), 1),
              "decode_block_size": decode_block,
              "device_s": round(dev_s, 2) if dev_s else None,
              "wall_s": round(wall, 2),
              "wall_tokens_per_sec": round(gen_tokens / wall, 1),
              "wall_vs_device_ratio": (round(wall / dev_s, 2)
                                       if dev_s else None),
              "serving_stages": serving_stages,
              # profiler-measured device seconds against wall when
              # available (authoritative); engine-observed fraction
              # (stage timers) otherwise
              "host_bound_fraction": (
                  round(max(0.0, 1.0 - dev_s / wall), 4) if dev_s
                  else serving_stages["host_bound_fraction"]),
              "host_cores": os.cpu_count(),
              "pipeline": {"enabled": base_eng.pipeline,
                           "async_depth": base_eng.async_depth,
                           "harvest_interval": base_eng.harvest_interval},
              "n_chips": n_chips,
              "device": jax.devices()[0].device_kind}

    # pipeline-off control: the unpipelined host path (fresh metadata
    # upload + one blocking harvest per dispatch) on the SAME workload —
    # the measured before/after for the serving host-path pipeline
    off_t, off_d, off_wall, off_dev, off_eng = _ragged_run(
        model, {"params": params}, decode_block=decode_block,
        pipeline=False, **run_kw)
    off_stages = off_eng.serving_stages()
    detail["pipeline_off"] = {
        "wall_tokens_per_sec": round(off_t / off_wall, 1),
        "tokens_per_sec": round(off_t / (off_dev if off_dev else off_wall),
                                1),
        "dispatches": off_d,
        "host_bound_fraction": off_stages["host_bound_fraction"],
        "serving_stages": off_stages}

    # per-request latency percentiles (the tracker is always on; the
    # base run above is the tracer-OFF control) + tracer overhead: the
    # SAME workload re-run with the unified tracer armed, its Chrome
    # trace exported and schema-checked.  The hard <=5% overhead gate
    # lives in scripts/serve_smoke.py --trace (min-of-3); the bench row
    # records the single-run delta alongside it.
    detail["request_latency"] = dict(serving_stages["requests"])
    detail["request_latency"]["histogram"] = hist_latency
    from deepspeed_tpu import telemetry
    # back-to-back off/on pairs (the base run above warms process-wide
    # caches the later runs inherit — comparing against it would
    # measure process order, not the tracer); min-of-3 each side since
    # smoke walls are a few ms and a single run is noise-dominated
    ctrl_wall = min(_ragged_run(
        model, {"params": params}, decode_block=decode_block,
        **run_kw)[2] for _ in range(5))
    telemetry.configure(enabled=True)
    tr_wall = float("inf")
    for _ in range(5):
        telemetry.trace.clear()
        w = _ragged_run(model, {"params": params},
                        decode_block=decode_block, **run_kw)[2]
        tr_wall = min(tr_wall, w)
    serve_trace_path = "/tmp/dstpu_bench_ragged_serve_trace.json"
    telemetry.trace.export(serve_trace_path)
    telemetry.configure(enabled=False)
    trace_ok, trace_events = _validate_chrome_trace(serve_trace_path)
    detail["tracer"] = {
        "overhead_pct": round((tr_wall - ctrl_wall) / ctrl_wall * 100, 2),
        "wall_s_tracer_on": round(tr_wall, 3),
        "wall_s_tracer_off": round(ctrl_wall, 3),
        "events": trace_events,
        "chrome_trace_valid": trace_ok,
        "export": serve_trace_path}

    # tiered paged-KV store: resident-session capacity beyond HBM.  A
    # pool sized for ~2 resident sessions serves 8 concurrently — the
    # spill tiers park cold sessions (digest-verified page payloads)
    # instead of destroying them, so restore is a page upload rather
    # than a re-prefill.  The tiering-off control runs the SAME
    # oversubscribed workload with destructive eviction; per-step wall
    # latencies give the p50/p99 decode-block cost both ways.
    from deepspeed_tpu.inference.v2.ragged_engine import (
        RaggedInferenceEngineV2)

    t_sessions, t_new, t_page, t_pool = 8, 24, 16, 7
    t_rng = np.random.default_rng(5)
    t_prompts = [t_rng.integers(0, cfg.vocab_size, 12, dtype=np.int32)
                 for _ in range(t_sessions)]
    t_maxlen = min(64, cfg.max_position_embeddings)

    def _tier_serve(tiering):
        eng = RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=4, max_seq_len=t_maxlen,
            prefill_chunk=16, decode_block_size=4, page_size=t_page,
            num_pages=t_pool, kv_tiering=tiering)
        eng.generate_all(list(t_prompts), max_new_tokens=t_new)  # warmup
        for p in t_prompts:
            eng.put_request(p, max_new_tokens=t_new)
        lats = []
        while eng.has_work():
            t0 = time.perf_counter()
            eng.step()
            lats.append(time.perf_counter() - t0)
            eng.get_outputs()
        return np.asarray(lats), eng

    off_lat, t_off = _tier_serve(None)
    on_lat, t_on = _tier_serve({"host_pages": 64})
    from deepspeed_tpu.inference.paged import pages_for as _pages_for
    hbm_resident = max(1, (t_pool - 1) //
                       _pages_for(12 + t_new, t_page))
    tstats = t_on.tiering.stats()
    restore_ms = round(
        t_on.host_stats.seconds["restore"] * 1e3 /
        max(t_on.restores, 1), 3)
    detail["kv_tiering"] = {
        "sessions": t_sessions,
        "hbm_only_resident_sessions": hbm_resident,
        "resident_sessions": t_sessions - t_on.evictions,
        "resident_capacity_ratio": round(
            (t_sessions - t_on.evictions) / hbm_resident, 2),
        "spills": t_on.spills, "restores": t_on.restores,
        "evictions_tiering_off": t_off.evictions,
        "restore_stall_ms": restore_ms,
        "pages_verified": tstats["pages_verified"],
        "pages_restored": tstats["pages_restored"],
        "step_ms_p50": round(float(np.percentile(on_lat, 50)) * 1e3, 3),
        "step_ms_p99": round(float(np.percentile(on_lat, 99)) * 1e3, 3),
        "tiering_off_step_ms_p50": round(
            float(np.percentile(off_lat, 50)) * 1e3, 3),
        "tiering_off_step_ms_p99": round(
            float(np.percentile(off_lat, 99)) * 1e3, 3),
        "p99_vs_tiering_off": round(
            float(np.percentile(on_lat, 99)) /
            max(float(np.percentile(off_lat, 99)), 1e-9), 3),
        "stage_breakdown": {
            k: v for k, v in tstats.items() if k.endswith("_s")},
    }
    t_on.close()
    t_off.close()

    # million-token context (partial residency): the tiered KV store as
    # virtual memory for attention — the first sink_pages + most recent
    # window_pages stay HBM-resident while the parked middle streams
    # back through the chunked attention scan.  One sequence decodes on
    # a FIXED tiny HBM pool at growing context lengths; the row records
    # tokens/s vs context, the page-in (restore) stall p99 from the
    # dstpu_kv_pagein_stall_ms histogram, and the residency ratio
    # (HBM-resident pages / total KV pages) at each length.
    from deepspeed_tpu.models.llama import LlamaForCausalLM as _CausalLM

    lc_cfg = get_config(
        "tinyllama", vocab_size=64, hidden_size=32,
        intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=1024, dtype=jnp.float32,
        param_dtype=jnp.float32, scan_layers=False, remat=False,
        use_flash_attention=False)
    lc_params = jax.jit(_CausalLM(lc_cfg).init)(
        jax.random.PRNGKey(2), np.zeros((1, 8), np.int32))
    lc_tier = {"host_pages": 512, "long_context": True,
               "sink_pages": 1, "window_pages": 2, "chunk_pages": 2}
    lc_pool, lc_page, lc_new = 8, 16, 32
    lc_resident = (lc_tier["sink_pages"] + lc_tier["window_pages"]
                   + lc_tier["chunk_pages"] + 1)
    lc_rng = np.random.default_rng(7)
    lc_ctxs = (128, 256, 512)

    def _lc_serve(ctx, warm=False):
        prompt = lc_rng.integers(1, 64, size=(ctx - lc_new,),
                                 dtype=np.int32)
        eng = RaggedInferenceEngineV2(
            _CausalLM(lc_cfg), params=lc_params, max_seqs=2,
            max_seq_len=1024, prefill_chunk=16, page_size=lc_page,
            num_pages=lc_pool, decode_block_size=4,
            kv_reserve="on_demand", kv_tiering=dict(lc_tier))
        t0 = time.perf_counter()
        outs = eng.generate_all([prompt], max_new_tokens=lc_new)
        lc_wall = time.perf_counter() - t0
        assert all(len(t) == ctx for t in outs.values())
        st = eng.serving_stages()["kv_tiering"]
        eng.close()
        total_pages = _pages_for(ctx, lc_page)
        return {
            "tokens_per_sec": round(lc_new / max(lc_wall, 1e-9), 1),
            "wall_s": round(lc_wall, 3),
            "pageins": st["pageins"],
            "pagein_pages": st["pagein_pages"],
            "residency_ratio": round(
                min(lc_resident, total_pages) / total_pages, 3),
        }

    _lc_serve(lc_ctxs[0])           # warmup: compiles the scan programs
    lc_by_ctx = {str(c): _lc_serve(c) for c in lc_ctxs}
    _lc_hist = _registry.get("dstpu_kv_pagein_stall_ms")
    _lc_p99 = _lc_hist.quantile(99) if _lc_hist is not None else None
    detail["long_context"] = {
        "hbm_pool_pages": lc_pool,
        "page_size": lc_page,
        "hbm_resident_pages": lc_resident,
        "knobs": {k: lc_tier[k] for k in
                  ("sink_pages", "window_pages", "chunk_pages")},
        "by_context": lc_by_ctx,
        "restore_stall_ms_p50": (
            round(_lc_hist.quantile(50), 3) if _lc_hist else None),
        "restore_stall_ms_p99": (
            round(_lc_p99, 3) if _lc_p99 is not None else None),
        "max_over_hbm_ratio": round(
            _pages_for(lc_ctxs[-1], lc_page) / (lc_pool - 1), 2),
    }

    # cross-request prefix cache: sessions share a common system
    # prompt; the index attaches fully-matched resident KV pages
    # read-only (copy-on-write on divergence) so each admission
    # prefills only its private suffix.  Reuse ratio = shared fraction
    # of the prompt; matches are page-granular, so cached tokens are
    # the page-aligned floor of the shared span.  The cache-off
    # control re-runs the highest-reuse workload with the index
    # disabled — same engine shape, same prompts, full prefill.
    from deepspeed_tpu.telemetry.requests import RequestLatencyTracker

    p_sessions, p_page = 8, 16
    p_total, p_new = (256, 32) if on_tpu else (56, 8)
    p_pool = (4 * _pages_for(p_total + p_new, p_page)
              + p_total // p_page + 2)

    def _pfx_serve(reuse, prefix):
        prng = np.random.default_rng(11)
        n_shared = int(p_total * reuse)
        sys_prompt = prng.integers(0, cfg.vocab_size, n_shared,
                                   dtype=np.int32)
        prompts = [np.concatenate([sys_prompt, prng.integers(
            0, cfg.vocab_size, p_total - n_shared, dtype=np.int32)])
            for _ in range(p_sessions)]
        eng = RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=4,
            max_seq_len=p_total + p_new, prefill_chunk=16,
            decode_block_size=4, page_size=p_page, num_pages=p_pool,
            prefix_cache=prefix)
        # warmup compiles both program shapes and (cache on) registers
        # the shared prefix — the timed pass sees steady-state serving
        eng.generate_all(list(prompts), max_new_tokens=p_new)
        pc0 = dict(eng.serving_stages().get("prefix_cache") or {})
        eng.request_latency = RequestLatencyTracker()
        for p in prompts:
            eng.put_request(p, max_new_tokens=p_new)
        while eng.has_work():
            eng.step()
            eng.get_outputs()
        rl = eng.request_latency.summary()
        row = {"ttft_ms_p50": rl["ttft_ms_p50"],
               "ttft_ms_p99": rl["ttft_ms_p99"],
               "prefill_computed_tokens": rl["prefill_computed_tokens"],
               "prefill_cached_tokens": rl["prefill_cached_tokens"]}
        pc1 = eng.serving_stages().get("prefix_cache")
        if pc1:
            row.update(
                hit_rate=pc1["hit_rate"],
                hit_requests=(pc1["hit_requests"]
                              - int(pc0.get("hit_requests", 0))),
                cow_copies=(pc1["cow_copies"]
                            - int(pc0.get("cow_copies", 0))))
        eng.close()
        return row

    pfx = {"sessions": p_sessions, "prompt_tokens": p_total,
           "page_size": p_page,
           "reuse": {str(r): _pfx_serve(r, True)
                     for r in (0.0, 0.5, 0.9)},
           "cache_off_control": _pfx_serve(0.9, False)}
    pfx["ttft_p50_speedup_at_0.9"] = round(
        pfx["cache_off_control"]["ttft_ms_p50"] /
        max(pfx["reuse"]["0.9"]["ttft_ms_p50"], 1e-9), 2)
    detail["prefix_cache"] = pfx

    # speculative decoding: ngram (prompt-lookup, no second model), a
    # small random draft model (machinery cost at worst-case ~0
    # acceptance — random weights give the drafter nothing to learn
    # from), and self-draft (draft == target: the draft-quality upper
    # bound, isolating the verify/rollback machinery's ceiling).  The
    # spec-off control is the base run above.  `tokens_per_target_pass`
    # (= 1 + mean accepted length) is the per-weight-read amortization
    # speculation exists to raise.
    base_wall_tps = gen_tokens / wall
    import dataclasses as _dc
    draft_cfg = _dc.replace(
        cfg, num_hidden_layers=max(1, cfg.num_hidden_layers // 4),
        scan_layers=False)
    draft_params = jax.jit(LlamaModel(draft_cfg).init)(
        jax.random.PRNGKey(1), np.ones((1, 2), np.int32),
        positions=np.zeros((1, 2), np.int32))
    spec_runs = {
        "ngram": dict(speculation="ngram"),
        "draft": dict(speculation="draft",
                      draft_model=LlamaModel(draft_cfg),
                      draft_params=draft_params),
        "self_draft": dict(speculation="draft",
                           draft_model=LlamaModel(cfg),
                           draft_params={"params": params}),
    }
    detail["speculation"] = {
        "off_control": {"wall_tokens_per_sec": round(base_wall_tps, 1),
                        "tokens_per_dispatch": round(
                            gen_tokens / max(dispatches, 1), 1)}}
    from deepspeed_tpu.telemetry import profiler as _prof
    for sname, skw in spec_runs.items():
        st_, sd_, swall, sdev, seng = _ragged_run(
            model, {"params": params}, decode_block=decode_block,
            **run_kw, **skw)
        ss = seng.serving_stages()
        brk = dict(ss.get("speculation") or {})
        if brk:
            brk["tokens_per_target_pass"] = round(
                1.0 + brk["mean_accepted_len"], 3)
        # host-vs-device attribution (PR 6's recorded blind spot): the
        # jit closures are named, so the XPlane trace _ragged_run just
        # wrote splits device seconds per program — where does the
        # draft/verify tick actually spend its accelerator time?
        progs = _prof.device_seconds_by_program(
            "/tmp/dstpu_bench_ragged_trace")
        split = {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in _prof.split_host_device(
                     swall, sdev if sdev else None).items()}
        split.update({
            "draft_device_s": round(_prof.device_seconds_matching(
                progs, "draft"), 4),
            "verify_device_s": round(_prof.device_seconds_matching(
                progs, "spec_verify"), 4),
            "decode_device_s": round(_prof.device_seconds_matching(
                progs, "ragged_decode_block"), 4),
            "prefill_device_s": round(_prof.device_seconds_matching(
                progs, "ragged_fused_step"), 4)})
        detail["speculation"][sname] = {
            "wall_tokens_per_sec": round(st_ / swall, 1),
            "tokens_per_sec": round(st_ / (sdev if sdev else swall), 1),
            "speedup_vs_off_wall": round((st_ / swall) /
                                         max(base_wall_tps, 1e-9), 3),
            "dispatches": sd_,
            "host_device_split": split,
            "breakdown": brk}

    # decode-block sweep: on-device sampling makes larger K nearly free
    # in device time and divides the host-dispatch count by K
    best_tps = gen_tokens / best_s
    if on_tpu:
        sweep = {}
        for K in (16, 32):
            kt, kd, kwall, kdev, _ = _ragged_run(
                model, {"params": params}, decode_block=K, **run_kw)
            ks = kdev if kdev else kwall
            sweep[K] = {"tokens_per_sec": round(kt / ks, 1),
                        "tokens_per_dispatch": round(kt / max(kd, 1), 1),
                        "wall_tokens_per_sec": round(kt / kwall, 1)}
            if kt / ks > best_tps:
                best_tps = kt / ks
                detail.update(
                    decode_block_size=K, dispatches=kd,
                    generated_tokens=int(kt),
                    tokens_per_dispatch=round(kt / max(kd, 1), 1),
                    device_s=round(kdev, 2) if kdev else None,
                    wall_s=round(kwall, 2),
                    wall_tokens_per_sec=round(kt / kwall, 1))
        detail["decode_block_sweep"] = sweep

    # quantized serving: fp8 KV pool + int8 weights (the memory-bound
    # decode regime where both matter)
    qt, _, qwall, qdev, qeng = _ragged_run(
        model, {"params": params}, kv_cache_dtype="fp8",
        quantize_weights="w8a8", **run_kw)
    detail["kv_fp8_w8a8_tokens_per_sec"] = round(
        qt / (qdev if qdev else qwall), 1)
    detail["kv_fp8_cache_bytes_ratio"] = round(
        qeng.cache_bytes() / max(base_eng.cache_bytes(), 1), 3)

    # quantized KV as a pool format (the kv_quant tentpole): capacity at
    # a FIXED HBM byte budget, spill traffic vs the full-width control,
    # and quality measured (not assumed) — per-tick logit error and
    # greedy divergence under teacher forcing, so the numbers isolate
    # KV quantization from trajectory divergence.
    kq_page = 16
    kq_pps = _pages_for(12 + t_new, kq_page)    # pages per session
    kq_budget = RaggedInferenceEngineV2(
        model, {"params": params}, max_seqs=4, max_seq_len=t_maxlen,
        prefill_chunk=16, decode_block_size=4, page_size=kq_page,
        num_pages=1 + 2 * kq_pps).cache_bytes()  # fp pool, ~2 sessions

    def _kq_capacity(fmt):
        """Serve the 8-session workload on a pool sized by the SAME
        byte budget; resident capacity and evictions tell the story."""
        eng = RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=4, max_seq_len=t_maxlen,
            prefill_chunk=16, decode_block_size=4, page_size=kq_page,
            kv_pool_bytes=kq_budget, kv_cache_dtype=fmt)
        eng.generate_all(list(t_prompts), max_new_tokens=t_new)
        return {"num_pages": eng.num_pages,
                "resident_sessions": max(1, (eng.num_pages - 1) //
                                         kq_pps),
                "evictions": eng.evictions,
                "pool_bytes": eng.cache_bytes()}

    def _kq_spill(fmt):
        """Tiering-on run: every spilled page carries the pool's
        storage format, so bytes_spilled measures the NVMe/host traffic
        the format saves."""
        eng = RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=4, max_seq_len=t_maxlen,
            prefill_chunk=16, decode_block_size=4, page_size=kq_page,
            num_pages=1 + 2 * kq_pps, kv_cache_dtype=fmt,
            kv_tiering={"host_pages": 64})
        eng.generate_all(list(t_prompts), max_new_tokens=t_new)
        st = eng.tiering.stats()
        out = {"spills": eng.spills,
               "bytes_spilled": st["bytes_spilled"],
               "pages_verified": st["pages_verified"]}
        eng.close()
        return out

    def _kq_quality(fmt, n_seqs=6, gen=40, prompt_len=8):
        """Teacher-forced lockstep decode: the quantized pool replays
        the fp pool's greedy token stream tick for tick, comparing
        logits at every position."""
        from deepspeed_tpu.inference.common import unroll_scan_params
        qrng = np.random.default_rng(17)
        pp_q = t_maxlen // kq_page
        kq_unroll = bool(getattr(cfg, "scan_layers", False))

        def _mk(pool_fmt):
            pcfg = _dc.replace(
                cfg, decode=True, ragged_decode=False, paged_decode=True,
                max_cache_len=t_maxlen, scan_layers=False,
                kv_page_size=kq_page, kv_num_pages=pp_q + 1,
                tensor_parallel=False, kv_cache_dtype=pool_fmt)
            pmodel = type(model)(pcfg)

            @jax.jit
            def tick(cache, tok, pos):
                # one sequence on contiguous pages 1..pp: flat KV row
                # for position p is page_size + p
                meta = {"kv_lens": (pos + 1)[None].astype(jnp.int32),
                        "page_indices": jnp.arange(
                            1, pp_q + 1, dtype=jnp.int32)[None],
                        "cu_q_lens": jnp.asarray([0, 1], jnp.int32),
                        "num_seqs": jnp.asarray([1], jnp.int32),
                        "new_kv_dest": (kq_page + pos)[None].astype(
                            jnp.int32)}
                p = (unroll_scan_params(params) if kq_unroll
                     else params)
                out, mut = pmodel.apply(
                    {"params": p, "cache": cache}, tok[None, None],
                    positions=pos[None, None], ragged_meta=meta,
                    mutable=["cache"])
                logits = out[0] if isinstance(out, tuple) else out
                return logits[0, 0], mut["cache"]

            meta0 = {"kv_lens": np.zeros((1,), np.int32),
                     "page_indices": np.full((1, pp_q), -1, np.int32),
                     "cu_q_lens": np.zeros((2,), np.int32),
                     "num_seqs": np.zeros((1,), np.int32),
                     "new_kv_dest": np.zeros((1,), np.int32)}
            shapes = jax.eval_shape(lambda: pmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                positions=jnp.zeros((1, 1), jnp.int32),
                ragged_meta=meta0))
            zero = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
            return tick, zero

        f_tick, f_zero = _mk("none")
        q_tick, q_zero = _mk(fmt)
        max_err, errs, diverged, compared = 0.0, [], 0, 0
        for _ in range(n_seqs):
            prompt = qrng.integers(0, cfg.vocab_size, prompt_len,
                                   dtype=np.int32)
            f_cache, q_cache = f_zero, q_zero
            tok = None
            for p in range(prompt_len + gen - 1):
                t_in = (jnp.asarray(prompt[p], jnp.int32)
                        if p < prompt_len else tok)
                pos = jnp.asarray(p, jnp.int32)
                fl, f_cache = f_tick(f_cache, t_in, pos)
                ql, q_cache = q_tick(q_cache, t_in, pos)
                err = float(jnp.max(jnp.abs(fl - ql)))
                errs.append(err)
                max_err = max(max_err, err)
                if p >= prompt_len - 1:
                    compared += 1
                    diverged += int(int(jnp.argmax(fl)) !=
                                    int(jnp.argmax(ql)))
                    tok = jnp.argmax(fl).astype(jnp.int32)
        return {"logit_max_abs_err": round(max_err, 5),
                "logit_mean_abs_err": round(
                    float(np.mean(errs)), 5),
                "greedy_tokens_compared": compared,
                "greedy_divergence_rate": round(
                    diverged / max(compared, 1), 4)}

    full_cap = _kq_capacity("none")
    full_spill = _kq_spill("none")
    kq = {"hbm_byte_budget": kq_budget, "page_size": kq_page,
          "sessions": t_sessions, "full_width": {
              **full_cap, "spill": full_spill}}
    for fmt in ("int8", "fp8"):
        cap = _kq_capacity(fmt)
        spill = _kq_spill(fmt)
        kq[fmt] = {
            **cap, "spill": spill,
            "resident_sessions_vs_full_width": round(
                cap["resident_sessions"] /
                max(full_cap["resident_sessions"], 1), 2),
            "spill_bytes_vs_full_width": round(
                spill["bytes_spilled"] /
                max(full_spill["bytes_spilled"], 1), 3),
            "quality": _kq_quality(fmt)}
    from deepspeed_tpu.inference.paged import kv_dequant_path
    kq["dequant_path"] = kv_dequant_path(
        cfg.hidden_size // cfg.num_attention_heads)
    detail["kv_quant"] = kq

    if on_tpu:
        # weight-BOUND quantized serving: this config's 0.38 GB model is
        # per-tick-overhead-bound (quantization cannot speed it up — the
        # w8a8 win above is vs the old dequant path), so demonstrate the
        # native-int8-dot capability where decode is actually limited by
        # weight bandwidth: a 1B-class model, same slot count.  FastGen's
        # quantized-serving claims are made in this regime.
        cfg1b = get_config("llama-1b", hidden_size=2048,
                           intermediate_size=5632, num_hidden_layers=22,
                           num_attention_heads=16, num_key_value_heads=4,
                           max_position_embeddings=512,
                           dtype=jnp.bfloat16, scan_layers=False,
                           remat=False, use_flash_attention=False,
                           decode=True)
        model1b = LlamaModel(cfg1b)
        params1b = jax.jit(model1b.init)(
            jax.random.PRNGKey(0), np.ones((1, 2), np.int32),
            positions=np.zeros((1, 2), np.int32))["params"]
        kw1b = dict(run_kw, prompt_lens=prompt_lens[:max_seqs],
                    new=32)
        bt, _, bwall, bdev, _ = _ragged_run(
            model1b, {"params": params1b}, decode_block=16, **kw1b)
        qt1, _, qwall1, qdev1, _ = _ragged_run(
            model1b, {"params": params1b}, decode_block=16,
            quantize_weights="w8a8", **kw1b)
        b_tps = bt / (bdev if bdev else bwall)
        q_tps = qt1 / (qdev1 if qdev1 else qwall1)
        detail["weight_bound_1b"] = {
            "bf16_tokens_per_sec": round(b_tps, 1),
            "w8a8_tokens_per_sec": round(q_tps, 1),
            "speedup": round(q_tps / max(b_tps, 1e-9), 2)}

    # tp=1 vs tp=2 serving (multi-device CPU mesh: the VERDICT-requested
    # comparison; single-chip TPU hosts have no second chip)
    if len(jax.devices()) >= 2 and not on_tpu:
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.comm import comm as _comm

        _comm._state.topology = None
        topo2 = dist.initialize_mesh(dp=1, tp=2,
                                     devices=jax.devices()[:2])
        t2, _, w2, dv2, _ = _ragged_run(model, {"params": params},
                                        topology=topo2, **run_kw)
        detail["tp2_tokens_per_sec"] = round(t2 / (dv2 if dv2 else w2), 1)
        detail["tp1_tokens_per_sec"] = round(gen_tokens / best_s, 1)

    # -- scale-out serving: replicated engines behind the SLO router ----
    # Open-loop request streams against a 2-replica ReplicaSet vs the
    # single-replica control, then a 2x-overload Poisson leg with
    # admission control on (queue caps + burn-rate shedding) and the
    # tracer enabled so residual wall attributes to the named router
    # spans.  On a 1-core host nothing overlaps — the row records that
    # caveat and asserts request conservation + bit-identical greedy
    # outputs instead of a throughput floor.
    from deepspeed_tpu import telemetry as _telemetry
    from deepspeed_tpu.serving import (ReplicaSet, Router,
                                       RouterRejection)
    from deepspeed_tpu.telemetry import SLOSet
    from deepspeed_tpu.telemetry.requests import percentile as _pctl

    so_n = 2 * max_seqs if on_tpu else 10
    so_rng = np.random.default_rng(11)
    so_prompts = [so_rng.integers(0, cfg.vocab_size, int(l),
                                  dtype=np.int32)
                  for l in so_rng.integers(4, max(chunk - 1, 5),
                                           size=so_n)]

    def so_engine(i=0):
        from deepspeed_tpu.inference.v2.ragged_engine import (
            RaggedInferenceEngineV2)
        return RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=max_seqs,
            max_seq_len=max_len, prefill_chunk=chunk,
            decode_block_size=decode_block)

    def so_run(n_rep, arrivals=None, slo=None, queue_cap=None,
               burn_shed=2.0, burn_defer=1.0):
        """One routed open-loop run.  ``arrivals`` (seconds from start)
        schedules submissions without waiting on responses; None means
        everything arrives at t0 (closed-burst)."""
        rs = ReplicaSet(so_engine, n_rep)
        router = Router(rs, policy="least_tokens", slo=slo,
                        queue_cap=queue_cap, burn_shed=burn_shed,
                        burn_defer=burn_defer)
        outs, rid2i, sub_t, e2e_ms, shed = {}, {}, {}, [], 0
        t0 = time.perf_counter()
        i = 0
        while i < len(so_prompts) or router.outstanding:
            now = time.perf_counter() - t0
            progressed = False
            while i < len(so_prompts) and (arrivals is None or
                                           arrivals[i] <= now):
                try:
                    rid = router.submit(so_prompts[i],
                                        max_new_tokens=new)
                    rid2i[rid] = i
                    sub_t[rid] = time.perf_counter()
                except RouterRejection:
                    shed += 1
                i += 1
                progressed = True
            if router.outstanding:
                router.pump()
                router.join()
                progressed = True
            for rid, toks in router.get_outputs().items():
                e2e_ms.append(
                    (time.perf_counter() - sub_t[rid]) * 1e3)
                outs[rid] = toks
            if not progressed:
                time.sleep(0.0005)     # idle until the next arrival
        wall = time.perf_counter() - t0
        stats = router.stats()
        rs.close()
        return outs, rid2i, sorted(e2e_ms), wall, shed, stats

    # capacity legs accept the whole burst (cap >= the workload);
    # admission only gates the overload leg below
    ctrl_outs, ctrl_map, ctrl_e2e, ctrl_so_wall, _, _ = so_run(
        1, queue_cap=so_n)
    cap_rps = len(ctrl_outs) / ctrl_so_wall
    so_outs, so_map, so_e2e, so_wall, _, so_stats = so_run(
        2, queue_cap=so_n)
    rps2 = len(so_outs) / so_wall

    # request conservation + greedy bit-parity vs the single-replica
    # control (greedy outputs are a pure function of prompt + params,
    # so routing must not change a single token)
    so_ref = {ctrl_map[rid]: toks for rid, toks in ctrl_outs.items()}
    assert sorted(so_map[r] for r in so_outs) == sorted(so_ref), (
        "scale-out run lost requests: "
        f"{len(so_outs)}/{len(so_ref)} finished")
    assert all(np.array_equal(so_outs[rid], so_ref[so_map[rid]])
               for rid in so_outs), (
        "routed greedy outputs diverged from single-replica serving")

    # 2x-overload Poisson leg: arrivals at twice the measured capacity,
    # tight queue caps + burn-rate shedding, tracer on so the wall not
    # covered by engine stages lands in the router_pump span
    so_arrivals = np.cumsum(so_rng.exponential(
        1.0 / (2.0 * cap_rps), size=so_n))
    slo_thr = max(3.0 * (_pctl(so_e2e, 99) or 0.0), 50.0)
    so_slo = SLOSet([f"router_e2e_ms_p99 <= {slo_thr:.1f}"])
    _telemetry.trace.configure(enabled=True)
    _telemetry.trace.clear()
    (ov_outs, ov_map, ov_e2e, ov_wall, ov_shed,
     ov_stats) = so_run(2, arrivals=so_arrivals, slo=so_slo,
                        queue_cap=max_seqs, burn_shed=1.0,
                        burn_defer=float("inf"))
    router_span_s = sum(
        ev.get("dur", 0.0) for ev in _telemetry.trace.snapshot()
        if ev.get("ph") == "X" and ev.get("name") in
        ("router_pump", "router_dispatch")) / 1e6
    _telemetry.trace.configure(enabled=False)
    _telemetry.trace.clear()
    goodput_rps = len(ov_outs) / ov_wall

    multi_device = len(jax.devices()) >= 2
    if multi_device:
        # real overlap available: the replication floor and the
        # admission guarantees are load-bearing
        assert rps2 / max(cap_rps, 1e-9) >= 1.8, (
            f"2-replica requests/s {rps2:.2f} < 1.8x single-replica "
            f"control {cap_rps:.2f}")
        assert (_pctl(ov_e2e, 99) or 0.0) <= slo_thr, (
            "overload leg: accepted-request p99 "
            f"{_pctl(ov_e2e, 99):.1f}ms blew the {slo_thr:.1f}ms SLO "
            "despite admission control")
        assert goodput_rps >= 0.8 * cap_rps, (
            f"overload goodput {goodput_rps:.2f} req/s < 0.8x capacity "
            f"{cap_rps:.2f}")
    detail["scale_out"] = {
        "replicas": 2,
        "policy": "least_tokens",
        "requests": so_n,
        "single_replica_rps": round(cap_rps, 3),
        "two_replica_rps": round(rps2, 3),
        "speedup": round(rps2 / max(cap_rps, 1e-9), 3),
        "e2e_ms_p50": round(_pctl(so_e2e, 50) or 0.0, 1),
        "e2e_ms_p99": round(_pctl(so_e2e, 99) or 0.0, 1),
        "bit_identical_to_single_engine": True,   # asserted above
        "overload": {
            "arrival_rps": round(2.0 * cap_rps, 3),
            "accepted": len(ov_map),
            "shed": ov_shed,
            "finished": len(ov_outs),
            "goodput_rps": round(goodput_rps, 3),
            "goodput_vs_capacity": round(
                goodput_rps / max(cap_rps, 1e-9), 3),
            "accepted_e2e_ms_p99": round(_pctl(ov_e2e, 99) or 0.0, 1),
            "slo_threshold_ms": round(slo_thr, 1),
            "router_span_s": round(router_span_s, 4),
            "rejected_queue_full": ov_stats["rejected_queue_full"],
            "rejected_shed": ov_stats["rejected_shed"],
        },
    }
    if not multi_device:
        # this container exposes ONE host device: replica threads
        # interleave on it, so requests/s cannot scale — the row
        # records the measured numbers with the caveat, and the
        # conservation + bit-parity asserts above carry the gate
        detail["scale_out"]["caveat"] = (
            "single-device host: replica threads share one device, "
            "nothing overlaps; speedup is not meaningful here "
            "(conservation + greedy bit-parity asserted instead)")

    # -- disaggregated serving: prefill split from decode ---------------
    # Bimodal mix (1-in-4 long prefills among short chats) against a
    # 1-prefill + 1-decode role split vs one fused replica.  The split
    # keeps long prefills off the decode replica's step loop, so decode
    # TPOT stops inheriting prefill-induced stalls; finished KV crosses
    # replicas in spill format and every restored page is digest-
    # verified on the receiver.  Conservation, greedy bit-parity and
    # the digest accounting are hard gates on every host; the both-
    # beat-fused tail floors only bind where replicas own real devices.
    dgb_n = so_n
    dgb_rng = np.random.default_rng(13)
    dgb_long_hi = max(chunk + 1, min(2 * chunk, max_len - new - 1))
    dgb_lens = [int(dgb_rng.integers(chunk, dgb_long_hi + 1))
                if i % 4 == 0
                else int(dgb_rng.integers(4, max(chunk - 1, 5)))
                for i in range(dgb_n)]
    dgb_prompts = [dgb_rng.integers(0, cfg.vocab_size, l,
                                    dtype=np.int32) for l in dgb_lens]
    dgb_long = sum(1 for l in dgb_lens if l >= chunk)

    def dgb_engine(i=0):
        # page_size pinned to one prefill chunk: the router's long-
        # prefill threshold (handoff_min_prompt) seeds from the
        # replica page size, and the bimodal mix above straddles chunk
        from deepspeed_tpu.inference.v2.ragged_engine import (
            RaggedInferenceEngineV2)
        return RaggedInferenceEngineV2(
            model, {"params": params}, max_seqs=max_seqs,
            max_seq_len=max_len, prefill_chunk=chunk,
            page_size=chunk,
            num_pages=max_seqs * (max_len // chunk + 1) + 4,
            decode_block_size=decode_block,
            kv_tiering={"host_pages": 16 * max_seqs})

    def dgb_run(n_rep, roles=None):
        rs = ReplicaSet(dgb_engine, n_rep)
        router = Router(rs, policy="least_tokens", queue_cap=dgb_n)
        if roles:
            router.set_roles(roles)
        t0 = time.perf_counter()
        rid2i = {router.submit(p, max_new_tokens=new): i
                 for i, p in enumerate(dgb_prompts)}
        outs = router.drain()
        wall = time.perf_counter() - t0
        res = {
            "outs": {rid2i[r]: t for r, t in outs.items()},
            "wall": wall,
            "stats": router.stats(),
            "recs": [h.engine.request_latency.completed()
                     for h in rs.handles],
            "summ": [h.engine.request_latency.summary()
                     for h in rs.handles],
            "tiering": [dict(h.engine.tiering.counters)
                        for h in rs.handles],
        }
        for h in rs.handles:
            h.engine.audit_kv_sharing()
        rs.close()
        return res

    dgb_fused = dgb_run(1)
    _telemetry.trace.configure(enabled=True)
    _telemetry.trace.clear()
    dgb_split = dgb_run(2, roles={"r0": "prefill", "r1": "decode"})
    dgb_bytes = sum(
        int(ev.get("args", {}).get("bytes", 0))
        for ev in _telemetry.trace.snapshot()
        if ev.get("ph") == "X" and ev.get("name") == "handoff_transfer")
    _telemetry.trace.configure(enabled=False)
    _telemetry.trace.clear()

    assert sorted(dgb_split["outs"]) == sorted(dgb_fused["outs"]), (
        "disagg run lost requests: "
        f"{len(dgb_split['outs'])}/{len(dgb_fused['outs'])} finished")
    assert all(np.array_equal(dgb_split["outs"][i],
                              dgb_fused["outs"][i])
               for i in dgb_fused["outs"]), (
        "disaggregated greedy outputs diverged from fused serving")
    dgb_st = dgb_split["stats"]
    assert (dgb_st["handoff_kv"] == dgb_long
            and dgb_st["handoff_reprefill"] == 0), (
        f"vacuous split: expected {dgb_long} KV handoffs, got "
        f"kv={dgb_st['handoff_kv']} "
        f"reprefill={dgb_st['handoff_reprefill']}")
    dgb_tc = dgb_split["tiering"][1]
    assert (dgb_tc["imports"] == dgb_st["handoff_kv"]
            and dgb_tc["pages_verified"] == dgb_tc["pages_restored"] > 0
            and dgb_tc["quarantined"] == 0), (
        "handoff digest accounting broke: "
        f"imports={dgb_tc['imports']} "
        f"verified={dgb_tc['pages_verified']} "
        f"restored={dgb_tc['pages_restored']} "
        f"quarantined={dgb_tc['quarantined']}")

    # client-meaningful tails: TTFT from whichever replica produced the
    # first token (donor for longs — handoffs==0 on donor records);
    # TPOT from wherever decode steps ran (receiver continuations plus
    # short chats, never donor records, which end at one token)
    dgb_ttft = sorted(r["ttft_ms"] for rr in dgb_split["recs"]
                      for r in rr
                      if r["ttft_ms"] is not None and r["handoffs"] == 0)
    dgb_tpot = sorted(r["tpot_ms"] for rr in dgb_split["recs"]
                      for r in rr if r["tpot_ms"] is not None)
    dgb_f = dgb_fused["summ"][0]
    detail["disagg"] = {
        "replicas": "1 prefill + 1 decode",
        "requests": dgb_n,
        "long_prefills": dgb_long,
        "handoff_kv": dgb_st["handoff_kv"],
        "handoff_reprefill": dgb_st["handoff_reprefill"],
        "handoff_bytes": dgb_bytes,
        "pages_digest_verified": dgb_tc["pages_verified"],
        "fused_wall_s": round(dgb_fused["wall"], 3),
        "split_wall_s": round(dgb_split["wall"], 3),
        "fused_ttft_ms_p50": dgb_f["ttft_ms_p50"],
        "fused_ttft_ms_p99": dgb_f["ttft_ms_p99"],
        "fused_tpot_ms_p99": dgb_f["tpot_ms_p99"],
        "split_ttft_ms_p50": round(_pctl(dgb_ttft, 50) or 0.0, 2),
        "split_ttft_ms_p99": round(_pctl(dgb_ttft, 99) or 0.0, 2),
        "split_tpot_ms_p99": round(_pctl(dgb_tpot, 99) or 0.0, 2),
        "handoff_stall_ms_p50":
            dgb_split["summ"][1]["handoff_stall_ms_p50"],
        "handoff_stall_ms_p99":
            dgb_split["summ"][1]["handoff_stall_ms_p99"],
        "bit_identical_to_fused": True,       # asserted above
    }
    if multi_device:
        # real devices behind each role: the split must beat fused on
        # BOTH tails — TTFT (prefills no longer queue behind decode
        # blocks) and TPOT (decode steps no longer stall on prefills)
        assert (detail["disagg"]["split_ttft_ms_p99"]
                < dgb_f["ttft_ms_p99"]), (
            "disagg TTFT p99 "
            f"{detail['disagg']['split_ttft_ms_p99']}ms did not beat "
            f"fused {dgb_f['ttft_ms_p99']}ms")
        assert (detail["disagg"]["split_tpot_ms_p99"]
                < dgb_f["tpot_ms_p99"]), (
            "disagg TPOT p99 "
            f"{detail['disagg']['split_tpot_ms_p99']}ms did not beat "
            f"fused {dgb_f['tpot_ms_p99']}ms")
    else:
        detail["disagg"]["caveat"] = (
            "single-device host: both roles share one device, prefill "
            "and decode cannot overlap; tail floors not enforced "
            "(conservation, bit-parity and digest accounting asserted "
            "instead)")

    # -- network front door: HTTP/SSE serving at the socket -------------
    # The same 2-replica router behind the asyncio front door, measured
    # where the client sits: socket-level TTFT/TPOT from the load
    # generator at 8/64/200 simultaneous streams, against an in-process
    # control (submit straight into the Router, first-token time from
    # the event stream).  The delta IS the front door's overhead: HTTP
    # parse, SSE framing, the asyncio<->pump-thread hop, and kernel
    # socket buffers.
    from deepspeed_tpu.serving import FrontDoorServer
    from deepspeed_tpu.serving.client import LoadGenerator

    fd_new = 8
    fd_rng = np.random.default_rng(13)

    def fd_prompt_set(n):
        return [fd_rng.integers(0, cfg.vocab_size, int(l),
                                dtype=np.int32)
                for l in fd_rng.integers(4, max(chunk - 1, 5), size=n)]

    def fd_inproc(prompt_list):
        """In-process control: same workload straight into the Router,
        TTFT from the router's harvest-granularity event stream."""
        rs = ReplicaSet(so_engine, 2)
        router = Router(rs, policy="least_tokens",
                        queue_cap=len(prompt_list))
        router.collect_events = True
        sub, first = {}, {}
        t0 = time.perf_counter()
        for q in prompt_list:
            rid = router.submit(q, max_new_tokens=fd_new)
            sub[rid] = time.perf_counter()
        outs = {}
        while router.outstanding:
            router.pump()
            router.join()
            for name, rid, payload in router.poll_events():
                if name == "tokens" and rid not in first:
                    first[rid] = time.perf_counter()
            outs.update(router.get_outputs())
        wall = time.perf_counter() - t0
        rs.close()
        assert len(outs) == len(prompt_list), (
            f"in-process control lost requests: {len(outs)}/"
            f"{len(prompt_list)}")
        ttfts = sorted((first[r] - sub[r]) * 1e3 for r in first)
        return {"requests_per_s": round(len(outs) / wall, 3),
                "ttft_ms_p50": round(_pctl(ttfts, 50) or 0.0, 1),
                "ttft_ms_p99": round(_pctl(ttfts, 99) or 0.0, 1)}

    detail["frontdoor"] = {"replicas": 2, "max_new_tokens": fd_new,
                           "streams": {}}
    for fd_streams in (8, 64, 200):
        fd_prompts = fd_prompt_set(fd_streams)
        rs = ReplicaSet(so_engine, 2)
        router = Router(rs, policy="least_tokens",
                        queue_cap=fd_streams)
        srv = FrontDoorServer(router, port=0).start()
        gen = LoadGenerator(
            srv.host, srv.port,
            lambda i, P=fd_prompts: {"prompt": P[i].tolist(),
                                     "max_new_tokens": fd_new},
            requests=fd_streams, concurrency=fd_streams)
        fd_sum = gen.run()
        srv.close()
        rs.close()
        # conservation at the socket: every stream completes and every
        # generated token arrives exactly once over SSE
        assert fd_sum["completed"] == fd_streams, (
            f"front door lost streams at {fd_streams}-way: "
            f"{fd_sum['completed']}/{fd_streams} ({fd_sum['errors']})")
        assert fd_sum["tokens_streamed"] == fd_streams * fd_new, (
            f"front door dropped tokens at {fd_streams}-way: "
            f"{fd_sum['tokens_streamed']}/{fd_streams * fd_new}")
        detail["frontdoor"]["streams"][str(fd_streams)] = {
            "requests_per_s": fd_sum["requests_per_s"],
            "ttft_ms_p50": fd_sum["ttft_ms_p50"],
            "ttft_ms_p99": fd_sum["ttft_ms_p99"],
            "tpot_ms_p50": fd_sum["tpot_ms_p50"],
            "tpot_ms_p99": fd_sum["tpot_ms_p99"],
            "tokens_streamed": fd_sum["tokens_streamed"],
        }
    detail["frontdoor"]["inprocess_control_8"] = fd_inproc(
        fd_prompt_set(8))
    if not multi_device:
        detail["frontdoor"]["caveat"] = (
            "single-device host: replica threads and the asyncio loop "
            "share one core, so requests/s does not scale with "
            "streams; the row records socket-level latency overhead "
            "vs the in-process control (conservation asserted at "
            "every stream count)")

    # closed-loop autotune: the online controller walks a deliberately
    # mis-tuned engine (harvest=1, depth=1) back toward the hand-tuned
    # base config above; the row records all three throughputs plus the
    # decision trail (scripts/serve_smoke.py --autotune hard-gates
    # convergence/guard/attribution — this is the measured record)
    mis_kw = dict(harvest_interval=1, async_depth=1)
    # the smoke workload is only ~9 host steps — stretch generation so
    # the controller sees enough ticks to run whole probe trials
    at_kw = run_kw if on_tpu else {**run_kw, "new": 40}
    # decode_block=4 keeps a dispatch in (nearly) every host step, so
    # the per-window blocking_gets_per_dispatch signal stays dense
    at_block = decode_block if on_tpu else 4
    at_tok, _, at_wall, _, _ = _ragged_run(
        model, {"params": params}, decode_block=at_block,
        **mis_kw, **at_kw)
    # hand-tuned control on the SAME workload (engine defaults), so the
    # three throughputs in the row are directly comparable
    hd_tok, _, hd_wall, _, _ = _ragged_run(
        model, {"params": params}, decode_block=at_block, **at_kw)
    ctl_cfg = {"interval": 4, "settle": 1, "cooldown": 0,
               "objective": "-blocking_gets_per_dispatch"}
    cv_tok, _, cv_wall, _, cv_eng = _ragged_run(
        model, {"params": params}, decode_block=at_block,
        control=ctl_cfg, **mis_kw, **at_kw)
    ctl = cv_eng._controller
    assert ctl.counts["guard_violations"] == 0, (
        f"oscillation guard violated: {ctl.counts}")
    knob_end = ctl.knobs.snapshot()
    detail["autotune"] = {
        "mis_tuned": dict(mis_kw),
        "mis_tuned_tokens_per_sec": round(at_tok / max(at_wall, 1e-9), 1),
        "hand_tuned_tokens_per_sec": round(hd_tok / max(hd_wall, 1e-9), 1),
        "converged_tokens_per_sec": round(cv_tok / max(cv_wall, 1e-9), 1),
        "decisions": ctl.counts["decisions"],
        "accepts": ctl.counts["accepts"],
        "reverts": ctl.counts["reverts"],
        "freezes": ctl.counts["freezes"],
        "guard_violations": ctl.counts["guard_violations"],
        "knob_end": {k: knob_end[k] for k in sorted(knob_end)},
        "knob_trajectory": [
            {"tick": d["tick"], "knob": d["knob"], "new": d["new"]}
            for d in ctl.decision_log
            if d["action"] in ("accept", "rule")],
    }

    print(json.dumps({
        "metric": "ragged_continuous_batching_tokens_per_sec",
        "value": round(best_tps, 1),
        "unit": "tokens/s",
        # floor = this config's round-4 result (BENCH_MATRIX r4: 19302.3
        # tok/s device) — serving must not regress round over round
        "vs_baseline": round(best_tps / 19302.3, 3) if on_tpu else 0.0,
        "detail": detail,
    }))


def bench_infinity(args) -> None:
    """Config infinity: the beyond-HBM tiers at 7B scale on ONE chip.

    Llama-2-7B (13.5 GB bf16 params, 54 GB fp32 moments — 4x over a
    16 GB chip) takes a full MEASURED train step: params + grads in
    pinned host memory streamed per layer, Adam moments streamed through
    the device in flat host-resident buckets by the host-offload
    optimizer tier (``runtime/swap_tensor.py HostMomentSwapper``; the
    reference capability: ZeRO-Offload 13B on one 32GB V100 at >30
    TFLOPS, docs/_pages/training.md:302).  The row records the measured
    full step, the host-link rooflines that bound it (in-program
    pinned_host<->HBM GB/s), and the NVMe tier's bucketed swap bandwidth
    with the client-link control that bounds IT under this harness (the
    tunnel; on a local TPU host the same stream is disk-bound against
    the io row's measured GB/s)."""
    import os

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.llama import (LlamaLMLoss, count_params,
                                            flops_per_token, get_config)

    on_tpu = not args.smoke
    if on_tpu:
        size = args.size or "llama2-7b"
        # unrolled layers: XLA streams per-layer host->HBM param copies
        # (scan hoists whole stacked copies — measured 25.3G vs 15.8G)
        cfg = get_config(size, max_position_embeddings=1024,
                         dtype=jnp.bfloat16, remat=True,
                         remat_policy="full", scan_layers=False,
                         use_flash_attention=True)
        # micro=1: larger micros would amortize the per-step host->HBM
        # param stream over more tokens, but XLA keeps ~20 async
        # host-param copy-starts in flight as HLO temps (even with the
        # latency-hiding scheduler off) and micro>=2 OOMs a 16 GB chip.
        # The row instead RECORDS the fwd+bwd host-link bound — at
        # micro=1 the step is already ~3/4 pure transfer, so the
        # TFLOPS number is the link, not the framework (see
        # fwd_bwd_link_fraction in the detail)
        micro = int(os.environ.get("DSTPU_INFINITY_MICRO", "1"))
        seq = int(os.environ.get("DSTPU_INFINITY_SEQ", "1024"))
    else:
        cfg = get_config("tinyllama", dtype=jnp.float32, remat=False,
                         scan_layers=False)
        micro, seq = 2, 32
    nvme_dir = os.environ.get("DSTPU_NVME_DIR", "/tmp/dstpu_nvme")
    topo = dist.initialize_mesh()
    ds = {
        "train_batch_size": micro,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": on_tpu, "master_weights": False},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu", "pin_memory": True},
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
        },
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000000,
    }
    batch = _tokens(cfg.vocab_size, micro, seq)
    engine, *_ = deepspeed_tpu.initialize(
        model=LlamaLMLoss(cfg), config=ds, topology=topo,
        example_batch=batch, rng=jax.random.PRNGKey(0))
    n_params = count_params(engine.state.params)
    from deepspeed_tpu.runtime.swap_tensor import HostMomentSwapper

    host_tier = isinstance(engine.nvme_swapper, HostMomentSwapper)

    # fwd+bwd alone — reuse the SAME with_gmetrics program the full
    # train step dispatches (a metrics-free variant would cost a second
    # multi-minute 7B compile for two scalar reductions of difference)
    fused_metrics = engine.gas == 1
    if engine._nvme_grad_step_fn is None and engine.nvme_swapper is not None:
        engine._nvme_grad_step_fn = engine._build_grad_step(
            host_grads=engine.offload_param, with_gmetrics=fused_metrics)
    gfn = engine._nvme_grad_step_fn
    if gfn is None:                        # smoke fallback: no swapper
        gfn = engine._grad_step_fn = engine._build_grad_step()
        fused_metrics = False
    mb = jax.tree_util.tree_map(jnp.asarray, batch)
    rngk = jax.random.PRNGKey(1)
    out = gfn(engine.state, mb, rngk)      # compile
    loss, grads = out[0], out[1]
    loss_v = float(jax.device_get(loss))
    jax.block_until_ready(grads)
    times = []
    for _ in range(2 if on_tpu else 1):
        t0 = time.perf_counter()
        out = gfn(engine.state, mb, rngk)
        loss, grads = out[0], out[1]
        jax.block_until_ready((loss, grads))
        times.append(time.perf_counter() - t0)
    fb_s = min(times)
    fwd_bwd_flops_tok = flops_per_token(cfg, seq) * 2.0 / 3.0
    tflops = (fwd_bwd_flops_tok * micro * seq / fb_s) / 1e12
    del grads

    # the MEASURED full train step: fwd+bwd + host-moment optimizer
    # stream (per-bucket programs, moments never leave the accelerator
    # host).  First call compiles the bucket programs.
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))]
    step_times = []
    for _ in range(2 if on_tpu else 1):
        t0 = time.perf_counter()
        losses.append(float(jax.device_get(
            engine.train_batch(batch=batch))))
        step_times.append(time.perf_counter() - t0)
    full_step_s = min(step_times)
    moment_gb = n_params * 8 / 1e9

    detail = {"params": n_params, "seq": seq, "micro": micro,
              "fwd_bwd_step_s": round(fb_s, 2),
              "full_train_step_s": round(full_step_s, 2),
              "full_step_measured": True,
              "optimizer_tier": ("host-moment stream" if host_tier
                                 else "device"),
              "optimizer_step_s": round(full_step_s - fb_s, 2),
              "moment_bytes_total_gb": round(moment_gb, 1),
              "losses": [round(x, 3) for x in losses],
              "initial_loss": round(loss_v, 3),
              "final_loss": round(losses[-1], 3),
              "offload": "param=cpu(host-streamed) grads=cpu "
                         "optimizer=cpu(host-moment buckets)",
              "device": jax.devices()[0].device_kind}

    if on_tpu:
        # host-link rooflines: in-program pinned_host<->HBM copies of a
        # 2 GB block, device time from profiler events (wall lies behind
        # the tunnel).  These BOUND the tiers above: fwd+bwd moves
        # ~2x params h2d + params d2h (grads); the optimizer moves
        # 2x moments each way.
        import sys as _sys

        _sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "scripts"))
        from _prof import profile_device

        from deepspeed_tpu.utils.sharding import memory_space

        d0 = jax.devices()[0]
        hostsh = jax.sharding.SingleDeviceSharding(
            d0, memory_kind="pinned_host")
        devsh = jax.sharding.SingleDeviceSharding(d0, memory_kind="device")
        N = 256 * 1024 * 1024                      # 1 GB fp32
        xh = jax.jit(lambda k: jax.random.normal(k, (N,), jnp.float32),
                     out_shardings=hostsh)(jax.random.PRNGKey(0))
        jax.block_until_ready(xh)
        f_h2d = jax.jit(lambda a: jax.device_put(
            a, memory_space("device")) * 1.000001, out_shardings=devsh)
        yd = f_h2d(xh)
        jax.block_until_ready(yd)
        ms, _ = profile_device(lambda: f_h2d(xh), n=3, tag="h2d")
        h2d_gbps = N * 4 / (ms / 1e3) / 1e9 if ms else 0.0
        f_d2h = jax.jit(lambda a: jax.device_put(
            a * 1.000001, memory_space("pinned_host")),
            out_shardings=hostsh)
        zh = f_d2h(yd)
        jax.block_until_ready(zh)
        ms, _ = profile_device(lambda: f_d2h(yd), n=3, tag="d2h")
        d2h_gbps = N * 4 / (ms / 1e3) / 1e9 if ms else 0.0
        del xh, yd, zh
        param_gb = n_params * 2 / 1e9
        bound_s = 0.0
        if h2d_gbps and d2h_gbps:
            # fwd+bwd: params h2d twice (remat) + grads d2h once;
            # optimizer: moments h2d + d2h + params both ways
            bound_s = (2 * param_gb / h2d_gbps + param_gb / d2h_gbps +
                       (moment_gb + param_gb) / h2d_gbps +
                       (moment_gb + param_gb) / d2h_gbps)
        detail["host_link_h2d_gbps"] = round(h2d_gbps, 2)
        detail["host_link_d2h_gbps"] = round(d2h_gbps, 2)
        detail["link_roofline_step_s"] = round(bound_s, 2)
        detail["link_bound_fraction"] = round(
            bound_s / full_step_s, 2) if full_step_s else None
        if h2d_gbps and d2h_gbps:
            # fwd+bwd alone: params h2d twice (remat recompute) + bf16
            # grads d2h once — the bound the TFLOPS number sits on
            fb_bound = 2 * param_gb / h2d_gbps + param_gb / d2h_gbps
            detail["fwd_bwd_link_bound_s"] = round(fb_bound, 2)
            detail["fwd_bwd_link_fraction"] = round(fb_bound / fb_s, 2)

    # NVMe tier: bucketed swap of the two largest leaves (full-model
    # NVMe streaming through THIS harness is client-link-bound — the
    # control below proves it; the host-moment tier above is the
    # measured full step)
    from deepspeed_tpu.runtime.swap_tensor import NvmeOptimizerSwapper

    flat = jax.tree_util.tree_flatten_with_path(engine.state.params)[0]
    big = sorted(flat, key=lambda kv: -kv[1].size)[:2]
    sub_params = {"/".join(str(getattr(k, "key", k)) for k in kp): v
                  for kp, v in big}
    sub_grads = jax.tree_util.tree_map(
        lambda v: jnp.ones(v.shape, v.dtype), sub_params)
    def measure_swap(verify: bool, reps: int = 3):
        swapper = NvmeOptimizerSwapper(nvme_dir, sub_params,
                                       sdc_verify=verify)
        try:
            swapper.apply(sub_params, sub_grads, lr=1e-4, gscale=1.0)
            nb = sum(v.size * 8 for v in sub_params.values())
            best = float("inf")
            for _ in range(reps):         # best-of: amortize cache noise
                t0 = time.perf_counter()
                swapper.start_prefetch()  # as the engine does, post-bwd
                swapper.apply(sub_params, sub_grads, lr=1e-4, gscale=1.0)
                swapper.drain()           # charge deferred write-back here
                best = min(best, time.perf_counter() - t0)
            stages = dict(swapper.stage_stats)
        finally:
            swapper.close()
        return 2 * nb / best / 1e9, nb, stages

    # verify-off control FIRST (warms the page cache the same way for
    # both), then the verify-on run the row reports — the delta is the
    # measured end-to-end checksum cost on the stream
    gbps_off, _, _ = measure_swap(verify=False)
    stream_gbps, nbytes, stages = measure_swap(verify=True)
    # per-stage pipeline waits: the evidence that the stream is
    # overlap-bound or bandwidth-bound, not an asserted property
    detail["nvme_swap_stages"] = stages
    detail["nvme_swap_gbps"] = round(stream_gbps, 3)
    detail["nvme_swap_gbps_verify_off"] = round(gbps_off, 3)
    # SDC checksum overhead on the moment stream (target <= 5%).  The
    # digests run on a side thread pool, so the cost hides behind the
    # pipeline wherever >= 2 host cores exist; on a 1-core container
    # every pass serializes and this measures the raw 2-extra-memory-
    # passes cost instead (~bytes/9GBps over the stream wall) — read
    # it together with host_cores.  Negative deltas are run-to-run
    # noise, clamped to 0.
    detail["sdc_overhead_pct"] = round(
        max(0.0, (gbps_off - stream_gbps) / gbps_off * 100.0), 2) \
        if gbps_off > 0 else None
    detail["host_cores"] = os.cpu_count()

    # one traced swap step: the swap-path spans (swap_in_wait /
    # bucket_update / swap_out_wait / swap_verify / apply) re-emitted
    # through the unified tracer must export as valid Chrome-trace JSON
    from deepspeed_tpu import telemetry
    telemetry.configure(enabled=True)
    telemetry.trace.clear()
    tr_swapper = NvmeOptimizerSwapper(nvme_dir, sub_params,
                                      sdc_verify=True)
    try:
        tr_swapper.apply(sub_params, sub_grads, lr=1e-4, gscale=1.0)
        tr_swapper.start_prefetch()
        tr_swapper.apply(sub_params, sub_grads, lr=1e-4, gscale=1.0)
        tr_swapper.drain()
    finally:
        tr_swapper.close()
    swap_trace_path = "/tmp/dstpu_infinity_swap_trace.json"
    telemetry.trace.export(swap_trace_path)
    telemetry.configure(enabled=False)
    trace_ok, trace_events = _validate_chrome_trace(swap_trace_path)
    detail["swap_trace"] = {"chrome_trace_valid": trace_ok,
                            "events": trace_events,
                            "export": swap_trace_path}
    if on_tpu:
        # client-link control: eager device_put/device_get of 64 MB —
        # the path every NVMe swap byte takes under this tunnel harness
        buf = np.random.default_rng(0).standard_normal(
            16 * 1024 * 1024).astype(np.float32)
        t0 = time.perf_counter()
        db = jax.device_put(buf, jax.devices()[0])
        jax.block_until_ready(db)
        up = buf.nbytes / (time.perf_counter() - t0) / 1e9
        t0 = time.perf_counter()
        _ = np.asarray(db)
        down = buf.nbytes / (time.perf_counter() - t0) / 1e9
        detail["client_link_control_gbps"] = {
            "h2d": round(up, 3), "d2h": round(down, 3)}
        denom = 1.0 / max(up, 1e-9) + 1.0 / max(down, 1e-9)
        detail["nvme_swap_vs_client_link"] = round(
            stream_gbps / (2.0 / denom), 2)

    print(json.dumps({
        "metric": "zero_infinity_7b_single_chip_fwd_bwd_tflops",
        "value": round(tflops, 2),
        "unit": "TFLOPS",
        # reference ZeRO-Offload: 13B on one V100 at >30 TFLOPS
        "vs_baseline": round(tflops / 30.0, 3),
        "detail": detail,
    }))


def bench_io(args) -> None:
    """AIO engine throughput (reference DeepNVMe ds_io numbers: 7/4 GB/s
    read/write without GDS, BASELINE.md).  Sweeps the native engine
    against ``$DSTPU_IO_DIR`` (default /tmp — point it at the NVMe mount
    for authoritative numbers)."""
    import os

    from deepspeed_tpu.io.bench import raw_control, tune

    directory = os.environ.get("DSTPU_IO_DIR", "/tmp")
    size = (64 if args.smoke else 512) << 20
    best = tune(directory, size, loops=1 if args.smoke else 2,
                verbose=False)
    # device-roofline control: O_DIRECT sequential, no ring engine —
    # "the write number IS the disk" must be data, not folklore
    ctrl_r, ctrl_w = raw_control(directory, size)
    print(json.dumps({
        "metric": "aio_read_write_gbps",
        "value": round(best["read_gbps"] + best["write_gbps"], 2),
        "unit": "GB/s (r+w)",
        # reference DeepNVMe without GDS: 7 read + 4 write = 11 combined
        "vs_baseline": round((best["read_gbps"] + best["write_gbps"]) / 11.0,
                             3),
        "detail": {"read_gbps": round(best["read_gbps"], 2),
                   "write_gbps": round(best["write_gbps"], 2),
                   "control_read_gbps": round(ctrl_r, 2),
                   "control_write_gbps": round(ctrl_w, 2),
                   "engine_vs_control_write": round(
                       best["write_gbps"] / ctrl_w, 2) if ctrl_w else None,
                   "dir": directory, "size_mb": size >> 20,
                   "config": best["config"]},
    }))


def bench_leafwise_multiproc(args) -> None:
    """Multi-process LEAFWISE moment-stream rate: two real
    jax.distributed processes (the tests/unit/multiproc fixture worker)
    each swap THEIR ZeRO-3 shard's Adam moments through the per-shard
    leafwise NVMe stream — the path every ``process_count > 1`` job
    runs (the bucketed pipeline is single-process only).  The row is
    the combined cross-rank stream rate; per-rank read/write rates ride
    in ``detail``.  Point ``$DSTPU_IO_DIR`` at the NVMe mount for
    authoritative numbers."""
    import os
    import socket
    import subprocess
    import sys
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "unit", "multiproc", "worker_train.py")
    scratch = tempfile.mkdtemp(
        prefix="dstpu_leafwise_mp_",
        dir=os.environ.get("DSTPU_IO_DIR", None))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({"DSTPU_COORD": f"127.0.0.1:{port}",
                    "DSTPU_NPROC": "2", "DSTPU_PID": str(pid),
                    "DSTPU_MODE": "nvme", "DSTPU_DIR": scratch,
                    "JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""})
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    stats = {}
    for p in procs:
        out, _ = p.communicate(timeout=900)
        assert p.returncode == 0, f"leafwise_mp worker failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                rec = json.loads(line[len("RESULT "):])
                stats[rec["pid"]] = rec["leafwise"]
    shutil.rmtree(scratch, ignore_errors=True)
    assert len(stats) == 2 and all(s is not None for s in stats.values()), \
        stats
    combined = sum(s["stream_gbps"] for s in stats.values())
    print(json.dumps({
        "metric": "nvme_leafwise_multiproc_stream_gbps",
        "value": round(combined, 4),
        "unit": "GB/s (r+w, 2 ranks)",
        # reference DeepNVMe without GDS: 7 read + 4 write = 11 combined
        # (same floor as the io row — each rank's shard stream rides the
        # same AIO engine)
        "vs_baseline": round(combined / 11.0, 4),
        "detail": {f"rank{pid}": {
            "read_gbps": s["read_gbps"], "write_gbps": s["write_gbps"],
            "bytes_read": s["bytes_read"],
            "bytes_written": s["bytes_written"], "wall_s": s["wall_s"]}
            for pid, s in sorted(stats.items())},
    }))


CONFIGS = {
    "1": bench_gpt2_ddp,
    "2": bench_gpt2_zero2_fused,
    "3": bench_llama_zero3,
    "4": bench_ulysses_longctx,
    "5": bench_moe_ep,
    "infer": bench_inference,
    "ragged": bench_ragged,
    "io": bench_io,
    "infinity": bench_infinity,
    "leafwise_mp": bench_leafwise_multiproc,
}


def bench_all(args) -> None:
    """Run EVERY config in a fresh subprocess; write the machine-readable
    matrix to BENCH_MATRIX.json (the committed evidence for all rows —
    regressions in configs 2-5 can't hide behind the headline number)."""
    import datetime
    import os
    import subprocess
    import sys

    records = {}
    for name in ["1", "2", "3", "4", "5", "infer", "ragged", "io",
                 "infinity", "leafwise_mp"]:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--config", name, "--steps", str(args.steps)]
        if args.smoke:
            cmd.append("--smoke")
        print(f"=== bench --config {name}", flush=True)
        tries = 2 if not args.smoke else 1
        # the infinity config streams ~120GB of moments+grads per
        # measured step through host+NVMe tiers: give it headroom
        budget = 3600 if name == "infinity" else 1800
        for attempt in range(tries):
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=budget)
            except subprocess.TimeoutExpired:
                print(f"config {name} attempt {attempt + 1} timed out",
                      flush=True)
                continue
            lines = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")]
            if lines:
                records[name] = json.loads(lines[-1])
                print(lines[-1], flush=True)
                break
            # tunnel compile flakes (HTTP 500) warrant one retry in a
            # fresh process; real failures repeat
            print(f"config {name} attempt {attempt + 1} produced no "
                  f"JSON:\n{r.stderr[-500:]}", flush=True)
        else:
            records[name] = {"metric": f"config_{name}", "value": None,
                             "unit": "FAILED", "vs_baseline": 0.0}
    # device info comes from the children's records — the parent must
    # never touch jax: on standard TPU VMs libtpu is exclusive per
    # process and a parent hold would fail every child's init
    dev_info = next((r.get("detail", {}) for r in records.values()
                     if r.get("detail", {}).get("device")), {})
    out = {
        "generated": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "device": dev_info.get("device", "unknown"),
        "n_chips": dev_info.get("n_chips", 1),
        "smoke": bool(args.smoke),
        "configs": records,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_MATRIX.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="1", choices=sorted(CONFIGS),
                   help="BASELINE.md target config to run")
    p.add_argument("--all", action="store_true",
                   help="run every config (fresh subprocess each) and "
                        "write BENCH_MATRIX.json")
    p.add_argument("--size", default=None,
                   help="model preset override (e.g. gpt2-350m)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes (auto on CPU)")
    args = p.parse_args()
    if args.all:
        # children probe their own backend (and set smoke on CPU); the
        # parent stays jax-free so it never locks an exclusive libtpu
        bench_all(args)
        return
    if jax.devices()[0].platform == "cpu":
        args.smoke = True
    CONFIGS[args.config](args)


if __name__ == "__main__":
    main()
