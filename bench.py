"""Headline benchmark: GPT-2 training throughput + MFU on one chip.

Run by the driver on real TPU hardware at the end of every round; prints ONE
JSON line ``{"metric", "value", "unit", "vs_baseline"}``.  The metric is
model FLOPs utilization (MFU) for a bf16 GPT-2 train step — the BASELINE.md
north star is ZeRO-3 Llama-2-7B at >=45% MFU on v5p-128, so ``vs_baseline``
reports value/45.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOPs per chip by device kind substring
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v6": 918e12,  # trillium
    "cpu": 1e12,       # nominal, for smoke runs
}

NORTH_STAR_MFU = 45.0


def peak_flops(kind: str) -> float:
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()) or k.lower() in kind.lower():
            return v
    return 197e12


def main() -> None:
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.gpt2 import (GPT2LMLoss, count_params,
                                           get_config)

    if on_tpu:
        cfg_model = get_config("gpt2-125m", n_positions=1024,
                               dtype=jnp.bfloat16, remat=True,
                               scan_layers=True)
        micro, seq, steps = 8, 1024, 20
    else:  # CPU smoke: tiny shapes so the line still prints
        cfg_model = get_config("gpt2-125m", n_positions=128, n_embd=256,
                               n_layer=4, n_head=4, dtype=jnp.float32,
                               remat=False)
        micro, seq, steps = 2, 128, 3

    topo = dist.initialize_mesh()  # all visible devices on the data axis
    dp = topo.zero_partition_count()
    ds_config = {
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": bool(on_tpu)},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.01}},
        "steps_per_print": 1000000,
    }
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg_model.vocab_size, size=(micro * dp, seq), dtype=np.int32)}

    engine, *_ = deepspeed_tpu.initialize(
        model=GPT2LMLoss(cfg_model), config=ds_config, topology=topo,
        example_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))

    n_params = count_params(engine.state.params)

    # warmup (compile)
    engine.train_batch(batch=batch)
    jax.effects_barrier()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = steps * micro * dp / dt
    tokens_per_sec = samples_per_sec * seq
    from deepspeed_tpu.models.gpt2 import flops_per_token
    model_flops = tokens_per_sec * flops_per_token(cfg_model, seq)
    n_chips = len(jax.devices())
    mfu = 100.0 * model_flops / (peak_flops(dev.device_kind) * n_chips)

    result = {
        "metric": "gpt2_125m_bf16_train_mfu",
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 3),
        "detail": {
            "samples_per_sec_per_chip": round(samples_per_sec / n_chips, 2),
            "tokens_per_sec": round(tokens_per_sec),
            "params": n_params,
            "device": dev.device_kind,
            "n_chips": n_chips,
            "final_loss": float(jax.device_get(loss)),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
