from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)

__all__ = [
    "compute_elastic_config", "elasticity_enabled",
    "ensure_immutable_elastic_config", "ElasticityError",
    "ElasticityConfigError", "ElasticityIncompatibleWorldSize",
]
