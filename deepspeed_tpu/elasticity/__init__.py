from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    nearest_valid_worlds,
    validate_world_size,
)

__all__ = [
    "compute_elastic_config", "elasticity_enabled",
    "ensure_immutable_elastic_config", "nearest_valid_worlds",
    "validate_world_size", "ElasticityError",
    "ElasticityConfigError", "ElasticityIncompatibleWorldSize",
]
