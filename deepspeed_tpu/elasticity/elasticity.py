"""Elastic training: batch-size / device-count co-design.

Re-implements the reference elasticity solver semantics
(``elasticity/elasticity.py:233 compute_elastic_config``, ``:83
_get_compatible_gpus_v01``, ``:126 _get_compatible_gpus_v02``) for TPU
jobs.  The problem is hardware-agnostic scheduling math: pick ONE global
train batch size that (a) stays under a user cap, (b) decomposes as
``micro_batch x grad_accum x chips`` for as many chip counts as possible,
so a preemptible/elastic TPU job can be rescaled across that chip-count
menu without changing the effective batch size (and therefore without
perturbing convergence).

v0.1 picks the batch size with the widest valid-chip menu; v0.2 works at
node (TPU host) granularity — chip counts move in whole hosts, and the
``model_parallel_size`` (our tp) divides each host's chips so the menu is
expressed in data-parallel ranks.

On TPU the "resource scheduler" counterpart is the GKE/Borg-style job
controller: it reads the same config via the
``DEEPSPEED_ELASTICITY_CONFIG`` environment variable and must agree with
the runtime (``ensure_immutable_elastic_config``).
"""
from __future__ import annotations

import bisect
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

ELASTICITY = "elasticity"
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"
LATEST_VERSION = 0.2

# Highly composite numbers: each has more divisors than any smaller
# integer, so scaling a base micro-batch by one maximizes the number of
# chip counts that divide the result.  Covers batch sizes to ~720k.
_HCN = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
    1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
    50400, 55440, 83160, 110880, 166320, 221760, 277200, 332640, 498960,
    554400, 665280, 720720,
]


class ElasticityError(RuntimeError):
    """Generic elasticity failure."""


class ElasticityConfigError(ElasticityError):
    """Bad or missing elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size is not on the valid chip-count menu.

    Carries the menu so callers (the elastic agent, the launcher) can
    steer toward a schedulable allocation instead of burning restarts:
    ``valid_worlds`` is the full menu in CHIPS (dp * model_parallel) and
    ``nearest`` the menu entries closest to the offending world.
    """

    def __init__(self, msg: str, valid_worlds: Sequence[int] = (),
                 nearest: Sequence[int] = ()):
        super().__init__(msg)
        self.valid_worlds = list(valid_worlds)
        self.nearest = list(nearest)


def nearest_valid_worlds(menu: Sequence[int], world: int,
                         k: int = 3) -> List[int]:
    """The ``k`` menu entries closest to ``world`` (ties toward the
    smaller entry, result sorted ascending) — the 'did you mean'
    suggestion for an off-menu allocation."""
    return sorted(sorted(menu, key=lambda n: (abs(n - world), n))[:k])


def _largest_hcn_multiple(base: int, cap: int) -> int:
    """Largest ``base * h`` <= cap with h a highly-composite number (or
    ``base`` itself when it already exceeds the cap)."""
    if base >= cap:
        return base
    # rightmost HCN <= cap // base; bisect_right gives first > value
    i = bisect.bisect_right(_HCN, cap // base)
    return _HCN[max(i - 1, 0)] * base


def get_candidate_batch_sizes(bases: Sequence[int], cap: int) -> List[int]:
    """One candidate global batch per base (each micro-batch and their
    LCM), scaled to the largest HCN multiple under ``cap``."""
    return sorted({_largest_hcn_multiple(b, cap) for b in bases})


def get_valid_chips(batch_size: int, micro_batches: Sequence[int],
                    min_chips: int, max_chips: int) -> List[int]:
    """All chip counts n with ``min <= n <= max`` such that ``batch_size
    = micro_batch * gas * n`` for some configured micro-batch and integer
    gas — i.e. n divides batch_size // micro_batch."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        quotient = batch_size // mb
        for n in range(1, int(math.isqrt(quotient)) + 1):
            if quotient % n == 0:
                for d in (n, quotient // n):
                    if min_chips <= d <= max_chips:
                        valid.add(d)
    return sorted(valid)


def _solve_v01(micro_batches: Sequence[int], max_batch: int,
               min_chips: Optional[int] = None,
               max_chips: Optional[int] = None,
               prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """Pick the candidate batch size whose valid-chip menu is longest
    (ties broken toward larger/smaller batch per ``prefer_larger``)."""
    min_chips = min_chips or 1
    max_chips = max_chips or max_batch // min(micro_batches)
    bad = [mb for mb in micro_batches if mb > max_batch]
    if bad:
        raise ElasticityConfigError(
            f"micro batches {bad} exceed max_train_batch_size {max_batch}")

    lcm = math.lcm(*micro_batches)
    candidates = get_candidate_batch_sizes(
        list(micro_batches) + [lcm], max_batch)

    best_batch, best_menu = min(micro_batches), []
    for cand in candidates:
        menu = get_valid_chips(cand, micro_batches, min_chips, max_chips)
        better = len(menu) > len(best_menu) or (
            len(menu) == len(best_menu)
            and (cand > best_batch if prefer_larger else cand < best_batch))
        if better:
            best_batch, best_menu = cand, menu
    return best_batch, best_menu


def _solve_v02(micro_batches: Sequence[int], max_batch: int,
               current_chips: int, min_chips: int, max_chips: int,
               prefer_larger: bool, chips_per_node: int,
               model_parallel_size: int
               ) -> Tuple[int, List[int], Optional[int]]:
    """Node-granular solve: the menu moves in whole hosts and is
    expressed in data-parallel ranks (chips / tp)."""
    if chips_per_node % model_parallel_size:
        raise ElasticityError(
            f"chips per node {chips_per_node} must be divisible by "
            f"model_parallel_size {model_parallel_size}")
    dp_per_node = chips_per_node // model_parallel_size

    current_dp = current_chips // model_parallel_size

    def pick_micro(batch: int) -> Optional[int]:
        fits = [mb for mb in micro_batches
                if (batch // current_dp) % mb == 0]
        if not fits:
            return None
        return max(fits) if prefer_larger else fits[0]

    node_batch, node_menu = _solve_v01(
        micro_batches, max_batch // dp_per_node,
        min_chips // chips_per_node, max_chips // chips_per_node,
        prefer_larger=prefer_larger)
    batch = node_batch * dp_per_node
    dp_menu = [n * dp_per_node for n in node_menu]
    if current_dp in dp_menu:
        return batch, dp_menu, pick_micro(batch)

    # current allocation is off-menu: keep it, maximize batch under cap
    per_mb = [mb * current_dp * (max_batch // (mb * current_dp))
              for mb in micro_batches if mb * current_dp <= max_batch]
    if not per_mb:
        chips_menu = [n * model_parallel_size for n in dp_menu]
        near = nearest_valid_worlds(chips_menu, current_chips)
        raise ElasticityIncompatibleWorldSize(
            f"no configured micro batch fits: every micro_batch * dp "
            f"({micro_batches} * {current_dp}) exceeds "
            f"max_train_batch_size {max_batch}; nearest valid worlds "
            f"(chips): {near}", valid_worlds=chips_menu, nearest=near)
    batch = max(per_mb) if prefer_larger else min(per_mb)
    return batch, [current_dp], pick_micro(batch)


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get(ELASTICITY, {}).get("enabled", False))


def validate_world_size(ds_config: Dict, world_size: int) -> None:
    """Fail FAST when the discovered device/process count cannot run the
    requested elastic config.

    Called at launch (and on every elastic re-slice) with the world the
    hardware actually provides — today an impossible world only surfaces
    deep inside mesh construction as an opaque reshape error.  No-op
    when elasticity is disabled; raises
    :class:`ElasticityIncompatibleWorldSize` with the nearest valid
    worlds otherwise.
    """
    if not elasticity_enabled(ds_config):
        return
    compute_elastic_config(ds_config, world_size=int(world_size))


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict
                                    ) -> None:
    """The job controller exports the elastic config it scheduled with via
    ``DEEPSPEED_ELASTICITY_CONFIG``; the runtime must not drift from it."""
    if DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            f"{DEEPSPEED_ELASTICITY_CONFIG} not set: cannot verify the "
            "resource scheduler is scaling with a compatible chip-count "
            "menu")
        return
    sched = json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG])
    run = runtime_elastic_config_dict
    for key in ("max_train_batch_size", "micro_batch_sizes", "version"):
        sv, rv = sched.get(key), run.get(key)
        if sv is not None and rv is not None and sv != rv:
            raise ElasticityConfigError(
                f"elastic config drift on {key!r}: scheduler saw {sv}, "
                f"runtime has {rv}")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version:
                           str = "0.16.4", world_size: int = 0,
                           return_microbatch: bool = False):
    """Solve for (global batch size, valid chip-count menu[, micro batch]).

    Deterministic for a given config — callable identically from the job
    controller and from the runtime (reference contract,
    ``elasticity/elasticity.py:233``).  ``world_size``, when nonzero, is
    validated against the menu and selects the concrete micro-batch.
    """
    if not isinstance(ds_config, dict):
        raise ValueError(f"ds_config must be a dict, got {type(ds_config)}")
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{ELASTICITY}' section missing from config")
    ecfg = dict(ds_config[ELASTICITY])
    if not ecfg.get("enabled", False):
        raise ElasticityConfigError("elasticity is disabled in the config")

    version = float(ecfg.get("version", 0.2))
    micro_batches = list(ecfg.get("micro_batch_sizes", [2, 4, 6]))
    max_batch = int(ecfg.get("max_train_batch_size", 2000))
    min_chips = int(ecfg.get("min_gpus", 1))
    max_chips = int(ecfg.get("max_gpus", 10000))
    prefer_larger = bool(ecfg.get("prefer_larger_batch", True))
    mp_size = int(ecfg.get("model_parallel_size", 1))
    chips_per_node = int(ecfg.get("num_gpus_per_node", 1))

    if version > LATEST_VERSION:
        raise ElasticityConfigError(
            f"elasticity version {version} > latest {LATEST_VERSION}")
    if mp_size > 1 and version != 0.2:
        raise ElasticityConfigError(
            f"model parallelism requires elasticity v0.2, got {version}")

    candidate_micro = None
    if version == 0.1:
        batch, menu = _solve_v01(micro_batches, max_batch, min_chips,
                                 max_chips, prefer_larger)
    elif version == 0.2:
        current = world_size or int(os.environ.get("WORLD_SIZE", 0) or 0)
        if not current:
            raise ElasticityConfigError(
                "elasticity v0.2 needs the current world size: pass "
                "world_size= or set WORLD_SIZE")
        batch, menu, candidate_micro = _solve_v02(
            micro_batches, max_batch, current, min_chips, max_chips,
            prefer_larger, chips_per_node, mp_size)
    else:
        raise NotImplementedError(f"elasticity version {version}")
    batch = int(batch)
    logger.info(f"elasticity: batch={batch}, valid world sizes "
                f"(chips / model-parallel): {menu}")

    def micro_for(dp: int) -> int:
        for mb in sorted(set(micro_batches), reverse=True):
            if (batch // dp) % mb == 0:
                return mb
        raise ElasticityError(
            f"no configured micro batch divides {batch}//{dp}")

    if world_size > 0:
        # the menu is in data-parallel ranks (chips / model-parallel);
        # the reference compares the raw world size, which only agrees
        # when mp == 1 — we use the dp size consistently
        dp = world_size // mp_size
        if dp not in menu:
            chips_menu = [n * mp_size for n in menu]
            near = nearest_valid_worlds(chips_menu, world_size)
            raise ElasticityIncompatibleWorldSize(
                f"dp world size {dp} (world {world_size} / mp {mp_size}) "
                f"not in valid menu {menu}; nearest valid worlds "
                f"(chips): {near}",
                valid_worlds=chips_menu, nearest=near)
        return batch, menu, micro_for(dp)
    if return_microbatch:
        micro = candidate_micro if version == 0.2 else micro_for(menu[-1])
        return batch, menu, micro
    return batch, menu
