"""Managed-cluster environment discovery for the distributed bootstrap.

TPU-native counterpart of the reference's MPI/cloud env plumbing
(``deepspeed/comm/comm.py:694`` ``mpi_discovery``, ``:754``
``patch_aml_env_for_torch_nccl_backend`` / AWS-SM patching): derive the
``jax.distributed.initialize`` arguments — coordinator address, world
size, process id — from whatever launcher scheduled this process, so
multi-host bring-up on Slurm / OpenMPI / MPICH / Intel-MPI / torchrun /
Cloud-TPU pods needs no manual ``DSTPU_*`` plumbing.

Unlike the reference (which needs mpi4py collectives to agree on a
master address), every convention handled here carries enough in the
environment alone: scheduler-provided rank/size plus a deterministic
first-host coordinator.
"""
from __future__ import annotations

import json
import os
import re
from typing import Mapping, Optional

from deepspeed_tpu.utils.logging import logger

__all__ = ["discover_distributed_env", "first_slurm_host"]

DEFAULT_COORDINATOR_PORT = 29500


def first_slurm_host(nodelist: str) -> str:
    """First hostname of a compact Slurm nodelist.

    Handles ``host1``, ``a,b``, ``prefix[001-004,007]``, and
    ``prefix[3,5]x,other`` forms — only the FIRST entry is expanded
    (the coordinator); zero padding is preserved.
    """
    nodelist = nodelist.strip()
    m = re.match(r"([^,\[]+)(\[([^\]]+)\])?", nodelist)
    if not m:
        return nodelist.split(",")[0]
    prefix, _, spec = m.groups()
    if not spec:
        return prefix
    first = spec.split(",")[0]
    lo = first.split("-")[0]
    suffix = nodelist[m.end():].split(",")[0]
    return f"{prefix}{lo}{suffix}"


def discover_distributed_env(
        environ: Optional[Mapping[str, str]] = None,
        default_port: int = DEFAULT_COORDINATOR_PORT
) -> Optional[dict]:
    """Derive distributed-init settings from scheduler conventions.

    Returns ``None`` when nothing indicates a multi-process launch,
    ``{"auto": True}`` when the runtime self-discovers (Cloud TPU pod
    metadata — call ``jax.distributed.initialize()`` bare), else
    ``{"coordinator_address", "num_processes", "process_id",
    "local_rank", "source"}``.

    Priority: Cloud-TPU pod metadata > Slurm > OpenMPI (incl. AML /
    AWS-SageMaker hosted MPI) > MPICH/Intel-MPI PMI > torchrun-style
    RANK/WORLD_SIZE.
    """
    env = environ if environ is not None else os.environ

    # Cloud TPU pods (GKE / queued resources): libtpu metadata carries
    # the full topology; jax.distributed.initialize() with no arguments
    # is the supported path.  Single-worker TPU VMs also carry
    # TPU_WORKER_ID=0 — only a multi-host hostname list means a pod
    # (standing up a coordinator on a lone VM would break concurrent
    # single-process jobs on the same host).
    hostnames = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
                 if h]
    if len(hostnames) > 1:
        return {"auto": True, "source": "cloud-tpu"}

    port = int(env.get("MASTER_PORT", default_port))

    if "SLURM_PROCID" in env and "SLURM_NTASKS" in env:
        n = int(env["SLURM_NTASKS"])
        if n <= 1:
            return None
        nodelist = env.get("SLURM_STEP_NODELIST",
                           env.get("SLURM_JOB_NODELIST", ""))
        addr = env.get("MASTER_ADDR") or first_slurm_host(nodelist)
        if not addr:
            logger.warning("Slurm env detected but no nodelist/"
                           "MASTER_ADDR; skipping auto-discovery")
            return None
        return {"coordinator_address": f"{addr}:{port}",
                "num_processes": n,
                "process_id": int(env["SLURM_PROCID"]),
                "local_rank": int(env.get("SLURM_LOCALID", 0)),
                "source": "slurm"}

    if "OMPI_COMM_WORLD_RANK" in env and "OMPI_COMM_WORLD_SIZE" in env:
        n = int(env["OMPI_COMM_WORLD_SIZE"])
        if n <= 1:
            return None
        addr = env.get("MASTER_ADDR")
        if not addr and "AZ_BATCH_MASTER_NODE" in env:       # Azure ML
            host_port = env["AZ_BATCH_MASTER_NODE"].split(":")
            addr = host_port[0]
            if len(host_port) > 1 and "MASTER_PORT" not in env:
                port = int(host_port[1])
        if not addr and "AZ_BATCHAI_MPI_MASTER_NODE" in env:
            addr = env["AZ_BATCHAI_MPI_MASTER_NODE"]
        if not addr and "SM_HOSTS" in env:                   # AWS SageMaker
            try:
                addr = sorted(json.loads(env["SM_HOSTS"]))[0]
            except (ValueError, IndexError):
                addr = None
        if not addr:
            logger.warning(
                "OpenMPI env detected but no coordinator address "
                "(set MASTER_ADDR, or launch with a hostfile-aware "
                "runner); skipping auto-discovery")
            return None
        return {"coordinator_address": f"{addr}:{port}",
                "num_processes": n,
                "process_id": int(env["OMPI_COMM_WORLD_RANK"]),
                "local_rank": int(
                    env.get("OMPI_COMM_WORLD_LOCAL_RANK", 0)),
                "source": "openmpi"}

    if "PMI_RANK" in env and "PMI_SIZE" in env:              # MPICH / IMPI
        n = int(env["PMI_SIZE"])
        if n <= 1:
            return None
        addr = env.get("MASTER_ADDR") or env.get("I_MPI_HYDRA_HOST")
        if not addr:
            logger.warning("PMI env detected but no MASTER_ADDR; "
                           "skipping auto-discovery")
            return None
        return {"coordinator_address": f"{addr}:{port}",
                "num_processes": n,
                "process_id": int(env["PMI_RANK"]),
                "local_rank": int(env.get("MPI_LOCALRANKID", 0)),
                "source": "pmi"}

    if "RANK" in env and "WORLD_SIZE" in env and "MASTER_ADDR" in env:
        n = int(env["WORLD_SIZE"])
        if n <= 1:
            return None
        return {"coordinator_address": f"{env['MASTER_ADDR']}:{port}",
                "num_processes": n,
                "process_id": int(env["RANK"]),
                "local_rank": int(env.get("LOCAL_RANK", 0)),
                "source": "torchrun"}

    return None
