"""Multinode launch backends: PDSH / OpenMPI / MPICH / Slurm / MVAPICH.

Re-design of the reference ``launcher/multinode_runner.py`` (PDSHRunner
``:51``, OpenMPIRunner ``:120``, MPICHRunner ``:200``, SlurmRunner
``:357``, MVAPICHRunner ``:405``): each backend is a pure COMMAND
BUILDER — ``get_cmd`` returns the argv to exec — so every one is
testable without the scheduler installed.

TPU adaptation: a TPU pod host runs exactly ONE JAX process driving all
its local chips, so the reference's per-GPU process fan-out (sum of
hostfile slots) becomes one process per host; ``slots`` in the hostfile
is carried through as ``DSTPU_LOCAL_DEVICES`` for visibility control.
``jax.distributed.initialize`` consumes the coordinator env exported by
the runner (``DSTPU_COORDINATOR`` / ``DSTPU_NUM_PROCESSES`` /
``DSTPU_PROCESS_ID`` — per-process id comes from the backend's rank env
at runtime: ``PMI_RANK``, ``OMPI_COMM_WORLD_RANK``, ``SLURM_PROCID``).
"""
from __future__ import annotations

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional

PDSH_MAX_FAN_OUT = 1024


@dataclass
class LauncherArgs:
    """The subset of ``dstpu`` CLI args the runners consume (reference
    argparse namespace)."""

    user_script: str = ""
    user_args: List[str] = field(default_factory=list)
    hostfile: str = "/job/hostfile"
    include: str = ""
    exclude: str = ""
    num_nodes: int = -1
    launcher_args: str = ""
    master_addr: str = ""
    master_port: int = 29500
    no_python: bool = False
    module: bool = False
    slurm_comment: str = ""


class MultiNodeRunner(ABC):
    def __init__(self, args: LauncherArgs, resource_pool: Dict[str, int]):
        self.args = args
        self.resource_pool = resource_pool
        self.exports: Dict[str, str] = {}
        self.validate_args()

    @abstractmethod
    def backend_exists(self) -> bool:
        """Whether the backend binary is on PATH."""

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        """argv to exec on the launching host."""

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Runner", "").lower()

    def validate_args(self) -> None:
        pass

    # -- shared pieces --------------------------------------------------

    @property
    def process_count(self) -> int:
        # one JAX process per TPU host (see module docstring); the
        # reference sums per-host GPU slots here instead
        return len(self.resource_pool)

    def _python(self) -> List[str]:
        if self.args.no_python:
            return []
        exec_ = [sys.executable, "-u"]
        if self.args.module:
            exec_.append("-m")
        return exec_

    def _program(self) -> List[str]:
        return self._python() + [self.args.user_script] + \
            list(self.args.user_args)

    def _coordinator_env(self) -> Dict[str, str]:
        first = next(iter(self.resource_pool))
        addr = self.args.master_addr or first
        return {
            "DSTPU_COORDINATOR": f"{addr}:{self.args.master_port}",
            "DSTPU_NUM_PROCESSES": str(self.process_count),
        }


class PDSHRunner(MultiNodeRunner):
    """Reference ``PDSHRunner:51``: parallel ssh fan-out."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        env = dict(environment)
        env.update(self._coordinator_env())
        env.update(self.exports)
        env["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(self.resource_pool)
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in env.items() if k != "PDSH_RCMD_TYPE")
        # %n = pdsh's per-host index -> the process id
        remote = (f"cd {shlex.quote(os.getcwd())}; {exports}"
                  "export DSTPU_PROCESS_ID=%n; "
                  + " ".join(map(shlex.quote, self._program())))
        return (["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", hosts]
                + shlex.split(self.args.launcher_args) + [remote])


class OpenMPIRunner(MultiNodeRunner):
    """Reference ``OpenMPIRunner:120``."""

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def validate_args(self) -> None:
        if self.args.include or self.args.exclude:
            raise ValueError(
                "openmpi backend does not support include/exclude (filter "
                "the hostfile instead)")

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        launcher_args = shlex.split(self.args.launcher_args)
        btl_tcp = ["--mca", "btl_tcp_if_include", "eth0"]
        for i in range(len(launcher_args) - 1):
            if launcher_args[i] in ("-mca", "--mca") and \
                    launcher_args[i + 1] == "btl_tcp_if_include":
                btl_tcp = []
                break
        cmd = ["mpirun", "-n", str(self.process_count),
               "--npernode", "1",              # one process per TPU host
               "-hostfile", self.args.hostfile,
               "--mca", "btl", "^openib"] + btl_tcp + launcher_args
        for k, v in {**self._coordinator_env(), **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._program()


class MPICHRunner(MultiNodeRunner):
    """Reference ``MPICHRunner:200``."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        cmd = ["mpirun", "-n", str(self.process_count), "-ppn", "1",
               "-hostfile", self.args.hostfile] + \
            shlex.split(self.args.launcher_args)
        for k, v in {**self._coordinator_env(), **self.exports}.items():
            cmd += ["-genv", k, v]
        return cmd + self._program()


class SlurmRunner(MultiNodeRunner):
    """Reference ``SlurmRunner:357``."""

    def backend_exists(self) -> bool:
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        cmd = ["srun", "-n", str(self.process_count),
               "--ntasks-per-node=1"] + \
            shlex.split(self.args.launcher_args)
        if self.args.slurm_comment:
            cmd += ["--comment", self.args.slurm_comment]
        if self.args.include:
            cmd += ["--include", self.args.include]
        if self.args.exclude:
            cmd += ["--exclude", self.args.exclude]
        if self.args.num_nodes > 0:
            cmd += ["--nodes", str(self.args.num_nodes)]
        exports = "--export=ALL"
        for k, v in {**self._coordinator_env(), **self.exports}.items():
            exports += f",{k}={v}"
        return cmd + [exports] + self._program()


class MVAPICHRunner(MultiNodeRunner):
    """Reference ``MVAPICHRunner:405`` (mpirun_rsh)."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        cmd = ["mpirun_rsh", "-np", str(self.process_count),
               "-hostfile", self.args.hostfile] + \
            shlex.split(self.args.launcher_args)
        for k, v in {**self._coordinator_env(), **self.exports}.items():
            cmd.append(f"{k}={v}")
        return cmd + self._program()


class IMPIRunner(MultiNodeRunner):
    """Reference ``IMPIRunner:272`` (Intel MPI).

    Intel MPI takes per-rank env through colon-separated ``-n 1 -env``
    argument sets rather than a hostfile env broadcast; the TPU build
    keeps the reference's structure at one process per host (our
    process model) and disables IMPI's core pinning the way the
    reference does (``I_MPI_PIN 0``)."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def validate_args(self) -> None:
        if self.args.include or self.args.exclude:
            raise ValueError(
                "impi backend does not support include/exclude (filter "
                "the hostfile instead)")

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        cmd = ["mpirun", "-ppn", "1"] + shlex.split(self.args.launcher_args)
        for k, v in {**self._coordinator_env(), **self.exports}.items():
            cmd += ["-genv", k, str(v)]
        cmd += ["-genv", "I_MPI_PIN", "0"]
        cmd += ["-hosts", ",".join(self.resource_pool)]
        per_rank: List[str] = []
        for i in range(self.process_count):
            if per_rank:
                per_rank.append(":")
            per_rank += (["-n", "1", "-env", "DSTPU_PROCESS_ID", str(i)]
                         + self._program())
        return cmd + per_rank


RUNNERS = {
    "pdsh": PDSHRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "impi": IMPIRunner,
    "slurm": SlurmRunner,
    "mvapich": MVAPICHRunner,
}


def get_runner(launcher: str, args: LauncherArgs,
               resource_pool: Dict[str, int]) -> MultiNodeRunner:
    """Reference ``runner.py`` launcher dispatch."""
    try:
        cls = RUNNERS[launcher.lower()]
    except KeyError:
        raise ValueError(f"unknown launcher {launcher!r}; available: "
                         f"{sorted(RUNNERS)}")
    return cls(args, resource_pool)
