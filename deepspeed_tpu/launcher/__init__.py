from deepspeed_tpu.launcher.elastic_agent import (DSElasticAgent,
                                                  PreemptionError,
                                                  elastic_batch_config)

__all__ = ["DSElasticAgent", "PreemptionError", "elastic_batch_config"]
