"""``dstpu`` launcher CLI.

TPU-native counterpart of the reference launcher
(``deepspeed/launcher/runner.py:419 main`` + per-node ``launch.py``).  On
TPU pods each *host* runs exactly one JAX process that drives all of its
local chips, so the per-GPU process fan-out of the reference collapses to
one process per host:

- single host: exec the training script directly (all local chips visible);
- multi host: read a hostfile (reference format: ``hostname slots=N``), ssh
  to every host, export ``DSTPU_COORDINATOR`` / ``DSTPU_NUM_PROCESSES`` /
  ``DSTPU_PROCESS_ID``, and run the same script — the env that
  ``deepspeed_tpu.comm.init_distributed`` consumes for
  ``jax.distributed.initialize``.  (On GKE/Cloud-TPU the scheduler already
  provides this env and ``dstpu`` is unnecessary — documented divergence.)
"""
from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from typing import Dict, List

from deepspeed_tpu.utils.logging import logger

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path: str) -> Dict[str, int]:
    """Parse ``hostname slots=N`` lines (reference ``runner.py:213``)."""
    hosts: Dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if name in hosts:
                raise ValueError(f"duplicate host {name} in hostfile")
            hosts[name] = slots
    if not hosts:
        raise ValueError(f"no hosts found in hostfile {path}")
    return hosts


def filter_hosts(hosts: Dict[str, int], include: str = "", exclude: str = "") -> Dict[str, int]:
    """Apply ``--include``/``--exclude`` host filters (reference ``runner.py:293``;
    TPU hosts have no per-device slot filtering — whole hosts only)."""
    def parse_list(s: str) -> List[str]:
        return [h.split(":")[0] for h in s.split("@") if h]

    out = dict(hosts)
    if include:
        keep = parse_list(include)
        missing = [h for h in keep if h not in out]
        if missing:
            raise ValueError(f"--include hosts not in hostfile: {missing}")
        out = {h: out[h] for h in keep}
    if exclude:
        for h in parse_list(exclude):
            out.pop(h, None)
    if not out:
        raise ValueError("no hosts left after include/exclude filters")
    return out


def build_ssh_command(host: str, env: Dict[str, str], script_cmd: List[str],
                      ssh_port: int = 22) -> List[str]:
    exports = " ".join(f"export {k}={shlex.quote(v)};" for k, v in env.items())
    remote = f"cd {shlex.quote(os.getcwd())}; {exports} {' '.join(map(shlex.quote, script_cmd))}"
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(ssh_port), host, remote]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu", description="DeepSpeed-TPU multi-host launcher")
    parser.add_argument("--hostfile", type=str, default=None,
                        help="path to 'hostname slots=N' hostfile")
    parser.add_argument("--include", type=str, default="",
                        help="hosts to include, '@'-separated")
    parser.add_argument("--exclude", type=str, default="",
                        help="hosts to exclude, '@'-separated")
    parser.add_argument("--master_addr", type=str, default=None,
                        help="coordinator address (default: first host)")
    parser.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    script_cmd = [sys.executable, args.user_script] + args.user_args

    if args.hostfile is None:
        logger.info("dstpu: single-host launch")
        return subprocess.call(script_cmd)

    hosts = filter_hosts(parse_hostfile(args.hostfile), args.include, args.exclude)
    host_names = list(hosts.keys())
    coord = args.master_addr or host_names[0]
    n = len(host_names)
    logger.info(f"dstpu: launching on {n} hosts, coordinator {coord}:{args.master_port}")

    procs = []
    for idx, host in enumerate(host_names):
        env = {
            "DSTPU_COORDINATOR": f"{coord}:{args.master_port}",
            "DSTPU_NUM_PROCESSES": str(n),
            "DSTPU_PROCESS_ID": str(idx),
        }
        cmd = build_ssh_command(host, env, script_cmd, args.ssh_port)
        logger.info(f"dstpu: [{host}] {' '.join(cmd[:6])} ...")
        procs.append(subprocess.Popen(cmd))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
