"""Elastic runtime agent: resume-on-reslice supervision.

TPU-native re-design of the reference's ``DSElasticAgent``
(``elasticity/elastic_agent.py:32``, ``_invoke_run:127``): where the
reference subclasses torch-elastic's ``LocalElasticAgent`` to monitor
worker processes and re-rendezvous on membership change, the
single-controller JAX runtime supervises the TRAINING LOOP itself —
preemptible TPU pods lose/regain chips, and the agent:

1. polls device membership (``device_provider``) and catches runtime
   device failures (the XLA error a dead chip raises),
2. re-solves the elastic batch config for the new world size
   (:func:`deepspeed_tpu.elasticity.compute_elastic_config` — global
   batch stays constant, micro-batch x GAS reshuffle, so convergence is
   undisturbed: the reference contract),
3. rebuilds the mesh over the surviving devices and a fresh engine,
4. resumes from the newest complete sharded checkpoint (the store
   reshards across topologies on load — ``checkpoint/sharded.py``).

Graceful membership changes (scheduler notice) checkpoint first and lose
no steps; hard failures resume from the last periodic save, exactly the
reference's checkpoint-based recovery story.
"""
from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, Optional, Sequence

import jax

from deepspeed_tpu.resilience.distributed import CollectiveTimeout
from deepspeed_tpu.resilience.guards import SwapCorruptionError
from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.utils.logging import log_dist, logger


class PreemptionError(RuntimeError):
    """Raised (by harnesses or infrastructure hooks) to signal that the
    current slice is going away."""


def elastic_batch_config(ds_config: Dict, world_size: int) -> Dict:
    """Scheduler-side PREVIEW of the batch triple for ``world_size``
    (no-op when elasticity is absent/disabled).

    Job controllers call this out-of-band to size placements — the
    reference contract where the scheduler and runtime independently
    compute the same deterministic solve.  The AUTHORITATIVE solve is
    the engine's (config.py ``_apply_elasticity``), which additionally
    enforces the user-batch-key conflict check and the immutability
    contract; this helper intentionally skips those runtime-only
    validations."""
    ecfg = ds_config.get("elasticity", {})
    if not ecfg.get("enabled", False):
        return dict(ds_config)
    from deepspeed_tpu.elasticity import compute_elastic_config

    batch, _menu, micro = compute_elastic_config(
        ds_config, world_size=world_size, return_microbatch=True)
    # the batch triple is expressed in DATA-PARALLEL ranks: model
    # parallelism divides the world without multiplying the batch
    mp = max(int(ecfg.get("model_parallel_size", 1)), 1)
    assert world_size % mp == 0, (
        f"world_size {world_size} not divisible by model_parallel_size {mp}")
    dp = world_size // mp
    assert batch % (micro * dp) == 0, (
        f"elastic solve produced batch {batch} not divisible by "
        f"micro*dp = {micro}*{dp} — inconsistent triple")
    out = dict(ds_config)
    out["train_batch_size"] = int(batch)
    out["train_micro_batch_size_per_gpu"] = int(micro)
    out["gradient_accumulation_steps"] = int(batch // (micro * dp))
    return out


class DSElasticAgent:
    """Supervise an elastic training run across device-membership changes.

    Parameters
    ----------
    build_engine:
        ``(topology, ds_config) -> DeepSpeedEngine`` — rebuilt after every
        membership change (the mesh is baked into compiled programs).
    ds_config:
        DeepSpeed-style config dict; its ``elasticity`` section drives the
        batch re-solve.
    ckpt_dir:
        Sharded-checkpoint directory used for both periodic saves and
        resume.
    device_provider:
        ``() -> Sequence[jax.Device]`` — current healthy devices.  Default
        ``jax.devices()``.  Tests (and schedulers with advance notice)
        swap this to shrink/grow the slice.
    save_interval:
        Steps between periodic checkpoints (the hard-failure recovery
        granularity).
    max_restarts:
        Supervision budget; exceeded -> the last error re-raises.
        Default from the config's ``resilience.max_restarts``.
    backoff_base_s / backoff_cap_s:
        Jittered exponential backoff between HARD-failure restarts
        (device failures, rebuild failures) — graceful membership-notice
        restarts re-slice immediately.  Defaults from the config's
        ``resilience`` block.
    sleep_fn:
        The backoff clock; injectable so tests never really sleep.
    """

    def __init__(self, build_engine: Callable[[Any, Dict], Any],
                 ds_config: Dict, ckpt_dir: str,
                 device_provider: Optional[
                     Callable[[], Sequence[jax.Device]]] = None,
                 save_interval: int = 10,
                 max_restarts: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_cap_s: Optional[float] = None,
                 sleep_fn: Optional[Callable[[float], None]] = None):
        self.build_engine = build_engine
        self.ds_config = dict(ds_config)
        self.ckpt_dir = ckpt_dir
        self.device_provider = device_provider or jax.devices
        self.save_interval = int(save_interval)
        rcfg = self.ds_config.get("resilience") or {}
        self.max_restarts = int(max_restarts if max_restarts is not None
                                else rcfg.get("max_restarts", 10))
        self.backoff_base_s = float(
            backoff_base_s if backoff_base_s is not None
            else rcfg.get("backoff_base_s", 1.0))
        self.backoff_cap_s = float(
            backoff_cap_s if backoff_cap_s is not None
            else rcfg.get("backoff_cap_s", 60.0))
        self._sleep = sleep_fn or time.sleep
        self._rng = random.Random(int(self.ds_config.get("seed", 1234)))
        self.restarts = 0
        self.hard_failures = 0
        self.backoff_history: list = []
        self.restart_reasons: Dict[str, int] = {}
        self._last_world: Optional[int] = None

    def _note_restart(self, reason: str, **attrs) -> None:
        """Every restart decision leaves a control-plane record: a
        ``cat="control"`` trace event plus the
        ``dstpu_restarts_total{reason}`` counter — re-slices must be as
        auditable as the autotuner's knob moves."""
        self.restart_reasons[reason] = self.restart_reasons.get(reason, 0) + 1
        trace.event("elastic_restart", cat="control", reason=reason,
                    restart=self.restarts, budget=self.max_restarts,
                    **attrs)
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics
        if _metrics.enabled:
            _metrics.counter(
                "dstpu_restarts_total",
                "Elastic agent restarts by reason",
                labels=("reason",)).labels(reason=reason).inc()

    def _backoff(self) -> None:
        """Jittered exponential delay before retrying after a HARD
        failure — a dying pod must not hot-loop rebuild attempts
        against infrastructure that needs time to recover."""
        self.hard_failures += 1
        delay = min(self.backoff_cap_s,
                    self.backoff_base_s * (2.0 ** (self.hard_failures - 1)))
        delay *= 1.0 + 0.5 * self._rng.random()
        self.backoff_history.append(delay)
        if delay > 0:
            logger.warning(f"elastic agent: backing off {delay:.1f}s "
                           f"before restart (hard failure "
                           f"#{self.hard_failures})")
            self._sleep(delay)

    # -- helpers ----------------------------------------------------------

    def _make_engine(self, devices: Sequence[jax.Device]):
        import deepspeed_tpu.comm as dist

        world = len(devices)
        if self._last_world is not None and world != self._last_world:
            # topology CHANGED across a restart: the elastic solve keeps
            # the global batch constant while micro x GAS reshuffle, the
            # sharded store re-slices params/optimizer on load, and the
            # NVMe swapper re-buckets moments from the saved shard
            # records — emit the decision so operators can see the
            # re-slice, not just infer it from step timing
            solved = elastic_batch_config(self.ds_config, world)
            trace.event(
                "elastic_reslice", cat="control",
                old_world=self._last_world, new_world=world,
                batch=int(solved.get("train_batch_size", 0) or 0),
                micro=int(solved.get(
                    "train_micro_batch_size_per_gpu", 0) or 0),
                gas=int(solved.get(
                    "gradient_accumulation_steps", 0) or 0))
            log_dist(f"elastic agent: re-slicing world "
                     f"{self._last_world} -> {world}", ranks=[0])
        self._last_world = world
        # the config system re-solves the elastic batch triple itself for
        # the topology's dp world size (config.py _apply_elasticity) — the
        # agent only rebuilds the mesh and hands the config through
        cfg = dict(self.ds_config)
        topo = dist.initialize_mesh(dp=world, devices=list(devices))
        engine = self.build_engine(topo, cfg)
        tag, _ = engine.load_checkpoint(self.ckpt_dir)
        if tag:
            log_dist(f"elastic agent: resumed {tag} at step "
                     f"{engine.global_steps} on {world} devices", ranks=[0])
        else:
            log_dist(f"elastic agent: fresh start on {world} devices",
                     ranks=[0])
        return engine, cfg

    # -- the supervision loop ---------------------------------------------

    def run(self, data_fn: Callable[[int, int], Any], num_steps: int):
        """Train to ``num_steps`` across membership changes.

        ``data_fn(step, global_batch_size) -> batch`` must be
        deterministic in ``step`` so a resumed run replays the same data
        stream regardless of the device count (the elastic solver keeps
        the global batch size constant across the menu).

        Returns the final engine (for evaluation / state extraction).
        """
        from deepspeed_tpu.elasticity import (
            ElasticityIncompatibleWorldSize, validate_world_size)

        last_err: Optional[BaseException] = None
        while self.restarts <= self.max_restarts:
            devices = list(self.device_provider())
            if not devices:
                raise RuntimeError("elastic agent: no healthy devices")
            try:
                # fail FAST on an unschedulable world instead of burning
                # the restart budget against mesh-construction errors;
                # the exception lists the nearest valid worlds so the
                # scheduler can converge
                validate_world_size(self.ds_config, len(devices))
            except ElasticityIncompatibleWorldSize as e:
                trace.event("elastic_world_rejected", cat="control",
                            world=len(devices),
                            nearest=list(getattr(e, "nearest", [])))
                raise
            try:
                engine, cfg = self._make_engine(devices)
            except (PreemptionError, jax.errors.JaxRuntimeError,
                    CollectiveTimeout, SwapCorruptionError) as e:
                # losing the slice DURING rebuild/resume is the likeliest
                # failure on a degraded pod — it must consume a restart,
                # not crash the supervisor
                last_err = e
                self.restarts += 1
                self._note_restart("rebuild_failure", error=repr(e))
                logger.warning(
                    f"elastic agent: engine rebuild failed, restart "
                    f"{self.restarts}/{self.max_restarts} ({e})")
                if self.restarts <= self.max_restarts:
                    self._backoff()
                continue
            step = int(engine.global_steps)
            # read the SOLVED batch size off the engine (elastic mode
            # overrides whatever the dict said)
            gbs = int(engine.config.train_batch_size)
            try:
                while step < num_steps:
                    if list(self.device_provider()) != devices:
                        # scheduler notice: save, then re-slice losing
                        # nothing (reference _invoke_run membership check)
                        log_dist("elastic agent: membership change "
                                 "detected; checkpointing for re-slice",
                                 ranks=[0])
                        engine.save_checkpoint(self.ckpt_dir)
                        engine.wait_checkpoint()
                        raise PreemptionError("membership changed")
                    engine.train_batch(batch=data_fn(step, gbs))
                    step = int(engine.global_steps)
                    if step % self.save_interval == 0 or step == num_steps:
                        engine.save_checkpoint(self.ckpt_dir)
                engine.wait_checkpoint()
                return engine
            except PreemptionError as e:
                last_err = e
                self.restarts += 1
                self._note_restart("membership_change", step=step,
                                   world=len(devices))
                logger.warning(
                    f"elastic agent: restart {self.restarts}/"
                    f"{self.max_restarts} ({e})")
            except (jax.errors.JaxRuntimeError, CollectiveTimeout,
                    SwapCorruptionError) as e:
                # hard failure: a dead chip's runtime error, a
                # collective watchdog timeout (peer rank gone / wedged
                # transport), or persistent silent data corruption in
                # the NVMe swap path (file quarantined; the engine
                # already attempted an emergency checkpoint).  Resume
                # from the last periodic save (load_checkpoint verifies
                # and falls back to the newest VERIFIED tag if the last
                # save was torn)
                last_err = e
                self.restarts += 1
                self._note_restart("hard_failure", step=step,
                                   error=repr(e))
                logger.warning(
                    f"elastic agent: hard failure, restart "
                    f"{self.restarts}/{self.max_restarts} ({e})")
                if self.restarts <= self.max_restarts:
                    self._backoff()
        # budget exhausted: leave a black box before dying — the ring
        # holds the restart timeline the post-mortem needs
        err = RuntimeError(
            f"elastic agent: exceeded {self.max_restarts} restarts")
        from deepspeed_tpu.telemetry import flight
        flight.dump_on_fault(
            "restart_budget_exhausted", last_err or err,
            extra={"restarts": self.restarts,
                   "hard_failures": self.hard_failures,
                   "max_restarts": self.max_restarts,
                   "restart_reasons": dict(self.restart_reasons),
                   "backoff_history": [round(b, 3)
                                       for b in self.backoff_history],
                   "last_world": self._last_world})
        raise err from last_err
