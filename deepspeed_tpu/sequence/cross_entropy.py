"""Sequence/vocab-parallel cross entropy.

Reference: ``deepspeed/sequence/cross_entropy.py``
(``vocab_sequence_parallel_cross_entropy``) — cross entropy where the logits
are sharded over both the sequence axis (Ulysses) and the vocab axis
(Megatron TP).  On TPU the fused, sharding-aware form is a shard_map over
both axes: each device reduces its local vocab shard (max + masked gather +
sum-exp), psums the three partials over ``tensor``, computes local token
losses, and the mean over ``seq``/batch is a final psum — no device ever
materializes the full [B, S, V] log-softmax.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.parallel.topology import SEQ_AXIS, TENSOR_AXIS


def vocab_sequence_parallel_cross_entropy(
        logits: jax.Array, targets: jax.Array,
        mesh: Optional[Mesh] = None,
        seq_axis: str = SEQ_AXIS,
        vocab_axis: str = TENSOR_AXIS) -> jax.Array:
    """Mean next-token CE.  logits: [B, S, V] sharded (seq on S, optionally
    tensor on V); targets: [B, S] int sharded on S.  Returns a replicated
    scalar."""
    from deepspeed_tpu.sequence.layer import resolve_mesh

    mesh = resolve_mesh(mesh, seq_axis)
    tp = mesh.shape[vocab_axis]
    sp = mesh.shape[seq_axis]

    def body(logits, targets):
        lg = logits.astype(jnp.float32)   # [Bl, Sl, Vl]
        v_local = lg.shape[-1]
        v_start = jax.lax.axis_index(vocab_axis) * v_local if tp > 1 else 0

        local_max = jnp.max(lg, axis=-1)
        gmax = jax.lax.pmax(local_max, vocab_axis) if tp > 1 else local_max
        e = jnp.exp(lg - gmax[..., None])
        denom = jnp.sum(e, axis=-1)
        if tp > 1:
            denom = jax.lax.psum(denom, vocab_axis)

        # logit of the target id, if it falls in our vocab shard
        local_ids = targets - v_start
        in_shard = (local_ids >= 0) & (local_ids < v_local)
        picked = jnp.take_along_axis(
            lg, jnp.clip(local_ids, 0, v_local - 1)[..., None], axis=-1
        )[..., 0]
        picked = jnp.where(in_shard, picked, 0.0)
        if tp > 1:
            picked = jax.lax.psum(picked, vocab_axis)

        tok_loss = jnp.log(denom) + gmax - picked
        loss = jnp.mean(tok_loss)
        if sp > 1:
            loss = jax.lax.pmean(loss, seq_axis)
        return loss

    in_specs = (P(None, seq_axis, vocab_axis if tp > 1 else None),
                P(None, seq_axis))
    # both axes stay manual even at size 1 — in_specs may only name manual
    # axes, and size-1 manual axes are legal
    return _shard_map_compat(body, mesh=mesh, in_specs=in_specs, out_specs=P(),
                         axis_names={seq_axis, vocab_axis},
                         check_vma=False)(logits, targets)
