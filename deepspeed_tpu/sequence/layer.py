"""Ulysses sequence parallelism.

TPU-native re-design of DeepSpeed-Ulysses (``deepspeed/sequence/layer.py``:
``_SeqAllToAll:257``, ``DistributedAttention:311``, ``single_all_to_all:221``).
The mechanism is identical — all-to-all that scatters heads and gathers
sequence before attention, and the inverse after — but expressed as
``jax.shard_map`` manual over the ``seq`` mesh axis with
``jax.lax.all_to_all`` riding ICI, while every other axis (data/tensor/...)
stays under automatic GSPMD partitioning (``axis_names={"seq"}``).

Inside the shard_map body each device holds the full sequence for its head
group, so the local attention can be the Pallas flash kernel (Pallas composes
with shard_map, not with GSPMD auto-sharding).

GQA: when kv heads don't divide the seq group, the default
``uneven_kv="once"`` path moves each KV head through the all-to-all ONCE
(reference ``uneven_heads_all2all:111``): the pre-a2a tensor carries, per
destination device, exactly the kv heads that device's query-head block
consumes (plus at most one boundary duplicate), and the expansion to the
query-head count happens AFTER the scatter — so a2a bytes scale with
``Hkv``, not ``H``.  ``uneven_kv="replicate"`` keeps the round-5
behavior (expand to H heads pre-a2a — same math, ``H/Hkv`` more KV bytes
on the wire) and is the parity reference.
:func:`ulysses_comm_bytes` reports the per-device wire bytes of both
layouts for a given shape.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.parallel.topology import SEQ_AXIS
from deepspeed_tpu.ops.flash_attention import flash_attention


def _default_attn(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal)


def resolve_mesh(mesh: Optional[Mesh], axis: str) -> Mesh:
    """Mesh to shard_map over: explicit arg > ambient jax mesh context >
    the process-global topology (deepspeed_tpu.comm)."""
    if mesh is not None:
        return mesh
    from deepspeed_tpu.utils.compat import get_abstract_mesh

    am = get_abstract_mesh()
    if am is not None and axis in (am.axis_names or ()):
        return am
    import deepspeed_tpu.comm as dist

    return dist.get_topology().mesh


def _uneven_kv_plan(H: int, Hkv: int, sp: int):
    """Static routing tables for the send-each-kv-head-once all-to-all.

    Returns ``(idx [sp*m], lmap [sp, H/sp], m)``: the pre-a2a gather
    puts, for each destination device ``r``, the ``m`` kv heads its
    contiguous query-head block ``[r*H/sp, (r+1)*H/sp)`` consumes
    (boundary-padded by repeating the last), and ``lmap[r]`` maps each
    local query head to its kv head's position within that group after
    the scatter."""
    g = H // Hkv
    Hl = H // sp
    per_dev = []
    m = 0
    for r in range(sp):
        lo = (r * Hl) // g
        hi = ((r + 1) * Hl - 1) // g
        per_dev.append((lo, hi))
        m = max(m, hi - lo + 1)
    idx = []
    lmap = np.zeros((sp, Hl), np.int32)
    for r, (lo, hi) in enumerate(per_dev):
        heads = list(range(lo, hi + 1))
        idx.extend(heads + [hi] * (m - len(heads)))
        for j in range(Hl):
            lmap[r, j] = (r * Hl + j) // g - lo
    return np.asarray(idx, np.int32), lmap, m


def ulysses_comm_bytes(q_shape, kv_shape, sp: int, itemsize: int = 2
                       ) -> dict:
    """Per-device wire bytes of one Ulysses attention call (both
    directions of the head scatter/gather), for the replicating GQA
    layout vs the send-once layout — the measured-bytes record the
    VERDICT r5 uneven-head item asks for.  ``q_shape``/``kv_shape`` are
    the GLOBAL [B, H, S, D] / [B, Hkv, S, D] shapes."""
    B, H, S, D = q_shape
    Hkv = kv_shape[1]
    unit = B * S * D * itemsize * (sp - 1) // sp    # one head over the wire
    q_bytes = (H // sp) * unit                      # scatter q
    out_bytes = (H // sp) * unit                    # gather the output
    if Hkv % sp == 0:
        kv_even = 2 * (Hkv // sp) * unit
        return {"q_bytes": q_bytes, "out_bytes": out_bytes,
                "kv_bytes_even": kv_even,
                "total_even": q_bytes + out_bytes + kv_even}
    _, _, m = _uneven_kv_plan(H, Hkv, sp)
    kv_rep = 2 * (H // sp) * unit                   # kv expanded to H heads
    kv_once = 2 * m * unit                          # m ~= ceil(Hkv/sp) + 1
    return {"q_bytes": q_bytes, "out_bytes": out_bytes,
            "kv_bytes_replicate": kv_rep, "kv_bytes_once": kv_once,
            "kv_once_ratio": round(kv_once / kv_rep, 4),
            "total_replicate": q_bytes + out_bytes + kv_rep,
            "total_once": q_bytes + out_bytes + kv_once}


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      mesh: Optional[Mesh] = None,
                      axis: str = SEQ_AXIS,
                      causal: bool = True,
                      attn_fn: Optional[Callable] = None,
                      uneven_kv: str = "once") -> jax.Array:
    """Sequence-parallel attention.  q: [B, H, S, D], k/v: [B, Hkv, S, D]
    global shapes with S sharded over ``axis``; returns [B, H, S, D] sharded
    the same way.

    all-to-all #1: [B, H, S/sp, D] -> [B, H/sp, S, D]  (scatter heads)
    local attention over the full sequence
    all-to-all #2: inverse                             (gather heads)

    ``uneven_kv`` (only consulted when ``Hkv % sp != 0``): ``"once"``
    routes each kv head through the a2a once and expands to the query
    head count after the scatter (a2a bytes at the kv-head rate);
    ``"replicate"`` expands to H heads before the a2a (the round-5
    layout — same math, the bit-parity reference)."""
    if attn_fn is None:
        attn_fn = _default_attn
    mesh = resolve_mesh(mesh, axis)
    sp = mesh.shape[axis]
    if sp == 1:
        return attn_fn(q, k, v, causal)

    H, Hkv = q.shape[1], k.shape[1]
    assert H % sp == 0, f"q heads {H} must divide seq-parallel size {sp}"
    assert uneven_kv in ("once", "replicate"), uneven_kv
    uneven = Hkv % sp != 0
    if uneven and uneven_kv == "replicate":
        groups = H // Hkv
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
        uneven = False
    if uneven:
        assert H % Hkv == 0, f"GQA needs Hkv {Hkv} to divide H {H}"
        idx_np, lmap_np, _ = _uneven_kv_plan(H, Hkv, sp)

    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def body(q, k, v):
        # local: [B, H, S/sp, D] -> heads scattered, seq gathered
        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        out = attn_fn(ql, kl, vl, causal)
        return gather_heads(out)

    def body_uneven(q, k, v):
        ql = scatter_heads(q)
        # pre-a2a gather: per DESTINATION device, the kv heads its query
        # block consumes — each kv head crosses the wire once per
        # consumer instead of group-size times
        idx = jnp.asarray(idx_np)
        kl = scatter_heads(jnp.take(k, idx, axis=1))   # [B, m, S, D]
        vl = scatter_heads(jnp.take(v, idx, axis=1))
        r = jax.lax.axis_index(axis)
        lm = jnp.take(jnp.asarray(lmap_np), r, axis=0)  # [H/sp]
        out = attn_fn(ql, jnp.take(kl, lm, axis=1),
                      jnp.take(vl, lm, axis=1), causal)
        return gather_heads(out)

    spec = P(None, None, axis, None)
    return _shard_map_compat(body_uneven if uneven else body, mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)


class DistributedAttention:
    """Reference ``DistributedAttention`` (``sequence/layer.py:311``) shape:
    a callable wrapping any local attention with the Ulysses all-to-alls.

    ``scatter_idx``/``gather_idx`` are fixed to the head/seq dims of the
    [B, H, S, D] layout (the reference's defaults express the same choice for
    its [s, b, h] layout).
    """

    def __init__(self, local_attention: Optional[Callable] = None,
                 mesh: Optional[Mesh] = None, axis: str = SEQ_AXIS,
                 uneven_kv: str = "once"):
        self.local_attention = local_attention
        self.mesh = mesh
        self.axis = axis
        self.uneven_kv = uneven_kv

    def __call__(self, query, key, value, causal: bool = True, **kwargs):
        return ulysses_attention(query, key, value, mesh=self.mesh,
                                 axis=self.axis, causal=causal,
                                 attn_fn=self.local_attention,
                                 uneven_kv=self.uneven_kv)
