"""Ulysses sequence parallelism.

TPU-native re-design of DeepSpeed-Ulysses (``deepspeed/sequence/layer.py``:
``_SeqAllToAll:257``, ``DistributedAttention:311``, ``single_all_to_all:221``).
The mechanism is identical — all-to-all that scatters heads and gathers
sequence before attention, and the inverse after — but expressed as
``jax.shard_map`` manual over the ``seq`` mesh axis with
``jax.lax.all_to_all`` riding ICI, while every other axis (data/tensor/...)
stays under automatic GSPMD partitioning (``axis_names={"seq"}``).

Inside the shard_map body each device holds the full sequence for its head
group, so the local attention can be the Pallas flash kernel (Pallas composes
with shard_map, not with GSPMD auto-sharding).

GQA: when kv heads don't divide the seq group, kv is expanded to the query
head count first (the reference handles this case with
``uneven_heads_all2all:111``; head replication is the simpler TPU-friendly
equivalent — same math, denser layout).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.parallel.topology import SEQ_AXIS
from deepspeed_tpu.ops.flash_attention import flash_attention


def _default_attn(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal)


def resolve_mesh(mesh: Optional[Mesh], axis: str) -> Mesh:
    """Mesh to shard_map over: explicit arg > ambient jax mesh context >
    the process-global topology (deepspeed_tpu.comm)."""
    if mesh is not None:
        return mesh
    from deepspeed_tpu.utils.compat import get_abstract_mesh

    am = get_abstract_mesh()
    if am is not None and axis in (am.axis_names or ()):
        return am
    import deepspeed_tpu.comm as dist

    return dist.get_topology().mesh


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      mesh: Optional[Mesh] = None,
                      axis: str = SEQ_AXIS,
                      causal: bool = True,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Sequence-parallel attention.  q: [B, H, S, D], k/v: [B, Hkv, S, D]
    global shapes with S sharded over ``axis``; returns [B, H, S, D] sharded
    the same way.

    all-to-all #1: [B, H, S/sp, D] -> [B, H/sp, S, D]  (scatter heads)
    local attention over the full sequence
    all-to-all #2: inverse                             (gather heads)
    """
    if attn_fn is None:
        attn_fn = _default_attn
    mesh = resolve_mesh(mesh, axis)
    sp = mesh.shape[axis]
    if sp == 1:
        return attn_fn(q, k, v, causal)

    H, Hkv = q.shape[1], k.shape[1]
    assert H % sp == 0, f"q heads {H} must divide seq-parallel size {sp}"
    if Hkv % sp != 0:
        groups = H // Hkv
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)

    def body(q, k, v):
        # local: [B, H, S/sp, D] -> heads scattered, seq gathered
        def scatter_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def gather_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        out = attn_fn(ql, kl, vl, causal)
        return gather_heads(out)

    spec = P(None, None, axis, None)
    return _shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)


class DistributedAttention:
    """Reference ``DistributedAttention`` (``sequence/layer.py:311``) shape:
    a callable wrapping any local attention with the Ulysses all-to-alls.

    ``scatter_idx``/``gather_idx`` are fixed to the head/seq dims of the
    [B, H, S, D] layout (the reference's defaults express the same choice for
    its [s, b, h] layout).
    """

    def __init__(self, local_attention: Optional[Callable] = None,
                 mesh: Optional[Mesh] = None, axis: str = SEQ_AXIS):
        self.local_attention = local_attention
        self.mesh = mesh
        self.axis = axis

    def __call__(self, query, key, value, causal: bool = True, **kwargs):
        return ulysses_attention(query, key, value, mesh=self.mesh,
                                 axis=self.axis, causal=causal,
                                 attn_fn=self.local_attention)
