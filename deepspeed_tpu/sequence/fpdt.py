"""FPDT: Fully Pipelined Distributed Transformer long-context attention.

TPU-native re-design of the reference FPDT layer
(``sequence/fpdt_layer.py``: ``FPDT_InputConstruct:79`` load-balanced
chunking, ``_FPDTGPUOffloadingAttentionImpl_:514`` chunked attention with
double-buffered host offload, ``FPDT_Attention:971``) — million-token
sequences on top of Ulysses SP by processing the sequence in CHUNKS:

- ONE head-scatter all-to-all brings each rank the full sequence for its
  head group (the Ulysses move, ``sequence/layer.py``);
- the K/V (and Q) chunk stacks are parked in HOST memory
  (``pinned_host``) when ``offload=True`` — HBM holds only the current
  chunk pair plus online-softmax accumulators, so max sequence length is
  bounded by host RAM, not HBM (the reference's double-buffer streaming;
  XLA overlaps the H2D with compute the same way);
- each query chunk attends to its causal prefix of KV chunks via the
  flash kernel per pair (diagonal pair causal, earlier pairs full), and
  chunk partials merge by their log-sum-exp weights;
- :func:`fpdt_balanced_indices` stripes chunks round-robin across SP
  ranks so causal work is even (the reference's input construct).

Everything is plain differentiable JAX — the backward re-runs chunk
pairs under ``jax.checkpoint`` instead of a hand-written autograd.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.ops.flash_attention import _blockwise_fwd
from deepspeed_tpu.parallel.topology import SEQ_AXIS
from deepspeed_tpu.sequence.layer import resolve_mesh
from deepspeed_tpu.utils.sharding import memory_space


# ---------------------------------------------------------------------------
# load-balanced input construction (reference FPDT_InputConstruct:79)
# ---------------------------------------------------------------------------

def fpdt_balanced_indices(global_seq_len: int, chunk_size: int,
                          sp_size: int) -> np.ndarray:
    """Token permutation striping chunks round-robin across ranks: chunk c
    goes to rank ``c % sp`` — rank r's causal prefix work is then spread
    over the whole sequence instead of concentrating on high ranks.
    Returns [global_seq_len] gather indices; rank r's tokens are the slice
    ``[r * L : (r+1) * L]`` of the permuted sequence (L = global/sp)."""
    assert global_seq_len % chunk_size == 0
    total = global_seq_len // chunk_size
    assert total % sp_size == 0, (
        f"chunk count {total} must divide sp size {sp_size}")
    per_rank = total // sp_size
    # chunk index owned by (rank, slot): slot-major striping
    chunk_of = np.arange(total).reshape(per_rank, sp_size).T  # [sp, per]
    token_idx = (chunk_of[..., None] * chunk_size +
                 np.arange(chunk_size)).reshape(-1)
    return token_idx


def fpdt_input_construct(batch: dict, global_seq_len: int, chunk_size: int,
                         sp_size: int, sp_rank: Optional[int] = None
                         ) -> dict:
    """Permute [B, S] token-like arrays into the load-balanced layout;
    with ``sp_rank`` given, return only that rank's slice (reference
    ``FPDT_InputConstruct.generate``)."""
    idx = fpdt_balanced_indices(global_seq_len, chunk_size, sp_size)
    if sp_rank is not None:
        local = global_seq_len // sp_size
        idx = idx[sp_rank * local:(sp_rank + 1) * local]

    def pick(x):
        x = np.asarray(x)
        return x[:, idx] if x.ndim >= 2 and x.shape[1] == global_seq_len \
            else x

    return {k: pick(v) for k, v in batch.items()}


# ---------------------------------------------------------------------------
# chunked attention with lse merging
# ---------------------------------------------------------------------------

def _pair_attention(qc, kc, vc, *, causal_pair: bool, sm_scale: float,
                    block: int):
    """(out, lse) for one (q-chunk, kv-chunk) pair via the blockwise
    flash forward — O(chunk * block) live memory, never chunk^2."""
    return _blockwise_fwd(qc, kc, vc, sm_scale=sm_scale,
                          causal=causal_pair, block_q=block, block_k=block)


def _merge_chunks(outs, lses):
    """Merge per-KV-chunk partials [n, B, H, S, D] / [n, B, H, S] by lse
    weights (masked pairs carry lse = -inf and weight 0)."""
    m = jnp.max(lses, axis=0)                          # [B, H, S]
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    w = jnp.exp(lses - m[None])                        # [n, B, H, S]
    denom = jnp.maximum(w.sum(axis=0), 1e-30)
    out = (outs * w[..., None].astype(outs.dtype)).sum(axis=0)
    return (out / denom[..., None].astype(out.dtype)).astype(outs.dtype)


def fpdt_chunked_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           chunk_size: int, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           block: int = 512,
                           fetch=lambda x: x, park=lambda x: x
                           ) -> jax.Array:
    """Chunked causal attention over a FULL local view q/k/v [B, H, S, D].

    ``park`` places the chunk stacks (host memory under offload);
    ``fetch`` brings one chunk back to device.  The q-chunk loop is a
    ``lax.scan`` whose body is rematerialized — live memory is one chunk
    pair + accumulators regardless of S.
    """
    B, H, S, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(D)
    n = S // chunk_size
    if n <= 1:
        out, _ = _pair_attention(q, k, v, causal_pair=causal,
                                 sm_scale=sm_scale, block=block)
        return out
    assert S % chunk_size == 0, (S, chunk_size)

    def stack(x):
        return park(x.reshape(B, H, n, chunk_size, D)
                    .transpose(2, 0, 1, 3, 4))

    qs, ks, vs = stack(q), stack(k), stack(v)

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def q_chunk_step(_, i):
        qc = fetch(qs[i])

        def kv_step(carry, j):
            kc, vc = fetch(ks[j]), fetch(vs[j])

            def full_pair(_):
                return _pair_attention(qc, kc, vc, causal_pair=False,
                                       sm_scale=sm_scale, block=block)

            def diag_pair(_):
                return _pair_attention(qc, kc, vc, causal_pair=True,
                                       sm_scale=sm_scale, block=block)

            def dead_pair(_):
                return (jnp.zeros(qc.shape, qc.dtype),
                        jnp.full(qc.shape[:-1], -jnp.inf, jnp.float32))

            def live_pair(_):
                return jax.lax.cond(j == i, diag_pair, full_pair,
                                    operand=None)

            if causal:
                # past-diagonal pairs skip the compute entirely (the
                # reference's dynamic chunk loop; cond keeps shapes static)
                o_pair, lse_pair = jax.lax.cond(j <= i, live_pair,
                                                dead_pair, operand=None)
            else:
                o_pair, lse_pair = full_pair(None)
            return carry, (o_pair, lse_pair)

        _, (outs, lses) = jax.lax.scan(kv_step, None, jnp.arange(n))
        return None, _merge_chunks(outs, lses)

    _, out_chunks = jax.lax.scan(q_chunk_step, None, jnp.arange(n))
    # [n, B, H, chunk, D] -> [B, H, S, D]
    return out_chunks.transpose(1, 2, 0, 3, 4).reshape(B, H, S, D)


def _host_handles(mesh: Optional[Mesh]):
    """(park, fetch) pair moving chunk stacks to pinned host memory and
    chunks back, in-graph and sharding-preserving
    (``TransferToMemoryKind`` — the engine's ZeRO-Offload mechanism);
    identity when the backend has no host placement (CPU)."""
    devices = (mesh.devices.flat if mesh is not None else jax.devices())
    if list(devices)[0].platform == "cpu":
        return (lambda x: x), (lambda x: x)

    def park(x):
        return jax.device_put(
            x, memory_space("pinned_host"))

    def fetch(x):
        return jax.device_put(
            x, memory_space("device"))

    return park, fetch


def fpdt_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   chunk_size: int, mesh: Optional[Mesh] = None,
                   axis: str = SEQ_AXIS, causal: bool = True,
                   offload: bool = True, block: int = 512) -> jax.Array:
    """Ulysses + chunked/offloaded attention (the full FPDT move).

    q: [B, H, S, D], k/v: [B, Hkv, S, D] with S sharded over ``axis``;
    output sharded the same.  ``chunk_size`` is the GLOBAL chunk length
    (reference default 65536).  With sp == 1 this degrades to single-node
    chunked attention (still chunked + offloaded — FPDT's single-GPU
    mode).
    """
    mesh = resolve_mesh(mesh, axis)
    sp = mesh.shape[axis] if axis in mesh.shape else 1
    H, Hkv = q.shape[1], k.shape[1]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    park, fetch = (_host_handles(mesh) if offload
                   else ((lambda x: x), (lambda x: x)))

    if sp == 1:
        return fpdt_chunked_attention(q, k, v, chunk_size, causal=causal,
                                      block=block, fetch=fetch, park=park)

    assert H % sp == 0

    def body(q, k, v):
        def scatter_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def gather_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        out = fpdt_chunked_attention(ql, kl, vl, chunk_size, causal=causal,
                                     block=block, fetch=fetch, park=park)
        return gather_heads(out)

    spec = P(None, None, axis, None)
    return _shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)
