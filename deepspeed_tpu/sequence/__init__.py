from deepspeed_tpu.sequence.layer import (DistributedAttention,
                                          ulysses_attention,
                                          ulysses_comm_bytes)
from deepspeed_tpu.sequence.ring import ring_attention
from deepspeed_tpu.sequence.fpdt import (fpdt_attention,
                                         fpdt_chunked_attention,
                                         fpdt_input_construct)
from deepspeed_tpu.sequence.cross_entropy import \
    vocab_sequence_parallel_cross_entropy

__all__ = ["DistributedAttention", "ulysses_attention",
           "ulysses_comm_bytes", "ring_attention",
           "fpdt_attention", "fpdt_chunked_attention",
           "fpdt_input_construct", "vocab_sequence_parallel_cross_entropy"]
