from deepspeed_tpu.sequence.layer import (DistributedAttention,
                                          ulysses_attention)
from deepspeed_tpu.sequence.ring import ring_attention
from deepspeed_tpu.sequence.cross_entropy import \
    vocab_sequence_parallel_cross_entropy

__all__ = ["DistributedAttention", "ulysses_attention", "ring_attention",
           "vocab_sequence_parallel_cross_entropy"]
