"""Ring attention over the ``seq`` mesh axis.

The reference has no ring attention (SURVEY §2.3: long-sequence scaling is
Ulysses + FPDT chunking); this is the planned TPU-native extension — ring
attention maps directly onto ICI ``ppermute``: each device keeps its query
block resident and the K/V blocks rotate around the ring, one hop per step,
with online-softmax accumulation (blockwise attention a la
Liu et al., Ring Attention, 2023).

Compared to Ulysses (2 all-to-alls, needs heads % sp == 0), the ring scales
to any head count and overlaps the K/V hop with the block computation
(XLA schedules the collective-permute concurrently with the matmuls), at the
cost of sp sequential steps.

Differentiable by construction: the body is jnp + ``ppermute`` inside
``lax.scan`` (each step rematerialized via ``jax.checkpoint`` to keep
activation memory at one block).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.parallel.topology import SEQ_AXIS
from deepspeed_tpu.ops.flash_attention import DEFAULT_MASK_VALUE


def _block_attn_update(carry, q, k, v, mask, sm_scale):
    """One online-softmax accumulation step (GQA-grouped layout).
    q: [B, Hkv, G, Sq, D]; k/v: [B, Hkv, Sk, D]; mask: [Sq, Sk] bool.
    K/V stay at Hkv heads — the whole point of GQA is that the ring hops
    and the resident blocks carry only Hkv*D bytes per position."""
    acc, m, l = carry
    s = jnp.einsum("bhgqd,bhkd->bhgqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(mask[None, None, None], s, DEFAULT_MASK_VALUE)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return acc_new, m_new, l_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Optional[Mesh] = None,
                   axis: str = SEQ_AXIS,
                   causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Ring attention.  q: [B, H, S, D], k/v: [B, Hkv, S, D] global shapes
    with S sharded over ``axis``; output [B, H, S, D] sharded the same way.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    from deepspeed_tpu.sequence.layer import resolve_mesh

    mesh = resolve_mesh(mesh, axis)
    sp = mesh.shape[axis]
    groups = q.shape[1] // k.shape[1]
    if sp == 1:
        from deepspeed_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    def body(q, k, v):
        # locals: q [B, H, S/sp, D]; k/v [B, Hkv, S/sp, D]
        B, H, Sl, D = q.shape
        Hkv = k.shape[1]
        q = q.reshape(B, Hkv, groups, Sl, D)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % sp) for i in range(sp)]  # send k/v to the right
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)

        def step(carry, j):
            acc, m, l, kj, vj = carry
            # K/V block j hops originated from device (my - j) mod sp
            src = (my - j) % sp
            if causal:
                # src < my: full block; src == my: causal diag; src > my: skip
                mask = jnp.where(
                    src == my, k_pos <= q_pos,
                    jnp.broadcast_to(src < my, (Sl, Sl)))
            else:
                mask = jnp.ones((Sl, Sl), dtype=bool)
            acc, m, l = _block_attn_update((acc, m, l), q, kj, vj, mask,
                                           sm_scale)
            kj = jax.lax.ppermute(kj, axis, perm)
            vj = jax.lax.ppermute(vj, axis, perm)
            return (acc, m, l, kj, vj), None

        init = (jnp.zeros((B, Hkv, groups, Sl, D), jnp.float32),
                jnp.full((B, Hkv, groups, Sl), -jnp.inf, jnp.float32),
                jnp.zeros((B, Hkv, groups, Sl), jnp.float32),
                k, v)
        (acc, m, l, _, _), _ = jax.lax.scan(
            jax.checkpoint(step), init, jnp.arange(sp))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return out.reshape(B, H, Sl, D).astype(q.dtype)

    spec = P(None, None, axis, None)
    return _shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)
