from deepspeed_tpu.compression.compress import (apply_compression,
                                                get_compression_plan,
                                                init_compression,
                                                redundancy_clean,
                                                student_initialization)
from deepspeed_tpu.compression.layers import CompressedLinear, QuantAct
from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.compression.utils import (asym_quantize, binary_quantize,
                                             sym_quantize, ternary_quantize,
                                             topk_binarize)

__all__ = [
    "init_compression", "apply_compression", "get_compression_plan",
    "redundancy_clean", "student_initialization", "CompressedLinear",
    "QuantAct", "CompressionScheduler", "sym_quantize", "asym_quantize",
    "binary_quantize", "ternary_quantize", "topk_binarize",
]
