"""Compression scheduler (reference ``compression/scheduler.py:14
compression_scheduler``): decides, per training step, which compression
methods are live and the current weight-quantization bit-width.

The reference flips ``*_enabled`` flags on mutated modules at
``schedule_offset`` and halves quantization bits every ``q_period``
(``start_bits -> target_bits``).  Here the scheduler is pure step math;
its output feeds :func:`deepspeed_tpu.compression.apply_compression` or
a model's ``weight_bits`` argument.
"""
from __future__ import annotations

from typing import Any, Dict


class CompressionScheduler:
    def __init__(self, compression_config: Dict[str, Any]):
        self.config = compression_config or {}

    @staticmethod
    def _shared(group_cfg: Dict[str, Any]) -> Dict[str, Any]:
        return group_cfg.get("shared_parameters", group_cfg)

    def weight_quantization_bits(self, step: int) -> Dict[str, int]:
        """Current bits per weight-quantization group: linear start->target
        halving every ``quantization_period`` steps after
        ``schedule_offset`` (reference ``QuantizationScheduler``)."""
        out = {}
        wq = self.config.get("weight_quantization", {})
        shared = self._shared(wq)
        offset = int(shared.get("schedule_offset", 0))
        for name, g in wq.get("different_groups", {}).items():
            p = g.get("params", g)
            start = int(p.get("start_bits", 8))
            target = int(p.get("target_bits", 8))
            period = int(g.get("quantization_period",
                               p.get("quantization_period", 1)) or 1)
            if step < offset:
                out[name] = start
                continue
            halvings = (step - offset) // period
            bits = start
            for _ in range(halvings):
                if bits <= target:
                    break
                bits = max(bits // 2, target)
            out[name] = max(bits, target)
        return out

    def method_enabled(self, step: int, method: str) -> bool:
        """Is a compression family live at this step (its
        ``schedule_offset`` reached)?"""
        cfg = self.config.get(method, {})
        if not cfg:
            return False
        shared = self._shared(cfg)
        if not shared.get("enabled", bool(cfg.get("different_groups"))):
            return False
        return step >= int(shared.get("schedule_offset", 0))
