"""Compression orchestration over flax param trees.

Re-design of the reference ``compression/compress.py``
(``init_compression:100``, ``redundancy_clean:148``,
``student_initialization:192``): the torch version swaps nn.Linear
modules for ``LinearLayer_Compress`` in place; functionally, compression
becomes a PLAN — a map from param path to the methods targeting it,
built from the same config schema (method groups with ``modules``
keyword patterns) — and :func:`apply_compression`, a pure function
``(params, plan, step) -> params`` implementing fake-quant / pruning
with straight-through gradients.  Call it on the weights inside the loss
(QAT), or once at export time via :func:`redundancy_clean` (hard masks,
no STE).

``student_initialization`` exploits the scan-stacked layer layout: layer
reduction is literally ``teacher_leaf[teacher_layer_indices]`` on every
stacked leaf — the [L, ...] leading dim IS the layer index.
"""
from __future__ import annotations

import fnmatch
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression.layers import quantize_weight
from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.compression.utils import topk_binarize

_METHODS = ("weight_quantization", "sparse_pruning", "row_pruning",
            "head_pruning", "channel_pruning")


def _paths(params) -> List[str]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in kp) for kp, _ in flat]


def _matches(path: str, patterns: Sequence[str]) -> bool:
    for pat in patterns:
        if pat == "*" or pat in path or fnmatch.fnmatch(path, f"*{pat}*"):
            return True
    return False


def get_compression_plan(params, compression_config: Dict[str, Any]
                         ) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """{param_path: {method: spec}} from the reference config schema
    (method -> different_groups -> {params, modules}).  Only kernel-like
    leaves (ndim >= 2) are targeted, like the reference's Linear swap."""
    plan: Dict[str, Dict[str, Dict[str, Any]]] = {}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    shapes = { "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp): leaf.shape for kp, leaf in flat}
    for method in _METHODS:
        mcfg = compression_config.get(method, {})
        groups = mcfg.get("different_groups", {})
        for gname, g in groups.items():
            spec = dict(g.get("params", {}))
            spec["group"] = gname
            if "quantization_period" in g:
                spec["quantization_period"] = g["quantization_period"]
            mods = g.get("modules", ["*"])
            for path, shape in shapes.items():
                if len(shape) < 2:
                    continue
                if _matches(path, mods):
                    plan.setdefault(path, {})[method] = spec
    return plan


def init_compression(params, ds_config: Dict[str, Any]):
    """Reference ``init_compression``: returns ``(plan, scheduler)`` for
    the config's ``compression_training`` subtree.  Apply with
    :func:`apply_compression` each step."""
    ccfg = ds_config.get("compression_training", ds_config) or {}
    return get_compression_plan(params, ccfg), CompressionScheduler(ccfg)


def _apply_leaf(leaf, methods: Dict[str, Dict[str, Any]], step: int,
                scheduler: Optional[CompressionScheduler], hard: bool):
    w = leaf
    sp = methods.get("sparse_pruning")
    if sp is not None and step >= int(sp.get("schedule_offset", 0)):
        # dense_ratio = fraction KEPT (reference naming)
        keep = float(sp.get("dense_ratio", 1.0 - float(sp.get("ratio", 0.5))))
        mask = topk_binarize(jax.lax.stop_gradient(
            jnp.abs(w.astype(jnp.float32))), keep)
        w = w * jax.lax.stop_gradient(mask).astype(w.dtype)
    rp = methods.get("row_pruning")
    if rp is not None and step >= int(rp.get("schedule_offset", 0)):
        keep = float(rp.get("dense_ratio", 1.0 - float(rp.get("ratio",
                                                              0.5))))
        norms = jnp.linalg.norm(
            jax.lax.stop_gradient(w.astype(jnp.float32)).reshape(
                w.shape[0], -1), ord=1, axis=1)
        mask = jax.lax.stop_gradient(topk_binarize(norms, keep))
        w = w * mask.reshape((-1,) + (1,) * (w.ndim - 1)).astype(w.dtype)
    wq = methods.get("weight_quantization")
    if wq is not None and step >= int(wq.get("schedule_offset", 0)):
        bits = int(wq.get("target_bits", 8))
        if scheduler is not None:
            bits = scheduler.weight_quantization_bits(step).get(
                wq.get("group", ""), bits)
        method = "asymmetric" if wq.get("quantization_type",
                                        "symmetric") == "asymmetric" \
            else "symmetric"
        groups = int(wq.get("quantize_groups", 1))
        q = quantize_weight(w.astype(jnp.float32), bits, method, groups)
        w = (jax.lax.stop_gradient(q).astype(w.dtype) if hard
             else q.astype(w.dtype))
    return w


def apply_compression(params, plan, step: int = 0,
                      scheduler: Optional[CompressionScheduler] = None,
                      hard: bool = False):
    """Pure QAT transform: fake-quantize / mask every planned leaf at
    this step.  ``hard=True`` detaches (export semantics, reference
    ``redundancy_clean``)."""
    import jax.tree_util as jtu

    flat, treedef = jtu.tree_flatten_with_path(params)
    out = []
    for kp, leaf in flat:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)
        methods = plan.get(path)
        out.append(leaf if methods is None else
                   _apply_leaf(leaf, methods, step, scheduler, hard))
    return jtu.tree_unflatten(treedef, out)


def redundancy_clean(params, plan,
                     scheduler: Optional[CompressionScheduler] = None):
    """Permanently apply masks/quantization grids (reference
    ``redundancy_clean:148``) — the post-training export pass."""
    return apply_compression(params, plan, step=1 << 30,
                             scheduler=scheduler, hard=True)


def student_initialization(student_params, teacher_params,
                           ds_config: Dict[str, Any]):
    """Layer reduction (reference ``student_initialization:192``): copy
    ``teacher_layer``-indexed slices of every scan-stacked leaf under
    ``module_name_prefix`` into the student, plus whole
    ``other_module_name`` subtrees."""
    ccfg = ds_config.get("compression_training", ds_config)
    lr_cfg = ccfg["layer_reduction"]
    prefix = lr_cfg["module_name_prefix"].replace(".", "/")
    teacher_layer = list(lr_cfg["teacher_layer"])
    others = [n.replace(".", "/") for n in
              lr_cfg.get("other_module_name", [])]
    idx = np.asarray(teacher_layer)

    import jax.tree_util as jtu

    s_flat, treedef = jtu.tree_flatten_with_path(student_params)
    t_flat = dict()
    for kp, leaf in jtu.tree_flatten_with_path(teacher_params)[0]:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)
        t_flat[path] = leaf

    out = []
    for kp, s_leaf in s_flat:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)
        t_leaf = t_flat.get(path)
        if t_leaf is None:
            out.append(s_leaf)
            continue
        if path.startswith(prefix) or f"/{prefix}/" in f"/{path}":
            # scan-stacked leaf: leading dim is the layer index
            assert t_leaf.shape[0] >= max(teacher_layer) + 1, (
                f"{path}: teacher has {t_leaf.shape[0]} layers, config "
                f"asks for layer {max(teacher_layer)}")
            sel = jnp.asarray(t_leaf)[idx]
            assert sel.shape == s_leaf.shape, (
                f"{path}: student {s_leaf.shape} vs selected {sel.shape}")
            out.append(sel.astype(s_leaf.dtype))
        elif any(path.startswith(o) or f"/{o}" in f"/{path}"
                 for o in others):
            assert t_leaf.shape == s_leaf.shape, (
                f"{path}: {t_leaf.shape} vs {s_leaf.shape}")
            out.append(jnp.asarray(t_leaf).astype(s_leaf.dtype))
        else:
            out.append(s_leaf)
    return jtu.tree_unflatten(treedef, out)
