"""Compression-aware flax layers.

Re-design of the reference ``compression/basic_layer.py``
(``LinearLayer_Compress:121``, ``QuantAct:17``): the torch versions
mutate module state (masks as buffers, learnable score Parameters bolted
on by ``enable_*`` calls); here compression is DECLARED in the module
config and applied functionally each forward — weight fake-quant,
sparse/row/head pruning (l1 static or topk learnable-score), activation
quantization — all with straight-through gradients, all jit-safe.
"""
from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.utils import (asym_quantize, binary_quantize,
                                             sym_quantize, ternary_quantize,
                                             topk_binarize)


def quantize_weight(w: jax.Array, bits: int, method: str = "symmetric",
                    num_groups: int = 1) -> jax.Array:
    if bits == 1:
        return binary_quantize(w, num_groups)
    if bits == 2:
        return ternary_quantize(w, num_groups)
    if method == "asymmetric":
        return asym_quantize(w, bits, num_groups)
    return sym_quantize(w, bits, num_groups)


class QuantAct(nn.Module):
    """Activation fake-quant (reference ``QuantAct:17``): dynamic range
    per call, or a static range tracked as a running min/max EMA in a
    mutable ``quant_stats`` collection."""

    num_bits: int = 8
    quant_mode: str = "symmetric"      # symmetric | asymmetric
    dynamic: bool = True
    ema_decay: float = 0.99

    @nn.compact
    def __call__(self, x, deterministic: bool = False):
        if self.dynamic:
            fn = sym_quantize if self.quant_mode == "symmetric" else \
                asym_quantize
            return fn(x, self.num_bits, num_groups=1)
        mn = self.variable("quant_stats", "min",
                           lambda: jnp.zeros((), jnp.float32))
        mx = self.variable("quant_stats", "max",
                           lambda: jnp.ones((), jnp.float32))
        if not deterministic:
            mn.value = self.ema_decay * mn.value + \
                (1 - self.ema_decay) * jnp.min(x)
            mx.value = self.ema_decay * mx.value + \
                (1 - self.ema_decay) * jnp.max(x)
        fn = sym_quantize if self.quant_mode == "symmetric" else \
            asym_quantize
        return fn(x, self.num_bits, min_value=mn.value, max_value=mx.value)


class CompressedLinear(nn.Module):
    """Linear with declarative compression (reference
    ``LinearLayer_Compress``).  ``weight_bits`` enables fake-quant QAT
    (pass the scheduler's current bits); pruning knobs build masks:

    - ``sparse_pruning``: elementwise, "l1" (static from |w|) or "topk"
      (learnable scores);
    - ``row_pruning``: whole output rows;
    - ``head_pruning``: groups of output columns (O-projection style,
      needs ``num_heads``), topk only, like the reference.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    weight_bits: Optional[int] = None
    weight_quant_method: str = "symmetric"
    weight_quant_groups: int = 1
    sparse_pruning_ratio: Optional[float] = None
    sparse_pruning_method: str = "l1"
    row_pruning_ratio: Optional[float] = None
    row_pruning_method: str = "l1"
    head_pruning_ratio: Optional[float] = None
    num_heads: Optional[int] = None
    activation_quant_bits: Optional[int] = None

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (in_dim, self.features), self.dtype)
        b = self.param("bias", nn.initializers.zeros, (self.features,),
                       self.dtype) if self.use_bias else None

        if self.activation_quant_bits:
            x = QuantAct(num_bits=self.activation_quant_bits,
                         name="quant_act")(x)

        if self.sparse_pruning_ratio is not None:
            keep = 1.0 - self.sparse_pruning_ratio
            if self.sparse_pruning_method == "topk":
                scores = self.param(
                    "sparse_mask_scores",
                    nn.initializers.variance_scaling(1 / 3, "fan_in",
                                                     "uniform"),
                    (in_dim, self.features), jnp.float32)
                w = w * topk_binarize(scores, keep).astype(w.dtype)
            else:
                mask = topk_binarize(jax.lax.stop_gradient(jnp.abs(w)),
                                     keep)
                w = w * jax.lax.stop_gradient(mask).astype(w.dtype)

        if self.row_pruning_ratio is not None:
            keep = 1.0 - self.row_pruning_ratio
            if self.row_pruning_method == "topk":
                scores = self.param(
                    "row_mask_scores",
                    nn.initializers.variance_scaling(1 / 3, "fan_in",
                                                     "uniform"),
                    (1, self.features), jnp.float32)
                mask = topk_binarize(scores, keep).astype(w.dtype)
            else:
                norms = jnp.linalg.norm(
                    jax.lax.stop_gradient(w.astype(jnp.float32)),
                    ord=1, axis=0, keepdims=True)
                mask = jax.lax.stop_gradient(
                    topk_binarize(norms, keep)).astype(w.dtype)
            w = w * mask
            if b is not None:
                b = b * mask[0]

        if self.head_pruning_ratio is not None:
            assert self.num_heads, "head pruning needs num_heads"
            assert in_dim % self.num_heads == 0, (
                "head pruning slices the INPUT dim (O-projection layout)")
            keep = 1.0 - self.head_pruning_ratio
            scores = self.param(
                "head_pruning_scores",
                nn.initializers.variance_scaling(1 / 3, "fan_in",
                                                 "uniform"),
                (1, self.num_heads), jnp.float32)
            hmask = topk_binarize(scores, keep).astype(w.dtype)  # [1, H]
            per_head = jnp.repeat(hmask[0], in_dim // self.num_heads)
            w = w * per_head[:, None]

        if self.weight_bits is not None:
            w = quantize_weight(w, self.weight_bits,
                                self.weight_quant_method,
                                self.weight_quant_groups)

        y = x @ w
        return y + b if b is not None else y
