"""Straight-through-estimator quantizers and binarizers.

Re-design of the reference ``compression/utils.py`` autograd.Functions
(``TopKBinarizer:11``, ``SymQuantizer:63``, ``AsymQuantizer:105``,
``TernaryQuantizer``, ``BinaryQuantizer``): fake-quantization for
quantization-aware training.  Torch implements the straight-through
estimator as a custom backward returning the gradient unchanged; in JAX
the same thing is one idiom::

    x + stop_gradient(q(x) - x)

— forward value is ``q(x)``, backward is identity.  All functions are
pure and jit/grad-safe.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _ste(x: jax.Array, qx: jax.Array) -> jax.Array:
    return x + jax.lax.stop_gradient(qx - x)


def sym_quantize(x: jax.Array, num_bits: int, num_groups: int = 1,
                 min_value: Optional[jax.Array] = None,
                 max_value: Optional[jax.Array] = None) -> jax.Array:
    """Symmetric fake-quant with STE (reference ``SymQuantizer``)."""
    q_range = 2 ** num_bits
    shape = x.shape
    g = x.reshape(num_groups, -1)
    if min_value is None:
        absmax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    else:
        assert num_groups == 1
        absmax = jnp.maximum(jnp.abs(min_value), max_value).reshape(1, 1)
    scale = 2.0 * absmax / q_range
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(g / scale), -q_range // 2, q_range // 2 - 1) * scale
    return _ste(x, q.reshape(shape))


def asym_quantize(x: jax.Array, num_bits: int, num_groups: int = 1,
                  min_value: Optional[jax.Array] = None,
                  max_value: Optional[jax.Array] = None) -> jax.Array:
    """Asymmetric fake-quant with STE (reference ``AsymQuantizer``)."""
    q_range = 2 ** num_bits
    shape = x.shape
    g = x.reshape(num_groups, -1)
    if min_value is None:
        mn = jnp.min(g, axis=-1, keepdims=True)
        mx = jnp.max(g, axis=-1, keepdims=True)
    else:
        assert num_groups == 1
        mn = min_value.reshape(1, 1)
        mx = max_value.reshape(1, 1)
    scale = jnp.maximum((mx - mn) / q_range, 1e-12)
    zero = mn
    q = jnp.clip(jnp.round((g - zero) / scale), 0, q_range - 1) * scale + zero
    return _ste(x, q.reshape(shape))


def binary_quantize(x: jax.Array, num_groups: int = 1) -> jax.Array:
    """1-bit sign quantization scaled by per-group mean |x| (reference
    ``BinaryQuantizer``)."""
    shape = x.shape
    g = x.reshape(num_groups, -1)
    alpha = jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    q = jnp.where(g >= 0, alpha, -alpha)
    return _ste(x, q.reshape(shape))


def ternary_quantize(x: jax.Array, num_groups: int = 1) -> jax.Array:
    """{-a, 0, +a} quantization with 0.7*mean|x| threshold (reference
    ``TernaryQuantizer``)."""
    shape = x.shape
    g = x.reshape(num_groups, -1)
    thre = 0.7 * jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    mask = (jnp.abs(g) > thre).astype(g.dtype)
    alpha = jnp.sum(jnp.abs(g) * mask, axis=-1, keepdims=True) / \
        jnp.maximum(jnp.sum(mask, axis=-1, keepdims=True), 1.0)
    q = alpha * jnp.sign(g) * mask
    return _ste(x, q.reshape(shape))


def topk_binarize(scores: jax.Array, keep_ratio: float,
                  sigmoid: bool = False) -> jax.Array:
    """Binary mask keeping the top ``keep_ratio`` fraction of ``scores``
    (reference ``TopKBinarizer``); backward passes gradients straight
    through to the scores (learnable-mask pruning)."""
    if sigmoid:
        keep_ratio = jax.nn.sigmoid(keep_ratio)
    flat = scores.reshape(-1)
    k = jnp.maximum(
        jnp.ceil(keep_ratio * flat.size).astype(jnp.int32), 1)
    # threshold = k-th largest value
    sorted_desc = jnp.sort(flat)[::-1]
    thresh = sorted_desc[jnp.clip(k - 1, 0, flat.size - 1)]
    mask = (flat >= thresh).astype(scores.dtype).reshape(scores.shape)
    return _ste(scores, mask)
