from deepspeed_tpu.module_inject.hf_loader import (convert_hf_state_dict,
                                                   load_hf_checkpoint)

__all__ = ["convert_hf_state_dict", "load_hf_checkpoint"]
