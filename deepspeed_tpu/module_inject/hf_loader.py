"""HF checkpoint -> flax params conversion (module injection).

TPU-native counterpart of the reference ``module_inject/`` stack
(``replace_module.py replace_transformer_layer``, ``load_checkpoint.py``):
the reference swaps HuggingFace torch modules in place for fused/TP
kernel containers and surgically loads checkpoint shards into them.
Here the optimized model IS our flax model zoo, so "injection" becomes a
pure weight-layout conversion: torch (or numpy) state dicts map onto the
flax param trees — per-layer tensors stack onto the scan axis, torch
``[out, in]`` linear weights transpose to flax ``[in, out]`` kernels,
GPT-2's Conv1D stays untransposed — after which the inference engine's
AutoTP sharding places them across the mesh (the TP half of the
reference's injection policies).

Supported families: GPT-2, Llama, Mistral, Qwen2, Mixtral (matching
``models/gpt2|llama|mistral|qwen2|mixtral.py``).  Sources: a dict of tensors, an HF
``transformers`` model object, or a directory holding
``pytorch_model.bin`` / sharded ``pytorch_model-*.bin`` /
``model.safetensors``.
"""
from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, List

import numpy as np

__all__ = ["convert_hf_state_dict", "load_hf_checkpoint"]


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:
        import torch

        if isinstance(t, torch.Tensor):
            return t.detach().to(torch.float32).cpu().numpy()
    except ImportError:
        pass
    return np.asarray(t)


def _read_state_dict(source) -> Dict[str, np.ndarray]:
    if isinstance(source, dict):
        return {k: _to_numpy(v) for k, v in source.items()}
    if hasattr(source, "state_dict"):
        return {k: _to_numpy(v) for k, v in source.state_dict().items()}
    assert isinstance(source, str), f"unsupported source {type(source)}"
    if os.path.isdir(source):
        shards = (sorted(glob.glob(os.path.join(source, "pytorch_model*.bin")))
                  or sorted(glob.glob(os.path.join(source, "*.safetensors"))))
        assert shards, f"no checkpoint files under {source}"
    else:
        shards = [source]
    sd: Dict[str, np.ndarray] = {}
    for shard in shards:
        if shard.endswith(".safetensors"):
            from safetensors.numpy import load_file

            sd.update(load_file(shard))
        else:
            import torch

            part = torch.load(shard, map_location="cpu",
                              weights_only=True)
            sd.update({k: _to_numpy(v) for k, v in part.items()})
    return sd


def _strip_prefix(sd: Dict[str, np.ndarray], *prefixes: str
                  ) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in sd.items():
        for p in prefixes:
            if k.startswith(p):
                k = k[len(p):]
                break
        out[k] = v
    return out


def _stack(per_layer: List[Dict[str, Any]], scan_layers: bool):
    """[{path: arr} per layer] -> {path: [L, ...]} (scan) or
    {layer_name_i: {path: arr}} (unrolled)."""
    if scan_layers:
        out: Dict[str, Any] = {}
        keys = per_layer[0].keys()
        for k in keys:
            out[k] = np.stack([layer[k] for layer in per_layer])
        return out
    return per_layer


def _nest(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, v in flat.items():
        node = tree
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


# ---------------------------------------------------------------------------
# per-family converters
# ---------------------------------------------------------------------------

def _convert_gpt2(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    sd = _strip_prefix(sd, "transformer.")
    L = cfg.n_layer
    layers = []
    for i in range(L):
        p = f"h.{i}."
        # HF GPT-2 Conv1D stores [in, out] — flax kernel layout already
        layers.append({
            "ln_1/scale": sd[p + "ln_1.weight"],
            "ln_1/bias": sd[p + "ln_1.bias"],
            "attn/c_attn/kernel": sd[p + "attn.c_attn.weight"],
            "attn/c_attn/bias": sd[p + "attn.c_attn.bias"],
            "attn/c_proj/kernel": sd[p + "attn.c_proj.weight"],
            "attn/c_proj/bias": sd[p + "attn.c_proj.bias"],
            "ln_2/scale": sd[p + "ln_2.weight"],
            "ln_2/bias": sd[p + "ln_2.bias"],
            "mlp/c_fc/kernel": sd[p + "mlp.c_fc.weight"],
            "mlp/c_fc/bias": sd[p + "mlp.c_fc.bias"],
            "mlp/c_proj/kernel": sd[p + "mlp.c_proj.weight"],
            "mlp/c_proj/bias": sd[p + "mlp.c_proj.bias"],
        })
    flat = {
        "wte/embedding": sd["wte.weight"],
        "wpe/embedding": sd["wpe.weight"][:cfg.n_positions],
        "ln_f/scale": sd["ln_f.weight"],
        "ln_f/bias": sd["ln_f.bias"],
    }
    if cfg.scan_layers:
        for k, v in _stack(layers, True).items():
            flat[f"h/block/{k}"] = v
    else:
        for i, layer in enumerate(layers):
            for k, v in layer.items():
                flat[f"h_{i}/{k}"] = v
    return _nest(flat)


def _llama_layer(sd, p: str, qkv_bias: bool = False
                 ) -> Dict[str, np.ndarray]:
    out = {
        "input_layernorm/scale": sd[p + "input_layernorm.weight"],
        "post_attention_layernorm/scale":
            sd[p + "post_attention_layernorm.weight"],
        "self_attn/q_proj/kernel": sd[p + "self_attn.q_proj.weight"].T,
        "self_attn/k_proj/kernel": sd[p + "self_attn.k_proj.weight"].T,
        "self_attn/v_proj/kernel": sd[p + "self_attn.v_proj.weight"].T,
        "self_attn/o_proj/kernel": sd[p + "self_attn.o_proj.weight"].T,
    }
    if qkv_bias:                      # Qwen2: biases on q/k/v only
        for w in ("q_proj", "k_proj", "v_proj"):
            out[f"self_attn/{w}/bias"] = sd[f"{p}self_attn.{w}.bias"]
    return out


def _convert_llama(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    L = cfg.num_hidden_layers
    qkv_bias = bool(getattr(cfg, "attention_bias", False))
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p, qkv_bias)
        layer.update({
            "mlp/gate_proj/kernel": sd[p + "mlp.gate_proj.weight"].T,
            "mlp/up_proj/kernel": sd[p + "mlp.up_proj.weight"].T,
            "mlp/down_proj/kernel": sd[p + "mlp.down_proj.weight"].T,
        })
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_phi3(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Phi-3 fuses qkv into ``qkv_proj`` and gate/up into ``gate_up_proj``
    (reference ``phi3/containers.py`` FusedQKVParameter /
    FusedGatedMLPParameter); split them onto the Llama layout."""
    L = cfg.num_hidden_layers
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        qkv = sd[p + "self_attn.qkv_proj.weight"]     # [(H+2Hkv)*Dh, E]
        q, k_, v = np.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=0)
        gate_up = sd[p + "mlp.gate_up_proj.weight"]   # [2*I, E]
        gate, up = np.split(gate_up, 2, axis=0)
        layers.append({
            "input_layernorm/scale": sd[p + "input_layernorm.weight"],
            "post_attention_layernorm/scale":
                sd[p + "post_attention_layernorm.weight"],
            "self_attn/q_proj/kernel": q.T,
            "self_attn/k_proj/kernel": k_.T,
            "self_attn/v_proj/kernel": v.T,
            "self_attn/o_proj/kernel": sd[p + "self_attn.o_proj.weight"].T,
            "mlp/gate_proj/kernel": gate.T,
            "mlp/up_proj/kernel": up.T,
            "mlp/down_proj/kernel": sd[p + "mlp.down_proj.weight"].T,
        })
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_qwen2_moe(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Qwen2-MoE (reference ``qwen_v2_moe/container.py``): Qwen2 attention
    (qkv biases) + routed experts + dense shared expert with sigmoid
    gate."""
    L = cfg.num_hidden_layers
    E = cfg.num_local_experts
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p, qkv_bias=True)
        moe = p + "mlp."
        layer["mlp/gate"] = sd[moe + "gate.weight"].T
        layer["mlp/w1"] = np.stack(
            [sd[f"{moe}experts.{e}.gate_proj.weight"].T for e in range(E)])
        layer["mlp/w3"] = np.stack(
            [sd[f"{moe}experts.{e}.up_proj.weight"].T for e in range(E)])
        layer["mlp/w2"] = np.stack(
            [sd[f"{moe}experts.{e}.down_proj.weight"].T for e in range(E)])
        if getattr(cfg, "shared_expert_intermediate_size", 0):
            for ours, theirs in (("gate_proj", "gate_proj"),
                                 ("up_proj", "up_proj"),
                                 ("down_proj", "down_proj")):
                layer[f"shared_expert/{ours}/kernel"] = \
                    sd[f"{moe}shared_expert.{theirs}.weight"].T
            layer["shared_expert_gate/kernel"] = \
                sd[moe + "shared_expert_gate.weight"].T
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_mixtral(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    L = cfg.num_hidden_layers
    E = cfg.num_local_experts
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p)
        moe = p + "block_sparse_moe."
        layer["block_sparse_moe/gate"] = sd[moe + "gate.weight"].T
        for w in ("w1", "w2", "w3"):
            layer[f"block_sparse_moe/{w}"] = np.stack(
                [sd[f"{moe}experts.{e}.{w}.weight"].T for e in range(E)])
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _place_layers(flat, layers, cfg, prefix: str) -> None:
    if cfg.scan_layers:
        for k, v in _stack(layers, True).items():
            flat[f"{prefix}/block/{k}"] = v
    else:
        base = prefix.rsplit("/", 1)[0]  # "model/layers" -> "model"
        for i, layer in enumerate(layers):
            for k, v in layer.items():
                flat[f"{base}/layers_{i}/{k}"] = v


_CONVERTERS = {
    "GPT2Config": _convert_gpt2,
    "LlamaConfig": _convert_llama,
    # Mistral (sliding window) and Qwen2 (qkv biases, via the config's
    # attention_bias flag) share the Llama tensor layout — reference
    # model_implementations/{mistral,qwen_v2} are Llama-container reuses
    # the same way
    "MistralConfig": _convert_llama,
    "Qwen2Config": _convert_llama,
    "MixtralConfig": _convert_mixtral,
    # Phi-3: Llama-shaped with FUSED qkv/gate_up tensors (split on load);
    # Qwen2-MoE: routed experts + shared expert w/ sigmoid gate
    "Phi3Config": _convert_phi3,
    "Qwen2MoeConfig": _convert_qwen2_moe,
}


def convert_hf_state_dict(model_or_config, source) -> Dict[str, Any]:
    """Convert an HF-layout checkpoint into the flax params tree for one
    of our model families.  ``model_or_config``: a model-zoo module (its
    ``.config`` picks the family) or the config dataclass itself."""
    cfg = getattr(model_or_config, "config", model_or_config)
    name = type(cfg).__name__
    # subclass dispatch: MixtralConfig extends LlamaConfig
    for cls in type(cfg).__mro__:
        if cls.__name__ in _CONVERTERS:
            name = cls.__name__
            break
    if name not in _CONVERTERS:
        raise TypeError(f"no HF converter for config {type(cfg).__name__}; "
                        f"supported: {sorted(_CONVERTERS)}")
    sd = _read_state_dict(source)
    return {"params": _CONVERTERS[name](sd, cfg)}


def load_hf_checkpoint(model, source):
    """Reference ``init_inference(checkpoint=...)`` entry: returns params
    ready for ``deepspeed_tpu.init_inference(model, params=...)``."""
    return convert_hf_state_dict(model, source)
