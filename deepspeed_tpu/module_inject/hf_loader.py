"""HF checkpoint -> flax params conversion (module injection).

TPU-native counterpart of the reference ``module_inject/`` stack
(``replace_module.py replace_transformer_layer``, ``load_checkpoint.py``):
the reference swaps HuggingFace torch modules in place for fused/TP
kernel containers and surgically loads checkpoint shards into them.
Here the optimized model IS our flax model zoo, so "injection" becomes a
pure weight-layout conversion: torch (or numpy) state dicts map onto the
flax param trees — per-layer tensors stack onto the scan axis, torch
``[out, in]`` linear weights transpose to flax ``[in, out]`` kernels,
GPT-2's Conv1D stays untransposed — after which the inference engine's
AutoTP sharding places them across the mesh (the TP half of the
reference's injection policies).

Supported families: GPT-2, Llama, Mistral, Qwen2, Mixtral, Phi,
Phi-3, Qwen2-MoE, Falcon, OPT, GPT-J, BLOOM, GPT-NeoX, GPT-Neo,
BERT, DistilBERT (matching ``models/*.py``; the reference v2 model
zoo plus the v1 injection zoo's decoder AND encoder classes —
bloom/gptj/gptneo/gptneox and bert/distil_bert).  Sources: a dict of tensors, an HF
``transformers`` model object, or a directory holding
``pytorch_model.bin`` / sharded ``pytorch_model-*.bin`` /
``model.safetensors``.
"""
from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, List

import numpy as np

__all__ = ["convert_hf_state_dict", "load_hf_checkpoint"]


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:
        import torch

        if isinstance(t, torch.Tensor):
            return t.detach().to(torch.float32).cpu().numpy()
    except ImportError:
        pass
    return np.asarray(t)


def _read_state_dict(source) -> Dict[str, np.ndarray]:
    if isinstance(source, dict):
        return {k: _to_numpy(v) for k, v in source.items()}
    if hasattr(source, "state_dict"):
        return {k: _to_numpy(v) for k, v in source.state_dict().items()}
    assert isinstance(source, str), f"unsupported source {type(source)}"
    if os.path.isdir(source):
        shards = (sorted(glob.glob(os.path.join(source, "pytorch_model*.bin")))
                  or sorted(glob.glob(os.path.join(source, "*.safetensors"))))
        assert shards, f"no checkpoint files under {source}"
    else:
        shards = [source]
    sd: Dict[str, np.ndarray] = {}
    for shard in shards:
        if shard.endswith(".safetensors"):
            from safetensors.numpy import load_file

            sd.update(load_file(shard))
        else:
            import torch

            part = torch.load(shard, map_location="cpu",
                              weights_only=True)
            sd.update({k: _to_numpy(v) for k, v in part.items()})
    return sd


def _strip_prefix(sd: Dict[str, np.ndarray], *prefixes: str
                  ) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in sd.items():
        for p in prefixes:
            if k.startswith(p):
                k = k[len(p):]
                break
        out[k] = v
    return out


def _stack(per_layer: List[Dict[str, Any]], scan_layers: bool):
    """[{path: arr} per layer] -> {path: [L, ...]} (scan) or
    {layer_name_i: {path: arr}} (unrolled)."""
    if scan_layers:
        out: Dict[str, Any] = {}
        keys = per_layer[0].keys()
        for k in keys:
            out[k] = np.stack([layer[k] for layer in per_layer])
        return out
    return per_layer


def _nest(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, v in flat.items():
        node = tree
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


# ---------------------------------------------------------------------------
# per-family converters
# ---------------------------------------------------------------------------

def _convert_gpt2(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    sd = _strip_prefix(sd, "transformer.")
    L = cfg.n_layer
    layers = []
    for i in range(L):
        p = f"h.{i}."
        # HF GPT-2 Conv1D stores [in, out] — flax kernel layout already
        layers.append({
            "ln_1/scale": sd[p + "ln_1.weight"],
            "ln_1/bias": sd[p + "ln_1.bias"],
            "attn/c_attn/kernel": sd[p + "attn.c_attn.weight"],
            "attn/c_attn/bias": sd[p + "attn.c_attn.bias"],
            "attn/c_proj/kernel": sd[p + "attn.c_proj.weight"],
            "attn/c_proj/bias": sd[p + "attn.c_proj.bias"],
            "ln_2/scale": sd[p + "ln_2.weight"],
            "ln_2/bias": sd[p + "ln_2.bias"],
            "mlp/c_fc/kernel": sd[p + "mlp.c_fc.weight"],
            "mlp/c_fc/bias": sd[p + "mlp.c_fc.bias"],
            "mlp/c_proj/kernel": sd[p + "mlp.c_proj.weight"],
            "mlp/c_proj/bias": sd[p + "mlp.c_proj.bias"],
        })
    flat = {
        "wte/embedding": sd["wte.weight"],
        "wpe/embedding": sd["wpe.weight"][:cfg.n_positions],
        "ln_f/scale": sd["ln_f.weight"],
        "ln_f/bias": sd["ln_f.bias"],
    }
    _place_layers(flat, layers, cfg, prefix="h")
    return _nest(flat)


def _llama_layer(sd, p: str, qkv_bias: bool = False
                 ) -> Dict[str, np.ndarray]:
    out = {
        "input_layernorm/scale": sd[p + "input_layernorm.weight"],
        "post_attention_layernorm/scale":
            sd[p + "post_attention_layernorm.weight"],
        "self_attn/q_proj/kernel": sd[p + "self_attn.q_proj.weight"].T,
        "self_attn/k_proj/kernel": sd[p + "self_attn.k_proj.weight"].T,
        "self_attn/v_proj/kernel": sd[p + "self_attn.v_proj.weight"].T,
        "self_attn/o_proj/kernel": sd[p + "self_attn.o_proj.weight"].T,
    }
    if qkv_bias:                      # Qwen2: biases on q/k/v only
        for w in ("q_proj", "k_proj", "v_proj"):
            out[f"self_attn/{w}/bias"] = sd[f"{p}self_attn.{w}.bias"]
    return out


def _convert_llama(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    L = cfg.num_hidden_layers
    qkv_bias = bool(getattr(cfg, "attention_bias", False))
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p, qkv_bias)
        layer.update({
            "mlp/gate_proj/kernel": sd[p + "mlp.gate_proj.weight"].T,
            "mlp/up_proj/kernel": sd[p + "mlp.up_proj.weight"].T,
            "mlp/down_proj/kernel": sd[p + "mlp.down_proj.weight"].T,
        })
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_phi3(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Phi-3 fuses qkv into ``qkv_proj`` and gate/up into ``gate_up_proj``
    (reference ``phi3/containers.py`` FusedQKVParameter /
    FusedGatedMLPParameter); split them onto the Llama layout."""
    L = cfg.num_hidden_layers
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        qkv = sd[p + "self_attn.qkv_proj.weight"]     # [(H+2Hkv)*Dh, E]
        q, k_, v = np.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=0)
        gate_up = sd[p + "mlp.gate_up_proj.weight"]   # [2*I, E]
        gate, up = np.split(gate_up, 2, axis=0)
        layers.append({
            "input_layernorm/scale": sd[p + "input_layernorm.weight"],
            "post_attention_layernorm/scale":
                sd[p + "post_attention_layernorm.weight"],
            "self_attn/q_proj/kernel": q.T,
            "self_attn/k_proj/kernel": k_.T,
            "self_attn/v_proj/kernel": v.T,
            "self_attn/o_proj/kernel": sd[p + "self_attn.o_proj.weight"].T,
            "mlp/gate_proj/kernel": gate.T,
            "mlp/up_proj/kernel": up.T,
            "mlp/down_proj/kernel": sd[p + "mlp.down_proj.weight"].T,
        })
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_qwen2_moe(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Qwen2-MoE (reference ``qwen_v2_moe/container.py``): Qwen2 attention
    (qkv biases) + routed experts + dense shared expert with sigmoid
    gate."""
    L = cfg.num_hidden_layers
    E = cfg.num_local_experts
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p, qkv_bias=True)
        moe = p + "mlp."
        layer["mlp/gate"] = sd[moe + "gate.weight"].T
        layer["mlp/w1"] = np.stack(
            [sd[f"{moe}experts.{e}.gate_proj.weight"].T for e in range(E)])
        layer["mlp/w3"] = np.stack(
            [sd[f"{moe}experts.{e}.up_proj.weight"].T for e in range(E)])
        layer["mlp/w2"] = np.stack(
            [sd[f"{moe}experts.{e}.down_proj.weight"].T for e in range(E)])
        if getattr(cfg, "shared_expert_intermediate_size", 0):
            for ours, theirs in (("gate_proj", "gate_proj"),
                                 ("up_proj", "up_proj"),
                                 ("down_proj", "down_proj")):
                layer[f"shared_expert/{ours}/kernel"] = \
                    sd[f"{moe}shared_expert.{theirs}.weight"].T
            layer["shared_expert_gate/kernel"] = \
                sd[moe + "shared_expert_gate.weight"].T
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_opt(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """OPT (reference ``opt/container.py``): q/k/v/out with biases,
    learned positions (+2 offset rows kept verbatim), pre-LN, ReLU MLP."""
    sd = _strip_prefix(sd, "model.decoder.", "decoder.")
    assert not any("project_in" in k or "project_out" in k for k in sd), (
        "OPT converter: word_embed_proj_dim != hidden_size checkpoints "
        "(opt-350m's project_in/project_out) are not supported")
    L = cfg.num_hidden_layers
    layers = []
    for i in range(L):
        p = f"layers.{i}."
        layer = {}
        for w in ("q_proj", "k_proj", "v_proj", "out_proj"):
            layer[f"self_attn/{w}/kernel"] = \
                sd[f"{p}self_attn.{w}.weight"].T
            layer[f"self_attn/{w}/bias"] = sd[f"{p}self_attn.{w}.bias"]
        for ln in ("self_attn_layer_norm", "final_layer_norm"):
            layer[f"{ln}/scale"] = sd[f"{p}{ln}.weight"]
            layer[f"{ln}/bias"] = sd[f"{p}{ln}.bias"]
        for fc in ("fc1", "fc2"):
            layer[f"{fc}/kernel"] = sd[f"{p}{fc}.weight"].T
            layer[f"{fc}/bias"] = sd[f"{p}{fc}.bias"]
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["embed_tokens.weight"],
        "model/embed_positions/embedding": sd["embed_positions.weight"],
        "model/final_layer_norm/scale": sd["final_layer_norm.weight"],
        "model/final_layer_norm/bias": sd["final_layer_norm.bias"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_phi(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Phi-1/1.5/2 (reference ``phi/containers.py``): biased q/k/v/dense,
    parallel residual, gelu_new MLP with biases, biased LM head."""
    assert not any("q_layernorm" in k or "k_layernorm" in k for k in sd), (
        "Phi converter: qk_layernorm=True checkpoints are not supported "
        "(the module has no q/k layernorms) — loading one silently would "
        "produce wrong logits")
    L = cfg.num_hidden_layers
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = {
            "input_layernorm/scale": sd[p + "input_layernorm.weight"],
            "input_layernorm/bias": sd[p + "input_layernorm.bias"],
            "self_attn/o_proj/kernel": sd[p + "self_attn.dense.weight"].T,
            "self_attn/o_proj/bias": sd[p + "self_attn.dense.bias"],
        }
        for w in ("q_proj", "k_proj", "v_proj"):
            layer[f"self_attn/{w}/kernel"] = \
                sd[f"{p}self_attn.{w}.weight"].T
            layer[f"self_attn/{w}/bias"] = sd[f"{p}self_attn.{w}.bias"]
        for fc in ("fc1", "fc2"):
            layer[f"mlp/{fc}/kernel"] = sd[f"{p}mlp.{fc}.weight"].T
            layer[f"mlp/{fc}/bias"] = sd[f"{p}mlp.{fc}.bias"]
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/final_layernorm/scale": sd["model.final_layernorm.weight"],
        "model/final_layernorm/bias": sd["model.final_layernorm.bias"],
        "lm_head/kernel": sd["lm_head.weight"].T,
        "lm_head/bias": sd["lm_head.bias"],
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _convert_falcon(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Falcon (reference ``falcon/container.py``): fused query_key_value
    split into q/k/v (contiguous rows for the 7B MQA layout, per-kv-group
    interleave for the 40B new_decoder_architecture), LayerNorms with
    biases, GELU MLP."""
    sd = _strip_prefix(sd, "transformer.")
    L = cfg.num_hidden_layers
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    # supported layouts: contiguous q|k|v rows (MQA, falcon-7b) or the
    # new-architecture per-kv-group interleave.  The falcon-rw lineage
    # (old arch, num_kv_heads == num_heads) interleaves [q_i,k_i,v_i]
    # per head — a contiguous split would silently scramble it
    assert cfg.parallel_attn, (
        "falcon converter: parallel_attn=False checkpoints (falcon-rw "
        "lineage) are not supported")
    assert getattr(cfg, "new_decoder_architecture", False) or Hkv == 1, (
        "falcon converter: old-architecture checkpoints with "
        f"num_kv_heads={Hkv} > 1 interleave qkv per head — only MQA "
        "(falcon-7b) or new_decoder_architecture (falcon-40b+) layouts "
        "are supported")
    layers = []
    for i in range(L):
        p = f"h.{i}."
        qkv = sd[p + "self_attention.query_key_value.weight"]
        if getattr(cfg, "new_decoder_architecture", False):
            # [Hkv, H/Hkv + 2, Dh, E]: each kv group carries its q heads
            # then its k then its v row-blocks
            g = H // Hkv
            qkv4 = qkv.reshape(Hkv, g + 2, Dh, -1)
            q = qkv4[:, :g].reshape(H * Dh, -1)
            k_ = qkv4[:, g].reshape(Hkv * Dh, -1)
            v = qkv4[:, g + 1].reshape(Hkv * Dh, -1)
        else:
            q, k_, v = np.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=0)
        ln_attn = ("ln_attn" if getattr(cfg, "new_decoder_architecture",
                                        False) else "input_layernorm")
        layer = {
            "input_layernorm/scale": sd[f"{p}{ln_attn}.weight"],
            "input_layernorm/bias": sd[f"{p}{ln_attn}.bias"],
            "self_attention/q_proj/kernel": q.T,
            "self_attention/k_proj/kernel": k_.T,
            "self_attention/v_proj/kernel": v.T,
            "self_attention/o_proj/kernel":
                sd[p + "self_attention.dense.weight"].T,
            "mlp/dense_h_to_4h/kernel":
                sd[p + "mlp.dense_h_to_4h.weight"].T,
            "mlp/dense_4h_to_h/kernel":
                sd[p + "mlp.dense_4h_to_h.weight"].T,
        }
        if getattr(cfg, "new_decoder_architecture", False):
            layer["ln_mlp/scale"] = sd[p + "ln_mlp.weight"]
            layer["ln_mlp/bias"] = sd[p + "ln_mlp.bias"]
        layers.append(layer)
    flat = {
        "transformer/word_embeddings/embedding":
            sd["word_embeddings.weight"],
        "transformer/ln_f/scale": sd["ln_f.weight"],
        "transformer/ln_f/bias": sd["ln_f.bias"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["word_embeddings.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="transformer/h")
    return _nest(flat)


def _gptj_rot_perm(H: int, Dh: int, rot: int) -> np.ndarray:
    """Row permutation mapping GPT-J's INTERLEAVED rotary layout
    (rotate-every-two: freq i acts on dims 2i, 2i+1) onto the half
    (NeoX) layout our ``rotary_embedding`` computes (freq i acts on dims
    i, i+rot/2).  Attention scores are invariant because q and k are
    permuted identically."""
    idx = []
    for h in range(H):
        base = h * Dh
        idx += [base + j for j in range(0, rot, 2)]
        idx += [base + j for j in range(1, rot, 2)]
        idx += [base + j for j in range(rot, Dh)]
    return np.asarray(idx)


def _convert_gptj(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """GPT-J (reference ``module_inject/containers/gptj.py``): parallel
    residual, partial interleaved rotary (q/k rows permuted into the
    half layout — see ``_gptj_rot_perm``), biased GELU MLP + lm_head."""
    sd = {k: v for k, v in sd.items()}
    L, H, Dh = (cfg.num_hidden_layers, cfg.num_attention_heads,
                cfg.head_dim)
    perm = _gptj_rot_perm(H, Dh, int(cfg.rotary_dim))
    layers = []
    for i in range(L):
        p = f"transformer.h.{i}."
        layers.append({
            "ln_1/scale": sd[p + "ln_1.weight"],
            "ln_1/bias": sd[p + "ln_1.bias"],
            "attn/q_proj/kernel": sd[p + "attn.q_proj.weight"][perm].T,
            "attn/k_proj/kernel": sd[p + "attn.k_proj.weight"][perm].T,
            "attn/v_proj/kernel": sd[p + "attn.v_proj.weight"].T,
            "attn/o_proj/kernel": sd[p + "attn.out_proj.weight"].T,
            "mlp/fc_in/kernel": sd[p + "mlp.fc_in.weight"].T,
            "mlp/fc_in/bias": sd[p + "mlp.fc_in.bias"],
            "mlp/fc_out/kernel": sd[p + "mlp.fc_out.weight"].T,
            "mlp/fc_out/bias": sd[p + "mlp.fc_out.bias"],
        })
    flat = {
        "transformer/wte/embedding": sd["transformer.wte.weight"],
        "transformer/ln_f/scale": sd["transformer.ln_f.weight"],
        "transformer/ln_f/bias": sd["transformer.ln_f.bias"],
        "lm_head/kernel": sd["lm_head.weight"].T,
        "lm_head/bias": sd["lm_head.bias"],
    }
    _place_layers(flat, layers, cfg, prefix="transformer/h")
    return _nest(flat)


def _convert_gptneox(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """GPT-NeoX (reference ``module_inject/containers/gptneox.py``
    GPTNEOXLayerPolicy): fused per-head ``[q_h; k_h; v_h]``
    query_key_value split into q/k/v, parallel residual, half-layout
    partial rotary (no permutation needed), untied ``embed_out``."""
    sd = _strip_prefix(sd, "gpt_neox.")
    L, H, Dh = (cfg.num_hidden_layers, cfg.num_attention_heads,
                cfg.head_dim)
    layers = []
    for i in range(L):
        p = f"layers.{i}."
        w4 = sd[p + "attention.query_key_value.weight"].reshape(
            H, 3, Dh, -1)
        b3 = sd[p + "attention.query_key_value.bias"].reshape(H, 3, Dh)
        layers.append({
            "input_layernorm/scale": sd[p + "input_layernorm.weight"],
            "input_layernorm/bias": sd[p + "input_layernorm.bias"],
            "post_attention_layernorm/scale":
                sd[p + "post_attention_layernorm.weight"],
            "post_attention_layernorm/bias":
                sd[p + "post_attention_layernorm.bias"],
            "attention/q_proj/kernel": w4[:, 0].reshape(H * Dh, -1).T,
            "attention/q_proj/bias": b3[:, 0].reshape(-1),
            "attention/k_proj/kernel": w4[:, 1].reshape(H * Dh, -1).T,
            "attention/k_proj/bias": b3[:, 1].reshape(-1),
            "attention/v_proj/kernel": w4[:, 2].reshape(H * Dh, -1).T,
            "attention/v_proj/bias": b3[:, 2].reshape(-1),
            "attention/o_proj/kernel": sd[p + "attention.dense.weight"].T,
            "attention/o_proj/bias": sd[p + "attention.dense.bias"],
            "mlp/dense_h_to_4h/kernel":
                sd[p + "mlp.dense_h_to_4h.weight"].T,
            "mlp/dense_h_to_4h/bias": sd[p + "mlp.dense_h_to_4h.bias"],
            "mlp/dense_4h_to_h/kernel":
                sd[p + "mlp.dense_4h_to_h.weight"].T,
            "mlp/dense_4h_to_h/bias": sd[p + "mlp.dense_4h_to_h.bias"],
        })
    flat = {
        "gpt_neox/embed_in/embedding": sd["embed_in.weight"],
        "gpt_neox/final_layer_norm/scale": sd["final_layer_norm.weight"],
        "gpt_neox/final_layer_norm/bias": sd["final_layer_norm.bias"],
        "embed_out/kernel": sd["embed_out.weight"].T,
    }
    _place_layers(flat, layers, cfg, prefix="gpt_neox/layers")
    return _nest(flat)


def _convert_bloom(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """BLOOM (reference ``module_inject/containers/bloom.py``
    BLOOMLayerPolicy): fused per-head ``[q_h; k_h; v_h]``
    query_key_value split into q/k/v, biased everything, embedding
    LayerNorm, lm_head tied to word_embeddings."""
    sd = _strip_prefix(sd, "transformer.")
    L, H, Dh = (cfg.num_hidden_layers, cfg.num_attention_heads,
                cfg.head_dim)
    layers = []
    for i in range(L):
        p = f"h.{i}."
        w = sd[p + "self_attention.query_key_value.weight"]
        b = sd[p + "self_attention.query_key_value.bias"]
        w4 = w.reshape(H, 3, Dh, -1)
        b3 = b.reshape(H, 3, Dh)
        layer = {
            "input_layernorm/scale": sd[p + "input_layernorm.weight"],
            "input_layernorm/bias": sd[p + "input_layernorm.bias"],
            "post_attention_layernorm/scale":
                sd[p + "post_attention_layernorm.weight"],
            "post_attention_layernorm/bias":
                sd[p + "post_attention_layernorm.bias"],
            "self_attention/q_proj/kernel":
                w4[:, 0].reshape(H * Dh, -1).T,
            "self_attention/q_proj/bias": b3[:, 0].reshape(-1),
            "self_attention/k_proj/kernel":
                w4[:, 1].reshape(H * Dh, -1).T,
            "self_attention/k_proj/bias": b3[:, 1].reshape(-1),
            "self_attention/v_proj/kernel":
                w4[:, 2].reshape(H * Dh, -1).T,
            "self_attention/v_proj/bias": b3[:, 2].reshape(-1),
            "self_attention/dense/kernel":
                sd[p + "self_attention.dense.weight"].T,
            "self_attention/dense/bias":
                sd[p + "self_attention.dense.bias"],
            "mlp/dense_h_to_4h/kernel":
                sd[p + "mlp.dense_h_to_4h.weight"].T,
            "mlp/dense_h_to_4h/bias": sd[p + "mlp.dense_h_to_4h.bias"],
            "mlp/dense_4h_to_h/kernel":
                sd[p + "mlp.dense_4h_to_h.weight"].T,
            "mlp/dense_4h_to_h/bias": sd[p + "mlp.dense_4h_to_h.bias"],
        }
        layers.append(layer)
    flat = {
        "transformer/word_embeddings/embedding":
            sd["word_embeddings.weight"],
        "transformer/word_embeddings_layernorm/scale":
            sd["word_embeddings_layernorm.weight"],
        "transformer/word_embeddings_layernorm/bias":
            sd["word_embeddings_layernorm.bias"],
        "transformer/ln_f/scale": sd["ln_f.weight"],
        "transformer/ln_f/bias": sd["ln_f.bias"],
        # tied head: HF ties lm_head to word_embeddings
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["word_embeddings.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="transformer/h")
    return _nest(flat)


def _convert_mixtral(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    L = cfg.num_hidden_layers
    E = cfg.num_local_experts
    layers = []
    for i in range(L):
        p = f"model.layers.{i}."
        layer = _llama_layer(sd, p)
        moe = p + "block_sparse_moe."
        layer["block_sparse_moe/gate"] = sd[moe + "gate.weight"].T
        for w in ("w1", "w2", "w3"):
            layer[f"block_sparse_moe/{w}"] = np.stack(
                [sd[f"{moe}experts.{e}.{w}.weight"].T for e in range(E)])
        layers.append(layer)
    flat = {
        "model/embed_tokens/embedding": sd["model.embed_tokens.weight"],
        "model/norm/scale": sd["model.norm.weight"],
        "lm_head/kernel": (sd.get("lm_head.weight",
                                  sd["model.embed_tokens.weight"])).T,
    }
    _place_layers(flat, layers, cfg, prefix="model/layers")
    return _nest(flat)


def _place_layers(flat, layers, cfg, prefix: str,
                  unrolled: Optional[str] = None) -> None:
    """Place per-layer trees: scan-stacked under ``<prefix>/block`` or
    unrolled as ``<parent>/<unrolled.format(i)>``.  ``unrolled`` defaults
    to ``<last prefix component>_{i}`` (``model/layers`` -> ``layers_{i}``)."""
    if cfg.scan_layers:
        for k, v in _stack(layers, True).items():
            flat[f"{prefix}/block/{k}"] = v
    else:
        base, _, leaf = prefix.rpartition("/")
        pat = unrolled or (leaf + "_{i}")
        stem = f"{base}/" if base else ""
        for i, layer in enumerate(layers):
            for k, v in layer.items():
                flat[f"{stem}{pat.format(i=i)}/{k}"] = v


def _convert_bert(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """BERT (reference ``module_inject/containers/bert.py``
    HFBertLayerPolicy): post-LN encoder blocks, learned absolute +
    token-type embeddings with embedding LN, MLM head with the decoder
    tied to word_embeddings (copied into our explicit decoder Dense)."""
    sd = {k: v for k, v in sd.items()}
    L = cfg.num_hidden_layers
    layers = []
    for i in range(L):
        p = f"bert.encoder.layer.{i}."
        layers.append({
            "attention/query/kernel":
                sd[p + "attention.self.query.weight"].T,
            "attention/query/bias": sd[p + "attention.self.query.bias"],
            "attention/key/kernel": sd[p + "attention.self.key.weight"].T,
            "attention/key/bias": sd[p + "attention.self.key.bias"],
            "attention/value/kernel":
                sd[p + "attention.self.value.weight"].T,
            "attention/value/bias": sd[p + "attention.self.value.bias"],
            "attention_output/kernel":
                sd[p + "attention.output.dense.weight"].T,
            "attention_output/bias": sd[p + "attention.output.dense.bias"],
            "attention_layernorm/scale":
                sd[p + "attention.output.LayerNorm.weight"],
            "attention_layernorm/bias":
                sd[p + "attention.output.LayerNorm.bias"],
            "intermediate/kernel": sd[p + "intermediate.dense.weight"].T,
            "intermediate/bias": sd[p + "intermediate.dense.bias"],
            "output/kernel": sd[p + "output.dense.weight"].T,
            "output/bias": sd[p + "output.dense.bias"],
            "output_layernorm/scale": sd[p + "output.LayerNorm.weight"],
            "output_layernorm/bias": sd[p + "output.LayerNorm.bias"],
        })
    wte = sd["bert.embeddings.word_embeddings.weight"]
    flat = {
        "bert/word_embeddings/embedding": wte,
        "bert/position_embeddings/embedding":
            sd["bert.embeddings.position_embeddings.weight"],
        "bert/token_type_embeddings/embedding":
            sd["bert.embeddings.token_type_embeddings.weight"],
        "bert/embeddings_layernorm/scale":
            sd["bert.embeddings.LayerNorm.weight"],
        "bert/embeddings_layernorm/bias":
            sd["bert.embeddings.LayerNorm.bias"],
        "transform/kernel":
            sd["cls.predictions.transform.dense.weight"].T,
        "transform/bias": sd["cls.predictions.transform.dense.bias"],
        "transform_layernorm/scale":
            sd["cls.predictions.transform.LayerNorm.weight"],
        "transform_layernorm/bias":
            sd["cls.predictions.transform.LayerNorm.bias"],
        # tied decoder: HF reuses word_embeddings + a free bias, and
        # serializers routinely DROP the tied duplicate (safetensors
        # dedup) — the transform.* keys above are always present in MLM
        # checkpoints, so these .get fallbacks are the tied-dedup case,
        # not dead code (encoder-only checkpoints fail loudly above)
        "decoder/kernel": sd.get("cls.predictions.decoder.weight", wte).T,
        "decoder/bias": sd.get(
            "cls.predictions.decoder.bias",
            sd.get("cls.predictions.bias",
                   np.zeros(wte.shape[0], wte.dtype))),
    }
    _place_layers(flat, layers, cfg, prefix="bert/layer")
    return _nest(flat)


def _gptneo_check_attention(hf_config, cfg) -> None:
    """The state dict carries no trace of the attention schedule — a
    checkpoint trained with a different global/local pattern or window
    would convert cleanly and serve wrong logits silently (the same
    failure class as an untied head).  When the source exposes its HF
    config, validate it against the target's cycled pattern."""
    if hf_config is None:
        return
    L = cfg.num_hidden_layers
    layers = getattr(hf_config, "attention_layers", None)
    if layers is None:
        # config.json form: attention_types = [[["global","local"], N]]
        at = getattr(hf_config, "attention_types", None)
        if at:
            layers = [kind for pattern, n in at
                      for _ in range(n) for kind in pattern]
    if layers is not None:
        expect = [cfg.layer_kind(i) for i in range(L)]
        got = list(layers)[:L]
        if got != expect:
            raise ValueError(
                f"GPT-Neo checkpoint's attention schedule {got} does not "
                f"match the target config's cycled pattern {expect} "
                f"(attention_layers={cfg.attention_layers}); converting "
                "would serve wrong logits — build the target GPTNeoConfig "
                "with the checkpoint's attention_types")
    hf_window = getattr(hf_config, "window_size", None)
    if hf_window is not None and int(hf_window) != int(cfg.window_size):
        raise ValueError(
            f"GPT-Neo checkpoint was trained with window_size="
            f"{hf_window}, target config has {cfg.window_size}; local "
            "layers would attend over the wrong span — set window_size="
            f"{hf_window} on the target GPTNeoConfig")


def _convert_gptneo(sd: Dict[str, np.ndarray], cfg,
                    hf_config=None) -> Dict[str, Any]:
    """GPT-Neo (reference ``module_inject/containers/gptneo.py``
    HFGPTNEOLayerPolicy): separate biasless q/k/v + biased out_proj,
    GPT-2-shaped pre-LN block, tied head (no separate lm_head param —
    our module attends the embedding)."""
    _gptneo_check_attention(hf_config, cfg)
    sd = _strip_prefix(sd, "transformer.")
    L = cfg.num_hidden_layers
    layers = []
    for i in range(L):
        p = f"h.{i}."
        a = p + "attn.attention."
        layers.append({
            "ln_1/scale": sd[p + "ln_1.weight"],
            "ln_1/bias": sd[p + "ln_1.bias"],
            "attn/q_proj/kernel": sd[a + "q_proj.weight"].T,
            "attn/k_proj/kernel": sd[a + "k_proj.weight"].T,
            "attn/v_proj/kernel": sd[a + "v_proj.weight"].T,
            "attn/out_proj/kernel": sd[a + "out_proj.weight"].T,
            "attn/out_proj/bias": sd[a + "out_proj.bias"],
            "ln_2/scale": sd[p + "ln_2.weight"],
            "ln_2/bias": sd[p + "ln_2.bias"],
            "mlp/c_fc/kernel": sd[p + "mlp.c_fc.weight"].T,
            "mlp/c_fc/bias": sd[p + "mlp.c_fc.bias"],
            "mlp/c_proj/kernel": sd[p + "mlp.c_proj.weight"].T,
            "mlp/c_proj/bias": sd[p + "mlp.c_proj.bias"],
        })
    head = sd.get("lm_head.weight")
    if head is not None and not np.allclose(head, sd["wte.weight"],
                                            atol=1e-6):
        # our module always ties (wte.attend); converting an untied
        # fine-tune silently would serve wrong logits
        raise ValueError(
            "GPT-Neo checkpoint carries an UNTIED lm_head.weight; this "
            "module only represents the tied head (every released "
            "EleutherAI GPT-Neo ties) — retie the head or extend "
            "GPTNeoModel with an untied lm_head first")
    flat = {
        "transformer/wte/embedding": sd["wte.weight"],
        "transformer/wpe/embedding": sd["wpe.weight"],
        "transformer/ln_f/scale": sd["ln_f.weight"],
        "transformer/ln_f/bias": sd["ln_f.bias"],
    }
    _place_layers(flat, layers, cfg, prefix="transformer/h")
    return _nest(flat)


def _convert_distilbert(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """DistilBERT (reference ``containers/distil_bert.py``): BERT-shaped
    minus token types — maps onto the BERT modules with a zeroed
    size-1 token-type table; ``vocab_*`` MLM head, projector tied to
    word_embeddings (dedup-safe .get)."""
    L, E = cfg.num_hidden_layers, cfg.hidden_size
    layers = []
    for i in range(L):
        p = f"distilbert.transformer.layer.{i}."
        layers.append({
            "attention/query/kernel": sd[p + "attention.q_lin.weight"].T,
            "attention/query/bias": sd[p + "attention.q_lin.bias"],
            "attention/key/kernel": sd[p + "attention.k_lin.weight"].T,
            "attention/key/bias": sd[p + "attention.k_lin.bias"],
            "attention/value/kernel": sd[p + "attention.v_lin.weight"].T,
            "attention/value/bias": sd[p + "attention.v_lin.bias"],
            "attention_output/kernel":
                sd[p + "attention.out_lin.weight"].T,
            "attention_output/bias": sd[p + "attention.out_lin.bias"],
            "attention_layernorm/scale": sd[p + "sa_layer_norm.weight"],
            "attention_layernorm/bias": sd[p + "sa_layer_norm.bias"],
            "intermediate/kernel": sd[p + "ffn.lin1.weight"].T,
            "intermediate/bias": sd[p + "ffn.lin1.bias"],
            "output/kernel": sd[p + "ffn.lin2.weight"].T,
            "output/bias": sd[p + "ffn.lin2.bias"],
            "output_layernorm/scale":
                sd[p + "output_layer_norm.weight"],
            "output_layernorm/bias": sd[p + "output_layer_norm.bias"],
        })
    wte = sd["distilbert.embeddings.word_embeddings.weight"]
    flat = {
        "bert/word_embeddings/embedding": wte,
        "bert/position_embeddings/embedding":
            sd["distilbert.embeddings.position_embeddings.weight"],
        "bert/token_type_embeddings/embedding":
            np.zeros((cfg.type_vocab_size, E), wte.dtype),
        "bert/embeddings_layernorm/scale":
            sd["distilbert.embeddings.LayerNorm.weight"],
        "bert/embeddings_layernorm/bias":
            sd["distilbert.embeddings.LayerNorm.bias"],
        "transform/kernel": sd["vocab_transform.weight"].T,
        "transform/bias": sd["vocab_transform.bias"],
        "transform_layernorm/scale": sd["vocab_layer_norm.weight"],
        "transform_layernorm/bias": sd["vocab_layer_norm.bias"],
        "decoder/kernel": sd.get("vocab_projector.weight", wte).T,
        "decoder/bias": sd["vocab_projector.bias"],
    }
    _place_layers(flat, layers, cfg, prefix="bert/layer")
    return _nest(flat)


_CONVERTERS = {
    "GPT2Config": _convert_gpt2,
    "LlamaConfig": _convert_llama,
    # Mistral (sliding window) and Qwen2 (qkv biases, via the config's
    # attention_bias flag) share the Llama tensor layout — reference
    # model_implementations/{mistral,qwen_v2} are Llama-container reuses
    # the same way
    "MistralConfig": _convert_llama,
    "Qwen2Config": _convert_llama,
    "MixtralConfig": _convert_mixtral,
    # Phi-3: Llama-shaped with FUSED qkv/gate_up tensors (split on load);
    # Qwen2-MoE: routed experts + shared expert w/ sigmoid gate;
    # Falcon: fused query_key_value + parallel-residual block
    "Phi3Config": _convert_phi3,
    "Qwen2MoeConfig": _convert_qwen2_moe,
    "FalconConfig": _convert_falcon,
    "OPTConfig": _convert_opt,
    "PhiConfig": _convert_phi,
    # GPT-J: parallel residual + interleaved partial rotary (permuted on
    # load); BLOOM: ALiBi + fused per-head qkv — the encoder/bloom/gptj
    # class of the reference v1 injection zoo
    "GPTJConfig": _convert_gptj,
    "BloomConfig": _convert_bloom,
    # GPT-NeoX: fused per-head qkv + parallel residual, half-layout
    # rotary (reference containers/gptneox.py)
    "GPTNeoXConfig": _convert_gptneox,
    # BERT: the encoder class (reference containers/bert.py);
    # DistilBERT maps onto the same modules (containers/distil_bert.py);
    # GPT-Neo: unscaled attention + global/local alternation
    # (containers/gptneo.py)
    "BertConfig": _convert_bert,
    "DistilBertConfig": _convert_distilbert,
    "GPTNeoConfig": _convert_gptneo,
}


def convert_hf_state_dict(model_or_config, source) -> Dict[str, Any]:
    """Convert an HF-layout checkpoint into the flax params tree for one
    of our model families.  ``model_or_config``: a model-zoo module (its
    ``.config`` picks the family) or the config dataclass itself."""
    cfg = getattr(model_or_config, "config", model_or_config)
    name = type(cfg).__name__
    # subclass dispatch: MixtralConfig extends LlamaConfig
    for cls in type(cfg).__mro__:
        if cls.__name__ in _CONVERTERS:
            name = cls.__name__
            break
    if name not in _CONVERTERS:
        raise TypeError(f"no HF converter for config {type(cfg).__name__}; "
                        f"supported: {sorted(_CONVERTERS)}")
    sd = _read_state_dict(source)
    if name == "GPTNeoConfig":
        # the only family whose architecture (attention schedule) is
        # invisible in the weights — validate it from the source config
        return {"params": _CONVERTERS[name](
            sd, cfg, hf_config=_source_hf_config(source))}
    return {"params": _CONVERTERS[name](sd, cfg)}


def _source_hf_config(source):
    """The HF config riding along with ``source``: the model object's
    ``.config``, or a ``config.json`` next to directory checkpoints."""
    hf_cfg = getattr(source, "config", None)
    if hf_cfg is not None:
        return hf_cfg
    if isinstance(source, str) and os.path.isdir(source):
        p = os.path.join(source, "config.json")
        if os.path.exists(p):
            import json
            from types import SimpleNamespace

            with open(p) as f:
                return SimpleNamespace(**json.load(f))
    return None


def load_hf_checkpoint(model, source):
    """Reference ``init_inference(checkpoint=...)`` entry: returns params
    ready for ``deepspeed_tpu.init_inference(model, params=...)``."""
    return convert_hf_state_dict(model, source)
