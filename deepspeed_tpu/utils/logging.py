"""Logging utilities.

TPU-native equivalent of the reference's ``deepspeed/utils/logging.py``
(``logger``, ``log_dist(message, ranks=[...])``).  Process identity comes from
``jax.process_index()`` instead of ``torch.distributed`` ranks; inside a
single-controller JAX program every host process runs the same Python, so
rank-filtered logging is still the right primitive.
"""
from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = "DeepSpeedTPU", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        fmt = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
            datefmt="%Y-%m-%d %H:%M:%S",
        )
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(fmt)
        lg.addHandler(handler)
    env_level = os.environ.get("DSTPU_LOG_LEVEL", "").lower()
    if env_level in LOG_LEVELS:
        lg.setLevel(LOG_LEVELS[env_level])
    return lg


logger = _create_logger()


@functools.lru_cache(maxsize=None)
def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax not initialised yet
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process indices.

    ``ranks=None`` or ``ranks=[-1]`` logs on every process (matching the
    reference semantics of ``log_dist`` in ``deepspeed/utils/logging.py``).
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)


def get_log_level_from_string(s: str) -> int:
    return LOG_LEVELS.get(s.lower(), logging.INFO)
