from deepspeed_tpu.utils.logging import logger, log_dist, print_rank_0
from deepspeed_tpu.utils.timer import (
    SynchronizedWallClockTimer,
    ThroughputTimer,
    Timer,
)

__all__ = [
    "logger", "log_dist", "print_rank_0",
    "SynchronizedWallClockTimer", "ThroughputTimer", "Timer",
]
