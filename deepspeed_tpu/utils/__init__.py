from deepspeed_tpu.utils.logging import logger, log_dist, print_rank_0
from deepspeed_tpu.utils.tensor_fragment import (
    list_param_paths,
    safe_get_full_fp32_param,
    safe_get_full_grad,
    safe_get_full_optimizer_state,
    safe_get_local_fp32_param,
    safe_get_local_grad,
    safe_get_local_optimizer_state,
    safe_set_full_fp32_param,
    safe_set_full_optimizer_state,
)
from deepspeed_tpu.utils.timer import (
    SynchronizedWallClockTimer,
    ThroughputTimer,
    Timer,
)

__all__ = [
    "logger", "log_dist", "print_rank_0",
    "SynchronizedWallClockTimer", "ThroughputTimer", "Timer",
    "safe_get_full_fp32_param", "safe_set_full_fp32_param",
    "safe_get_full_optimizer_state", "safe_set_full_optimizer_state",
    "safe_get_full_grad", "safe_get_local_fp32_param",
    "safe_get_local_optimizer_state", "safe_get_local_grad",
    "list_param_paths",
]
