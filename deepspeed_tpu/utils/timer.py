"""Wall-clock and throughput timers.

TPU-native re-design of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` at :44, ``ThroughputTimer`` at :199).  Where
the reference uses CUDA events per stream, the XLA equivalent of
"synchronize" is blocking on the output buffers of the last dispatched
computation: ``jax.block_until_ready`` / ``jax.effects_barrier``.  All timers
are host-side; device-side timing belongs to the profiler
(``deepspeed_tpu.profiling``).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics
from deepspeed_tpu.utils.logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _device_sync() -> None:
    try:
        import jax

        jax.effects_barrier()
    except Exception:  # pragma: no cover
        pass


class Timer:
    """A single named timer with accumulated elapsed time."""

    def __init__(self, name: str, synchronize: bool = True):
        self.name = name
        self.started = False
        self.synchronize = synchronize
        self._start_time = 0.0
        self._elapsed = 0.0
        self._record_count = 0
        self.last_interval = 0.0
        self._hist = None
        self._hist_fam = None

    def _observe(self, seconds: float) -> None:
        if self._hist is None or self._hist_fam is not _metrics.get(
                "dstpu_engine_seconds"):
            self._hist_fam = _metrics.histogram(
                "dstpu_engine_seconds",
                "Engine wall-clock timer intervals (s)",
                labels=("timer",))
            self._hist = self._hist_fam.labels(timer=self.name)
        self._hist.observe(seconds)

    def start(self) -> None:
        assert not self.started, f"timer {self.name} already started"
        if self.synchronize:
            _device_sync()
        self._start_time = time.perf_counter()
        self.started = True

    def stop(self, record: bool = True) -> None:
        assert self.started, f"timer {self.name} not started"
        if self.synchronize:
            _device_sync()
        self.last_interval = time.perf_counter() - self._start_time
        self._elapsed += self.last_interval
        if record:
            self._record_count += 1
        self.started = False
        if trace.enabled:
            trace.add_complete(self.name, self._start_time,
                               self.last_interval, cat="engine")
        if _metrics.enabled:
            self._observe(self.last_interval)

    def discard(self) -> None:
        """Abandon an in-flight interval without recording it (and without
        touching the accumulated window, unlike :meth:`reset`)."""
        self.started = False

    def record(self, seconds: float) -> None:
        """Fold in an externally bracketed interval — for stages whose
        start/stop live inside another component (the swap pipeline's
        per-stage I/O waits are summed there and recorded here), where a
        start()/stop() pair would add a device sync per bucket."""
        assert not self.started, f"timer {self.name} is mid-interval"
        self.last_interval = seconds
        self._elapsed += seconds
        self._record_count += 1
        if trace.enabled:
            trace.add_complete(self.name, time.perf_counter() - seconds,
                               seconds, cat="engine")
        if _metrics.enabled:
            self._observe(seconds)

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._record_count = 0

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed time in seconds."""
        was_started = self.started
        if was_started:
            self.stop(record=False)
        out = self._elapsed
        if reset:
            self.reset()
        if was_started:
            self.start()
        return out

    def mean(self) -> float:
        if self._record_count == 0:
            return 0.0
        return self._elapsed / self._record_count


class SynchronizedWallClockTimer:
    """Group of named timers; mirrors the reference API (`timer.py:44`)."""

    def __init__(self):
        self.timers: Dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"Device mem in use {in_use:.2f} GB | peak {peak:.2f} GB"
        except Exception:  # pragma: no cover
            return "Device memory stats unavailable"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None) -> None:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            msg += " | " + self.memory_usage()
        logger.info(msg)

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        assert normalizer > 0.0
        return {
            name: self.timers[name].mean() * 1000.0 / normalizer
            for name in names if name in self.timers
        }


class ThroughputTimer:
    """Samples/sec + TFLOPs tracking (reference ``timer.py:199``)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.initialized = False

    def update_epoch_count(self) -> None:
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self) -> None:
        self.initialized = True

    def start(self) -> None:
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_sync()
            self.start_time = time.perf_counter()

    def stop(self, global_step: bool = False, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _device_sync()
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self.start_time = 0.0
            if global_step and report_speed and \
                    self.global_step_count % self.steps_per_output == 0:
                logger.info(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                    f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.2f}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            if self.total_elapsed_time > 0:
                return samples / self.total_elapsed_time
        return 0.0


def trim_mean(data: List[float], trim_percent: float) -> float:
    """Trimmed mean used by comms benchmarking (reference ``timer.py`` tail)."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    if n == 0:
        return 0.0
    data = sorted(data)
    k = int(round(n * trim_percent))
    trimmed = data[k: max(n - k, k + 1)]
    if not trimmed:
        trimmed = data
    return sum(trimmed) / len(trimmed)
