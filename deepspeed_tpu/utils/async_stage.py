"""Shared bounded-async-stage substrate for the host-side pipelines.

Three subsystems grew the same machinery by hand: the NVMe moment
stream (``runtime/swap_tensor.py`` — ``buffer_count`` read buffers with
B-1 reads in flight, a bounded write-back window, deferred writes
drained at forced points), the serving host path
(``inference/v2/ragged_engine.py`` — a device-resident carry bounded by
``async_depth`` with forced harvests), and the SDC digest side pool
(``runtime/swap_tensor.py`` keyed futures with selective joins).  This
module extracts the common skeleton so new pipelines (the tiered
paged-KV store, for one) compose it instead of re-growing it:

``BoundedAsyncStage``
    a bounded window of keyed in-flight async operations.  Submitting
    past the window's depth first joins the oldest op (back-pressure —
    the swap stream's write-depth bound).  ``drain()`` is the forced-
    drain point: joins EVERYTHING, collects results, raises the first
    error only after all ops are reaped (the ``_drain_deferred``
    invalidation contract — no op left silently in flight).  ``pop``
    is the selective join the SDC verify gates need: joins exactly one
    keyed op, never blocking on unrelated in-flight work.

``HostBufferPool``
    a fixed ring of page-aligned host staging buffers
    (:func:`deepspeed_tpu.io.aio.aligned_empty` — the O_DIRECT
    eligibility requirement) with the swap stream's reuse invariant:
    a slot is only reissued once its previous tenant is released.

``StageTimers``
    per-stage wall timers + counters in the shape the existing
    telemetry consumers expect (``stage_stats`` / ``serving_stages``
    style ``<stage>_s`` floats), so substrate users feed
    ``MonitorMaster`` without a new schema.

The substrate is deliberately loop-free: no worker thread of its own.
Asynchrony comes from whatever the caller submits (AIO ops, executor
futures, device transfers) — the substrate only bounds, times, and
drains it, which is why one abstraction fits IO rings and thread pools
alike.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics

__all__ = ["BoundedAsyncStage", "HostBufferPool", "StageTimers"]


class StageTimers:
    """Accumulating wall timers + counters, one bucket per stage name.

    ``snapshot()`` emits ``{f"{stage}_s": seconds}`` floats plus raw
    counters — the exact shape ``stage_stats`` / ``serving_stages``
    consumers (bench rows, ``MonitorMaster``) already flatten.  When
    the process tracer is enabled every bracket also lands as a span
    (``cat`` labels the subsystem row in the exported trace).
    """

    def __init__(self, cat: str = "host") -> None:
        self.seconds: Dict[str, float] = {}
        self.counters: Dict[str, int] = {}
        self.cat = cat
        self._hists: Dict[str, Any] = {}
        self._hist_fam = None

    def _hist(self, name: str):
        h = self._hists.get(name)
        if h is None or self._hist_fam is not _metrics.get(
                "dstpu_stage_seconds"):
            self._hist_fam = _metrics.histogram(
                "dstpu_stage_seconds",
                "Async-pipeline stage bracket durations (s)",
                labels=("cat", "stage"))
            h = self._hist_fam.labels(cat=self.cat, stage=name)
            self._hists[name] = h
        return h

    @contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            if trace.enabled:
                trace.add_complete(name, t0, dt, cat=self.cat)
            if _metrics.enabled:
                self._hist(name).observe(dt)

    def add(self, name: str, seconds: float) -> None:
        self.seconds[name] = self.seconds.get(name, 0.0) + seconds
        if trace.enabled:
            # externally bracketed: anchor at now-dt (approximate start)
            trace.add_complete(name, time.perf_counter() - seconds,
                               seconds, cat=self.cat)
        if _metrics.enabled:
            self._hist(name).observe(seconds)

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {f"{k}_s": round(v, 6)
                               for k, v in sorted(self.seconds.items())}
        out.update(sorted(self.counters.items()))
        return out

    def reset(self) -> None:
        self.seconds.clear()
        self.counters.clear()


class BoundedAsyncStage:
    """Bounded window of keyed in-flight async operations.

    Parameters
    ----------
    waiter:
        ``waiter(op) -> result`` joins one submitted op (e.g.
        ``aio_handle.wait`` or ``Future.result``).  It is the ONLY way
        an op completes from the substrate's point of view.
    depth:
        max ops in flight.  ``submit`` past this first joins the
        oldest op (back-pressure), recording the blocked time under
        the ``submit_wait`` stage — the swap stream's write-depth
        bound generalized.
    timers:
        optional shared :class:`StageTimers`; one is created if absent.
    poller:
        optional ``poller(op) -> bool`` non-blocking completion probe
        (e.g. wraps ``aio_handle.poll``).  Enables :meth:`ready`, the
        opportunistic-harvest check the swap read-ahead needs: consume
        completed reads in submission order without blocking on ones
        still in flight.
    """

    def __init__(self, waiter: Callable[[Any], Any], depth: int = 2,
                 timers: Optional[StageTimers] = None,
                 name: str = "stage",
                 poller: Optional[Callable[[Any], bool]] = None) -> None:
        self._waiter = waiter
        self._poller = poller
        self.depth = max(1, int(depth))
        self.name = name
        self.timers = timers if timers is not None else StageTimers()
        # key -> (op, on_done) in submission order (the window IS the
        # ordering — oldest-first joins keep slot-reuse invariants)
        self._inflight: "OrderedDict[Any, Tuple[Any, Any]]" = OrderedDict()

    # -- introspection ---------------------------------------------------

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    def __contains__(self, key: Any) -> bool:
        return key in self._inflight

    def keys(self) -> List[Any]:
        return list(self._inflight)

    def ready(self, key: Any) -> bool:
        """Non-blocking: would ``pop(key)`` return without waiting?
        Requires a ``poller``; a poller-less stage conservatively
        reports not-ready for every live key (callers fall back to
        their blocking join point).  Unknown keys are trivially ready
        (``pop`` would return the default immediately)."""
        ent = self._inflight.get(key)
        if ent is None:
            return True
        if self._poller is None:
            return False
        return bool(self._poller(ent[0]))

    # -- the three verbs -------------------------------------------------

    def submit(self, key: Any, op: Any,
               on_done: Optional[Callable[[Any], Any]] = None) -> None:
        """Track ``op`` under ``key``; joins the oldest op first if the
        window is full.  ``on_done(result)`` runs at join time (drain,
        pop, or back-pressure) — the place buffer-release / metadata
        folds live.  Re-submitting a live key joins the old op first
        (a key names a logical slot; two ops on one slot would race)."""
        if key in self._inflight:
            self.pop(key)
        while len(self._inflight) >= self.depth:
            with self.timers.stage("submit_wait"):
                self._join_oldest()
        self._inflight[key] = (op, on_done)
        self.timers.count("submitted")

    def pop(self, key: Any, default: Any = None) -> Any:
        """Selective join: complete exactly ``key``'s op (if live) and
        return its result, never touching unrelated in-flight work —
        the SDC verify-gate lookup."""
        ent = self._inflight.pop(key, None)
        if ent is None:
            return default
        return self._finish(key, ent)

    def drain(self) -> List[Any]:
        """Forced-drain point: join EVERYTHING in submission order.
        Every op is reaped even when one fails; the first error is
        re-raised after the sweep (the ``_drain_deferred`` contract —
        callers at invalidation points must not leave ops racing a
        reused buffer)."""
        results, first_err = [], None
        with self.timers.stage("drain"):
            while self._inflight:
                key, ent = next(iter(self._inflight.items()))
                del self._inflight[key]
                try:
                    results.append(self._finish(key, ent))
                except BaseException as e:   # noqa: BLE001 — re-raised
                    if first_err is None:
                        first_err = e
        if first_err is not None:
            raise first_err
        return results

    def discard(self, key: Any) -> bool:
        """Drop exactly one keyed op without joining (no waiter, no
        ``on_done``) — the per-key form of :meth:`abandon`, for folding
        a single entry whose backing device just failed (degraded-mode
        tiering) while the rest of the window stays live.  Returns
        whether the key was in flight."""
        return self._inflight.pop(key, None) is not None

    def abandon(self) -> int:
        """Discard every in-flight op WITHOUT joining (no waiter, no
        ``on_done``) — the hung-replica escape hatch: after a watchdog
        abandons a wedged worker thread its futures may never resolve,
        so joining them would re-wedge the caller.  Returns the number
        of ops dropped.  Only correct when the ops' side effects are
        already written off (the replica is dead)."""
        n = len(self._inflight)
        self._inflight.clear()
        return n

    # -- internals -------------------------------------------------------

    def _join_oldest(self) -> None:
        key, ent = next(iter(self._inflight.items()))
        del self._inflight[key]
        self._finish(key, ent)

    def _finish(self, key: Any, ent: Tuple[Any, Any]) -> Any:
        op, on_done = ent
        res = self._waiter(op)
        self.timers.count("completed")
        if on_done is not None:
            res = on_done(res)
        return res


class HostBufferPool:
    """Fixed ring of page-aligned host staging buffers.

    Reuse invariant (the swap read-path's): ``acquire`` hands out the
    ring slot AFTER the caller's ``release`` of its previous tenant —
    here enforced by construction: ``acquire`` raises if every slot is
    checked out, so a bounded pipeline (window depth < pool size) can
    never scribble over bytes an in-flight op still owns.
    """

    def __init__(self, count: int, nbytes: int) -> None:
        from deepspeed_tpu.io.aio import aligned_empty

        self.count = max(1, int(count))
        self.nbytes = int(nbytes)
        self._bufs = [aligned_empty(self.nbytes) for _ in range(self.count)]
        self._free = list(range(self.count))

    @property
    def free(self) -> int:
        return len(self._free)

    def acquire(self) -> Tuple[int, Any]:
        """``(slot, buffer)``; the buffer is the caller's until
        ``release(slot)``."""
        if not self._free:
            raise RuntimeError(
                f"HostBufferPool exhausted ({self.count} slots all "
                "checked out) — the in-flight window must drain before "
                "reusing a staging buffer")
        slot = self._free.pop()
        return slot, self._bufs[slot]

    def peek(self, slot: int) -> Any:
        """The slot's buffer (the holder's view while checked out)."""
        return self._bufs[slot]

    def release(self, slot: int) -> None:
        if slot in self._free:
            raise RuntimeError(f"HostBufferPool slot {slot} double-freed")
        self._free.append(slot)
