"""Sharding-constraint helper shared by layers that need explicit GSPMD
placement (MoE dispatch, pipeline state)."""
from __future__ import annotations

import jax


def maybe_constrain(x: jax.Array, spec) -> jax.Array:
    """``with_sharding_constraint`` against the installed topology's mesh;
    no-op when no topology is installed (meshless unit tests) or when the
    mesh lacks one of the spec's axes."""
    import deepspeed_tpu.comm as dist

    topo = dist.peek_topology()
    if topo is None:
        return x
    axes = {a for e in spec if e is not None
            for a in ((e,) if isinstance(e, str) else e)}
    if not axes.issubset(set(topo.mesh.axis_names)):
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(topo.mesh, P(*spec)))


def memory_space(kind: str):
    """``jax.device_put`` target for crossing memory KINDS (host↔device
    streaming), across jax versions: ``TransferToMemoryKind`` pre-0.9,
    the ``jax.memory.Space`` enum from 0.9."""
    if hasattr(jax.memory, "TransferToMemoryKind"):
        return jax.memory.TransferToMemoryKind(kind)
    return (jax.memory.Space.Device if kind == "device"
            else jax.memory.Space.Host)
