"""Sharding-constraint helper shared by layers that need explicit GSPMD
placement (MoE dispatch, pipeline state)."""
from __future__ import annotations

import jax


def maybe_constrain(x: jax.Array, spec) -> jax.Array:
    """``with_sharding_constraint`` against the installed topology's mesh;
    no-op when no topology is initialized (meshless unit tests)."""
    try:
        import deepspeed_tpu.comm as dist

        topo = dist.get_topology()
        if topo is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(topo.mesh, P(*spec)))
    except Exception:
        return x
