"""Version-compat shims for the jax API surface this framework uses.

The framework targets current jax (``jax.shard_map`` with ``check_vma``
/ ``axis_names``), but must degrade gracefully on the 0.4.x line where
shard_map still lives in ``jax.experimental`` and spells those knobs
``check_rep`` / ``auto``.  Keeping the translation in ONE place means
call sites write the modern spelling only.
"""
from __future__ import annotations

try:                                        # jax >= 0.5
    from jax import shard_map as _shard_map

    _MODERN = True
except ImportError:                         # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False


def get_abstract_mesh():
    """``jax.sharding.get_abstract_mesh()`` (the ambient mesh context)
    on any supported jax; ``None`` when no ambient mesh is set or the
    jax line predates the concept."""
    import jax

    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:
        try:
            from jax._src.mesh import get_abstract_mesh as _gam

            m = _gam()
            return m if getattr(m, "axis_names", None) else None
        except Exception:
            return None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` with the modern keyword surface on any
    supported jax.  ``axis_names`` (partial-manual) maps onto the old
    API's complementary ``auto`` set."""
    if _MODERN:
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)
    kw = {"check_rep": check_vma}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    mapped = _shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, **kw)
    if kw.get("auto"):
        # the 0.4.x EAGER impl raises NotImplementedError for partial
        # manual; the jit lowering supports it — stage the call
        import jax

        return jax.jit(mapped)
    return mapped
