"""Tensor-fragment debug APIs: inspect/patch sharded training state.

Re-design of the reference ``utils/tensor_fragment.py`` ``safe_get/set_*``
family (``:132 safe_get_full_fp32_param``, ``:164
safe_get_full_optimizer_state``, ``:199 safe_get_full_grad``, local
variants ``:243-299``).  The reference walks per-rank flat-buffer
fragments (``tensor_fragment`` bookkeeping) because ZeRO scatters
torch tensors by hand; under GSPMD a "fragment" is just the addressable
shard of a global ``jax.Array``, so:

- **full** variants materialize the whole (fp32 master) leaf on the host
  — jax assembles across shards/processes transparently;
- **local** variants return only this process's addressable shard(s) —
  no cross-host traffic, the debugging-at-scale path;
- **set** variants rebuild the engine state functionally (a new
  ``TrainState`` with the leaf replaced, placed against the existing
  sharding).

Parameters are addressed by pytree path — a ``"/"``-joined string like
``"transformer/h/attn/kernel"`` (the flax param tree layout) — instead of
a live tensor object.  Optimizer-state keys accept both torch-style
("exp_avg", "exp_avg_sq") and optax-style ("mu", "nu") names.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

PathLike = Union[str, Tuple[str, ...]]

_OPTIM_KEY_ALIASES = {
    "exp_avg": "mu", "exp_avg_sq": "nu",
    "momentum": "mu", "variance": "nu",
    "mu": "mu", "nu": "nu", "trace": "trace",
}


def _split(path: PathLike) -> Tuple[str, ...]:
    if isinstance(path, str):
        return tuple(p for p in path.split("/") if p)
    return tuple(path)


def _lookup(tree: Any, parts: Tuple[str, ...]) -> Any:
    node = tree
    for p in parts:
        if isinstance(node, (dict,)):
            if p not in node:
                raise KeyError(
                    f"path component {p!r} not found; available: "
                    f"{sorted(node)[:20]}")
            node = node[p]
        elif isinstance(node, (list, tuple)):
            node = node[int(p)]
        else:
            node = getattr(node, p)
    return node


def _replace(tree: Any, parts: Tuple[str, ...], value: Any) -> Any:
    """Functional leaf replacement along a dict path."""
    if not parts:
        return value
    if isinstance(tree, dict):
        new = dict(tree)
        new[parts[0]] = _replace(tree[parts[0]], parts[1:], value)
        return new
    if isinstance(tree, (list, tuple)):
        i = int(parts[0])
        items = list(tree)
        items[i] = _replace(items[i], parts[1:], value)
        return type(tree)(items) if not hasattr(tree, "_fields") else \
            type(tree)(*items)
    raise TypeError(f"cannot replace inside {type(tree)}")


def _param_leaf(engine, path: PathLike):
    return _lookup(engine.state.params, _split(path))


def _state_replace(state, **kw):
    rep = getattr(state, "_replace", None) or getattr(state, "replace")
    return rep(**kw)


def _moment_trees(engine) -> Dict[str, Any]:
    """Locate first/second-moment trees inside the optax state (chain
    tuples, ScaleByAdamState.mu/nu, trace, or the 1-bit OnebitState)."""
    found: Dict[str, Any] = {}

    def walk(node):
        for key in ("mu", "nu", "trace"):
            sub = getattr(node, key, None)
            if sub is not None and key not in found:
                found[key] = sub
        if isinstance(node, (tuple, list)):
            for item in node:
                walk(item)

    walk(engine.state.opt_state)
    return found


def list_param_paths(engine) -> List[str]:
    """All addressable param paths (debug discovery helper)."""
    flat = jax.tree_util.tree_flatten_with_path(engine.state.params)[0]
    return ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in kp) for kp, _ in flat]


# ---------------------------------------------------------------------------
# full (cross-shard) accessors
# ---------------------------------------------------------------------------

def safe_get_full_fp32_param(engine, path: PathLike) -> np.ndarray:
    """Assembled fp32 master value of one parameter (reference ``:132``)."""
    leaf = _param_leaf(engine, path)
    return np.asarray(jax.device_get(leaf)).astype(np.float32)


def safe_set_full_fp32_param(engine, path: PathLike, value) -> None:
    """Overwrite one parameter globally (reference ``:148``); the new
    value is placed against the leaf's existing sharding."""
    parts = _split(path)
    leaf = _param_leaf(engine, parts)
    value = jnp.asarray(value, leaf.dtype)
    assert value.shape == leaf.shape, (value.shape, leaf.shape)
    new_leaf = jax.device_put(value, leaf.sharding)
    engine.state = _state_replace(
        engine.state,
        params=_replace(engine.state.params, parts, new_leaf))

def safe_get_full_optimizer_state(engine, path: PathLike,
                                  optim_state_key: str
                                  ) -> Optional[np.ndarray]:
    """Assembled optimizer moment for one parameter (reference ``:164``)."""
    key = _OPTIM_KEY_ALIASES.get(optim_state_key)
    if key is None:
        raise KeyError(f"unknown optimizer state key {optim_state_key!r}; "
                       f"known: {sorted(_OPTIM_KEY_ALIASES)}")
    trees = _moment_trees(engine)
    if key not in trees:
        return None
    leaf = _lookup(trees[key], _split(path))
    return np.asarray(jax.device_get(leaf)).astype(np.float32)


def safe_set_full_optimizer_state(engine, path: PathLike, value,
                                  optim_state_key: str) -> None:
    """Overwrite one optimizer moment globally (reference ``:181``)."""
    key = _OPTIM_KEY_ALIASES[optim_state_key]
    parts = _split(path)

    def walk_replace(node):
        sub = getattr(node, key, None)
        if sub is not None:
            leaf = _lookup(sub, parts)
            new_leaf = jax.device_put(jnp.asarray(value, leaf.dtype),
                                      leaf.sharding)
            return node._replace(**{key: _replace(sub, parts, new_leaf)})
        if isinstance(node, tuple) and not hasattr(node, "_fields"):
            return tuple(walk_replace(item) for item in node)
        return node

    engine.state = _state_replace(
        engine.state,
        opt_state=walk_replace(engine.state.opt_state))


def safe_get_full_grad(engine, path: PathLike) -> Optional[np.ndarray]:
    """Assembled gradient of one parameter (reference ``:199``).  Only
    populated on the imperative fwd/bwd path between ``backward()`` and
    ``step()`` — the fused ``train_batch`` consumes gradients inside one
    compiled program and never exposes them (documented divergence)."""
    grads = getattr(engine, "_pending_grads", None)
    if grads is None:
        return None
    leaf = _lookup(grads, _split(path))
    return np.asarray(jax.device_get(leaf)).astype(np.float32)


# ---------------------------------------------------------------------------
# local (addressable-shard) accessors
# ---------------------------------------------------------------------------

def _local_shard(leaf) -> np.ndarray:
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    return shards[0] if len(shards) == 1 else np.stack(shards)


def safe_get_local_fp32_param(engine, path: PathLike) -> np.ndarray:
    """This process's shard(s) of a parameter (reference ``:269``)."""
    return _local_shard(_param_leaf(engine, path)).astype(np.float32)


def safe_get_local_optimizer_state(engine, path: PathLike,
                                   optim_state_key: str
                                   ) -> Optional[np.ndarray]:
    key = _OPTIM_KEY_ALIASES[optim_state_key]
    trees = _moment_trees(engine)
    if key not in trees:
        return None
    return _local_shard(_lookup(trees[key],
                                _split(path))).astype(np.float32)


def safe_get_local_grad(engine, path: PathLike) -> Optional[np.ndarray]:
    grads = getattr(engine, "_pending_grads", None)
    if grads is None:
        return None
    return _local_shard(_lookup(grads, _split(path))).astype(np.float32)
