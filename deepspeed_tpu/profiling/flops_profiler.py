"""FLOPs profiler: per-module FLOPs / MACs / params for any jittable fn.

TPU-native re-design of the reference flops profiler
(``profiling/flops_profiler/profiler.py:30 FlopsProfiler``, ``:1106
get_model_profile``).  The reference monkey-patches every
``torch.nn.functional`` to accumulate counts into module attributes as the
eager graph runs.  Under JAX the program IS data: we trace the function
once to a jaxpr and fold a FLOPs cost over its equations — no patching, no
runtime overhead, exact trip counts for ``scan`` — and attribute each
equation to its originating ``flax`` module via the compiler name stack
(``nn.Module`` scopes become ``named_scope`` entries on every equation).

Costs follow the reference's conventions (``profiler.py:518-806``): a
matmul is ``2 * out_numel * K`` FLOPs (MACs = half), convs count
``2 * out_numel * (Cin/groups * prod(kernel))``, elementwise/reduction ops
count one FLOP per output element, and everything unrecognized counts 0 —
the same "model FLOPs" definition BASELINE.md's MFU numbers use.

API mirrors the reference: :func:`get_model_profile` returns
``(flops, macs, params)`` for a flax module, and :class:`FlopsProfiler`
wraps an engine with ``start_profile / stop_profile / print_model_profile``
driven by ``flops_profiler`` config (``profile_step``, ``module_depth``,
``top_modules``, ``detailed``, ``output_file``).
"""
from __future__ import annotations

import math
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

# elementwise / reduction primitives billed at 1 FLOP per output element
_ONE_PER_ELEMENT = {
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "neg", "abs",
    "exp", "log", "log1p", "expm1", "tanh", "logistic", "erf", "erf_inv",
    "erfc", "rsqrt", "sqrt", "cbrt", "sin", "cos", "tan", "sign", "floor",
    "ceil", "round", "integer_pow", "atan2", "and", "or", "xor", "not",
    "select_n", "clamp", "nextafter", "square",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin",
           "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"}
# structural ops: 0 FLOPs (data movement; billed as bytes, not flops)
_CALL_PRIMS = {"pjit", "jit", "closed_call", "core_call", "remat",
               "checkpoint", "custom_jvp_call", "custom_vjp_call",
               "custom_vjp_call_jaxpr", "custom_jvp_call_jaxpr",
               "shard_map", "smap", "xla_call"}


def _numel(shape) -> int:
    return int(np.prod(shape)) if shape else 1


@dataclass
class _Node:
    """Aggregated cost for one module-path prefix."""
    flops: float = 0.0
    macs: float = 0.0
    params: int = 0
    children: Dict[str, "_Node"] = field(default_factory=dict)

    def child(self, name: str) -> "_Node":
        return self.children.setdefault(name, _Node())


def _dot_cost(eqn) -> Tuple[float, float]:
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    out = eqn.outvars[0].aval.shape
    k = _numel([lhs[d] for d in lhs_c])
    macs = _numel(out) * k
    return 2.0 * macs, float(macs)


def _conv_cost(eqn) -> Tuple[float, float]:
    rhs = eqn.invars[1].aval.shape  # kernel
    out = eqn.outvars[0].aval.shape
    dn = eqn.params["dimension_numbers"]
    groups = int(eqn.params.get("feature_group_count", 1))
    kernel_spatial = [rhs[d] for d in dn.rhs_spec[2:]]
    del groups  # rhs channel dim is already Cin/groups in lax convention
    cin = rhs[dn.rhs_spec[1]]
    macs = _numel(out) * cin * _numel(kernel_spatial)
    return 2.0 * macs, float(macs)


def _eqn_cost(eqn) -> Tuple[float, float]:
    """(flops, macs) of one non-call equation."""
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_cost(eqn)
    if name in ("conv_general_dilated",):
        return _conv_cost(eqn)
    out_numel = _numel(eqn.outvars[0].aval.shape) if eqn.outvars else 0
    if name in _ONE_PER_ELEMENT:
        return float(out_numel), 0.0
    if name in _REDUCE:
        return float(_numel(eqn.invars[0].aval.shape)), 0.0
    if name in ("reduce_precision", "convert_element_type"):
        return 0.0, 0.0
    return 0.0, 0.0


def _accumulate(root: _Node, path: List[str], flops: float,
                macs: float) -> None:
    node = root
    node.flops += flops
    node.macs += macs
    for part in path:
        node = node.child(part)
        node.flops += flops
        node.macs += macs


def _name_path(eqn, prefix: List[str]) -> List[str]:
    stack = str(eqn.source_info.name_stack)
    parts = [p for p in stack.split("/") if p] if stack else []
    return prefix + parts


def _walk(jaxpr, root: _Node, prefix: List[str], repeat: float) -> None:
    for eqn in jaxpr.eqns:
        path = _name_path(eqn, prefix)
        name = eqn.primitive.name
        sub = None
        factor = repeat
        if name == "scan":
            sub = eqn.params["jaxpr"].jaxpr
            factor = repeat * int(eqn.params.get("length", 1))
        elif name == "while":
            # trip count is dynamic; bill one iteration (documented)
            sub = eqn.params["body_jaxpr"].jaxpr
        elif name == "cond":
            # bill the most expensive branch
            branches = eqn.params["branches"]
            costs = []
            for br in branches:
                tmp = _Node()
                _walk(br.jaxpr, tmp, path, repeat)
                costs.append(tmp)
            if costs:
                best = max(costs, key=lambda n: n.flops)
                _merge(root, best)
            continue
        elif "jaxpr" in eqn.params and hasattr(eqn.params["jaxpr"], "jaxpr"):
            sub = eqn.params["jaxpr"].jaxpr
        elif "call_jaxpr" in eqn.params:
            cj = eqn.params["call_jaxpr"]
            sub = cj.jaxpr if hasattr(cj, "jaxpr") else cj
        elif "fun_jaxpr" in eqn.params:
            sub = eqn.params["fun_jaxpr"].jaxpr
        if sub is not None:
            # sub-jaxpr name stacks are relative to the call site, hence
            # the prefix threading; costs stay rooted at `root`
            _walk(sub, root, path, factor)
            continue
        flops, macs = _eqn_cost(eqn)
        _accumulate(root, path, flops * factor, macs * factor)


def _merge(dst: _Node, src: _Node) -> None:
    dst.flops += src.flops
    dst.macs += src.macs
    for k, v in src.children.items():
        _merge(dst.child(k), v)


def _param_counts(params: Any, root: _Node,
                  root_name: Optional[str] = None) -> None:
    if params is None:
        return
    import jax.tree_util as jtu

    flat = jtu.tree_flatten_with_path(params)[0]
    for kp, leaf in flat:
        if not hasattr(leaf, "size"):
            continue
        parts = [str(getattr(k, "key", getattr(k, "idx", k))) for k in kp]
        # drop the collection name ('params') and prepend the root module
        # scope so paths line up with the name-stack module paths
        if parts and parts[0] in ("params", "batch_stats", "cache"):
            parts = parts[1:]
        if root_name:
            parts = [root_name] + parts
        node = root
        node.params += int(leaf.size)
        for p in parts[:-1]:  # last part is the leaf array name
            node = node.child(p)
            node.params += int(leaf.size)


def profile_fn(fn: Callable, *args, params: Any = None,
               root_name: Optional[str] = None,
               static_argnums=(), **kwargs) -> _Node:
    """Trace ``fn(*args, **kwargs)`` and return the module-path cost tree
    (flops/macs per flax scope; params attributed when ``params`` given)."""
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(
        *args, **kwargs)
    root = _Node()
    _walk(closed.jaxpr, root, [], 1.0)
    _param_counts(params, root, root_name)
    return root


# ---------------------------------------------------------------------------
# human-readable output (reference profiler.py:845-906 formatting helpers)
# ---------------------------------------------------------------------------

def _si(val: float, unit: str = "") -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(val) >= scale:
            return f"{val / scale:.2f} {suffix}{unit}"
    return f"{val:.2f} {unit}"


def params_to_string(n: int) -> str:
    return _si(float(n)).strip()


def flops_to_string(n: float) -> str:
    return _si(n, "FLOPs")


def macs_to_string(n: float) -> str:
    return _si(n, "MACs")


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` surface).

    ``start_profile()`` arms it; the engine calls :meth:`profile_step`
    once per step with the step callable + args; at the configured
    ``profile_step`` the cost tree is computed and
    :meth:`print_model_profile` renders the breakdown.
    """

    def __init__(self, fn: Optional[Callable] = None, ds_engine=None,
                 recompute_fwd_factor: float = 0.0):
        self.fn = fn
        self.ds_engine = ds_engine
        self.recompute_fwd_factor = recompute_fwd_factor
        self.started = False
        self._tree: Optional[_Node] = None
        self._duration: float = 0.0

    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self._tree = None

    def stop_profile(self) -> None:
        self.started = False

    def end_profile(self) -> None:
        self.stop_profile()
        self._tree = None

    # -- measurement --------------------------------------------------

    def profile(self, *args, params: Any = None, duration: float = 0.0,
                root_name: Optional[str] = None, **kwargs) -> None:
        assert self.fn is not None, "no function to profile"
        self._tree = profile_fn(self.fn, *args, params=params,
                                root_name=root_name, **kwargs)
        self._duration = duration

    # -- accessors (reference names) ----------------------------------

    def get_total_flops(self, as_string: bool = False):
        t = self._tree.flops if self._tree else 0.0
        return flops_to_string(t) if as_string else t

    def get_total_macs(self, as_string: bool = False):
        t = self._tree.macs if self._tree else 0.0
        return macs_to_string(t) if as_string else t

    def get_total_params(self, as_string: bool = False):
        t = self._tree.params if self._tree else 0
        return params_to_string(t) if as_string else t

    def get_total_duration(self, as_string: bool = False):
        return (f"{self._duration * 1e3:.2f} ms" if as_string
                else self._duration)

    # -- rendering ----------------------------------------------------

    def print_model_profile(self, profile_step: int = 1,
                            module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        if self._tree is None:
            return
        out = open(output_file, "w") if output_file else sys.stdout
        try:
            total = self._tree
            print("-" * 72, file=out)
            print("DeepSpeed-TPU Flops Profiler", file=out)
            print(f"profile step:                   {profile_step}",
                  file=out)
            print(f"params:                         "
                  f"{params_to_string(total.params)}", file=out)
            print(f"fwd+bwd+step flops:             "
                  f"{flops_to_string(total.flops)}", file=out)
            print(f"fwd+bwd+step MACs:              "
                  f"{macs_to_string(total.macs)}", file=out)
            if self._duration > 0:
                print(f"step latency:                   "
                      f"{self._duration * 1e3:.2f} ms", file=out)
                print(f"achieved:                       "
                      f"{_si(total.flops / self._duration, 'FLOPS')}",
                      file=out)
            if detailed:
                print("\nper-module breakdown "
                      "(flops | MACs | params):", file=out)
                self._print_tree(total, out, depth=0,
                                 max_depth=module_depth, top_modules=0)
            if top_modules > 0:
                print(f"\ntop {top_modules} modules per depth by flops:",
                      file=out)
                self._print_tree(total, out, depth=0,
                                 max_depth=module_depth,
                                 top_modules=top_modules)
            print("-" * 72, file=out)
        finally:
            if output_file:
                out.close()

    def print_model_aggregated_profile(self, module_depth: int = -1,
                                       top_modules: int = 1) -> None:
        self.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules, detailed=True)

    def _print_tree(self, node: _Node, out, depth: int, max_depth: int,
                    top_modules: int, name: str = "model") -> None:
        if max_depth >= 0 and depth > max_depth:
            return
        pad = "  " * depth
        print(f"{pad}{name}: {flops_to_string(node.flops)} | "
              f"{macs_to_string(node.macs)} | "
              f"{params_to_string(node.params)}", file=out)
        ranked = sorted(node.children.items(),
                        key=lambda kv: kv[1].flops, reverse=True)
        # top_modules bounds how many children print per level (reference
        # print_model_aggregated_profile semantics); <=0 means all
        limit = len(ranked) if top_modules <= 0 else min(top_modules,
                                                         len(ranked))
        for child_name, child in ranked[:limit]:
            self._print_tree(child, out, depth + 1, max_depth,
                             top_modules, child_name)


def get_model_profile(model, input_shape: Optional[Tuple[int, ...]] = None,
                      args: Tuple = (), kwargs: Optional[Dict] = None,
                      print_profile: bool = True, detailed: bool = True,
                      module_depth: int = -1, top_modules: int = 1,
                      as_string: bool = True,
                      output_file: Optional[str] = None,
                      rng=None):
    """Profile a flax module's forward pass; returns (flops, macs, params)
    — the reference ``get_model_profile`` contract
    (``flops_profiler/profiler.py:1106``)."""
    import jax.numpy as jnp

    kwargs = kwargs or {}
    if input_shape is not None:
        assert not args, "pass input_shape or args, not both"
        args = (jnp.ones(input_shape, jnp.float32),)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    # abstract trace only: ShapeDtypeStructs never allocate, so 7B-class
    # modules profile without materializing parameters
    variables = jax.eval_shape(lambda: model.init(rng, *args, **kwargs))

    prof = FlopsProfiler(lambda v, *a: model.apply(v, *a, **kwargs))
    prof.start_profile()
    prof.profile(variables, *args,
                 params=variables.get("params", variables),
                 root_name=type(model).__name__)
    if print_profile:
        prof.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules,
                                 detailed=detailed,
                                 output_file=output_file)
    flops, macs, params = (prof.get_total_flops(as_string),
                           prof.get_total_macs(as_string),
                           prof.get_total_params(as_string))
    prof.end_profile()
    return flops, macs, params
