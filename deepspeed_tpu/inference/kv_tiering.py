"""Tiered paged-KV store: HBM -> host RAM -> NVMe spill tiers.

Concurrent serving sessions are capped by HBM because KV pages live
only in the device pool (``inference/paged.py``): an idle or page-
stalled session holds hot memory until the engine evicts it and repays
its whole prefill from scratch.  This store extends the pool past HBM
the same way ZeRO-Infinity extends optimizer state past device memory:

    HBM (PageAllocator pool)  --spill-->  host RAM  --overflow-->  NVMe
         live decode pages         pinned page-aligned       bucketed AIO
                                   staging buffers           qd-128 files

A spilled sequence's pages are packed page-major into a page-aligned
host buffer (one contiguous slice per page across every cache leaf,
stride padded to the 4096-byte O_DIRECT alignment), digested per page
(``resilience/sdc.py`` — the spill path trusts neither host DRAM nor
disk), and demoted to NVMe through the hardened AIO path (qd-128,
optional O_DIRECT, fallocate preallocation) when the host budget
overflows.  Restore verifies every page against its spill-time digest
behind the ``kv.read_page`` fault hook: a transient flip heals via
re-read (NVMe) / re-copy (host tier), persistent corruption
quarantines the spilled payload (``.quarantine`` rename for
postmortem, like the swap and checkpoint layers) and raises
:class:`KVRestoreError` so the engine re-prefills loudly instead of
decoding on garbage.

All asynchrony (NVMe write-back, predictive NVMe->host prefetch under
the decode block) runs on the shared bounded-async-stage substrate
(``utils/async_stage.py``): bounded in-flight windows, forced-drain
points, per-stage timers in the existing telemetry schema.

**Degraded mode** (serving fault tolerance): a failing NVMe device must
not take serving down with it.  ``nvme_fail_threshold`` hard NVMe
failures since the last clean probe (EIO/ENOSPC at write submit or
cold read, or a quarantine of an NVMe-backed payload) trip the tier
OFFLINE:
``can_spill``/``_demote`` fall back host-only, every parked NVMe-backed
payload is folded (its session re-prefills via
:class:`KVRestoreError` on the next restore — loud, never silent), and
a ``tier_degraded`` flight record + trace event + metric mark the
trip.  While offline, blocked spills periodically run
:meth:`probe_nvme` — a write/read/verify round-trip through the same
``kv.write`` fault hook as the spill path — and a clean probe re-arms
the tier (``tier_rearmed``).

The store holds HOST STATE ONLY — device-side gather/scatter of pages
stays in the engine (it owns the cache pytree and the jitted
fixed-shape programs).  The unit of exchange is a list of per-leaf
``[n_pages, *leaf_page_shape]`` numpy arrays.
"""
from __future__ import annotations

import atexit
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple, Union

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.sdc import DigestPool, digest as sdc_digest
from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.utils.async_stage import (BoundedAsyncStage,
                                             HostBufferPool, StageTimers)
from deepspeed_tpu.utils.logging import logger

__all__ = ["TieredKVStore", "KVRestoreError"]

# Payload key: a request uid (int) for sequence spills, or an opaque
# string for payloads owned by other subsystems — the prefix cache
# demotes index pages under their prefix-hash key ("pfx-<hash>"), so
# one tier entry serves every future requester of that prefix.  Keys
# of both types coexist in one store (dict keys; the spill filename
# embeds the key via str()).
Key = Union[int, str]

_ALIGN = 4096                        # O_DIRECT / page alignment


class KVRestoreError(RuntimeError):
    """A spilled page failed verification beyond recovery; the payload
    is quarantined and the session must re-prefill."""

    def __init__(self, uid: "Key", page: int, msg: str) -> None:
        super().__init__(msg)
        self.uid = uid
        self.page = page


class _Entry:
    """One spilled sequence's payload in the tiers."""

    __slots__ = ("uid", "n_pages", "state", "buf", "slot", "path",
                 "digests", "seq")

    def __init__(self, uid: "Key", n_pages: int) -> None:
        self.uid = uid
        self.n_pages = n_pages
        self.state = "host"         # host | writing | nvme | reading
        self.buf: Optional[np.ndarray] = None   # packed bytes (host/writing)
        self.slot: Optional[int] = None          # staging pool slot (reading)
        self.path: Optional[str] = None          # spill file (writing/nvme)
        self.digests: Optional[List[tuple]] = None  # per-page (sum, nbytes)
        self.seq = 0                # spill order (demotion picks oldest)


class TieredKVStore:
    """Host-RAM + NVMe spill tiers for paged KV, per-page verified.

    Parameters
    ----------
    page_shapes / page_dtypes:
        per cache leaf (flattened tree order): the per-PAGE shape
        (leaf shape minus the leading page dim) and numpy dtype.  They
        fix the packed layout; the engine owns the treedef.
    host_pages / nvme_pages:
        tier budgets in KV pages (0 disables the tier).
    """

    def __init__(self, *, page_shapes: Sequence[tuple],
                 page_dtypes: Sequence[Any], pages_per_seq: int,
                 host_pages: int, nvme_pages: int = 0,
                 nvme_dir: Optional[str] = None, use_odirect: bool = False,
                 prefetch: bool = True, verify: bool = True,
                 checksum: str = "sum64", max_reread: int = 2,
                 write_depth: int = 4, read_depth: int = 2,
                 nvme_fail_threshold: int = 3,
                 probe_every: int = 8) -> None:
        self.pages_per_seq = int(pages_per_seq)
        self.host_budget = int(host_pages)
        self.nvme_budget = int(nvme_pages)
        self.verify = bool(verify)
        self.algo = str(checksum)
        self.max_reread = max(0, int(max_reread))
        self.prefetch_enabled = bool(prefetch) and self.nvme_budget > 0
        self.use_odirect = bool(use_odirect)
        # degraded mode: nvme_fail_threshold hard NVMe failures since
        # the last clean probe trip the tier offline (host-only until a
        # clean probe_nvme round-trip re-arms it, attempted every
        # probe_every spills blocked on the missing tier)
        self.nvme_fail_threshold = max(1, int(nvme_fail_threshold))
        self.probe_every = max(1, int(probe_every))
        self.nvme_offline = False
        self._nvme_streak = 0        # hard failures since last clean probe
        self._probe_backoff = 0      # blocked spills since last probe
        self._lost: Set[Key] = set()  # folded at trip: restore re-prefills

        # packed page layout: each leaf's bytes at a fixed offset inside
        # the page's stride-aligned slice (padding zeroed at pack time
        # so digests and spill files are deterministic)
        self._shapes = [tuple(s) for s in page_shapes]
        self._dtypes = [np.dtype(d) for d in page_dtypes]
        self._widths = [int(np.prod(s)) * d.itemsize
                        for s, d in zip(self._shapes, self._dtypes)]
        self._offsets = list(np.cumsum([0] + self._widths[:-1]).astype(int))
        used = int(sum(self._widths))
        self.page_stride = (used + _ALIGN - 1) // _ALIGN * _ALIGN
        self._used_bytes = used

        # tier state
        self._entries: Dict[Key, _Entry] = {}
        self._host_used = 0          # pages resident in host buffers
        self._nvme_used = 0          # pages on (or being written to) NVMe
        self._seq = 0

        # substrate: timers + digest side pool + bounded IO windows
        # (cat="kv" labels every bracket's trace span — spill/restore
        # stalls and the partial-residency page-in waits all land on
        # the kv row of the exported trace)
        self.timers = StageTimers(cat="kv")
        self._digests = DigestPool(algo=self.algo, workers=2,
                                   timers=self.timers,
                                   thread_name_prefix="dstpu-kvtier")
        self._aio = None             # lazy aio_handle (NVMe tier only)
        self._writes = BoundedAsyncStage(self._wait_op, depth=write_depth,
                                         timers=self.timers, name="kv-write")
        self._reads = BoundedAsyncStage(self._wait_op, depth=read_depth,
                                        timers=self.timers, name="kv-read")
        # staging ring for NVMe reads (prefetch + sync restore); writes
        # stream from the entry's own buffer, which stays alive (and
        # immutable) until the bounded window joins the op
        self._staging: Optional[HostBufferPool] = None

        self.counters: Dict[str, int] = {
            "spills": 0, "restores": 0, "pages_spilled": 0,
            "pages_restored": 0, "pages_verified": 0, "demotions": 0,
            "nvme_spills": 0, "prefetch_hits": 0, "prefetch_misses": 0,
            "rereads": 0, "reread_recovered": 0, "quarantined": 0,
            "spill_fallbacks": 0, "bytes_spilled": 0, "bytes_restored": 0,
            "exports": 0, "imports": 0,
            # partial-residency page-in (peek): parked middles streamed
            # through staging without dropping the tier entry
            "pageins": 0, "pagein_pages": 0, "pagein_bytes": 0,
            "pagein_prefetch_hits": 0, "pagein_prefetch_misses": 0,
            # degraded mode (persistent NVMe failure -> host-only)
            "nvme_failures": 0, "tier_degraded": 0, "tier_rearmed": 0,
            "degraded_folds": 0, "probes": 0, "probe_failures": 0}
        self._pagein_hist = None

        self.spill_dir: Optional[str] = None
        if self.nvme_budget > 0:
            if not nvme_dir:
                raise ValueError("nvme_pages > 0 requires nvme_dir")
            os.makedirs(nvme_dir, exist_ok=True)
            self.spill_dir = tempfile.mkdtemp(prefix="kvtier-",
                                              dir=nvme_dir)
            atexit.register(shutil.rmtree, self.spill_dir,
                            ignore_errors=True)

    # -- substrate plumbing ----------------------------------------------

    def _wait_op(self, op: int) -> int:
        return self._handle().wait(op)

    def _handle(self):
        if self._aio is None:
            from deepspeed_tpu.io.aio import aio_handle

            self._aio = aio_handle(queue_depth=128, thread_count=2,
                                   use_odirect=self.use_odirect)
        return self._aio

    def _stage_ring(self) -> HostBufferPool:
        if self._staging is None:
            self._staging = HostBufferPool(
                self._reads.depth + 1,
                self.pages_per_seq * self.page_stride)
        return self._staging

    # -- capacity --------------------------------------------------------

    @property
    def budget_pages(self) -> int:
        """Total spill capacity in pages (what put_request may admit
        beyond the HBM pool)."""
        return self.host_budget + self.nvme_budget

    def free_pages(self) -> int:
        nvme_budget = 0 if self.nvme_offline else self.nvme_budget
        return ((self.host_budget - self._host_used)
                + max(0, nvme_budget - self._nvme_used))

    def can_spill(self, n_pages: int) -> bool:
        """Whether a ``n_pages`` spill can land somewhere (host, or
        host-after-demotion, or straight to NVMe).  With the NVMe tier
        offline (degraded mode) only the host budget counts; a spill
        blocked on the missing tier periodically triggers a
        :meth:`probe_nvme` revival attempt."""
        if self.nvme_offline and self.nvme_budget > 0:
            host_free = self.host_budget - self._host_used
            if n_pages <= self.host_budget and host_free >= n_pages:
                return True
            # the dead tier is the binding constraint: probe for revival
            self._probe_backoff += 1
            if self._probe_backoff >= self.probe_every:
                self._probe_backoff = 0
                if not self.probe_nvme():
                    return False
                # fall through re-armed
            else:
                return False
        if n_pages > max(self.host_budget,
                         0 if self.nvme_offline else self.nvme_budget):
            return False
        return self.free_pages() >= n_pages

    def holds(self, uid: Key) -> bool:
        return uid in self._entries

    # -- spill -----------------------------------------------------------

    def spill(self, uid: Key, arrs: List[np.ndarray],
              n_pages: int) -> None:
        """Take ownership of ``uid``'s pages (per-leaf
        ``[n_pages, ...]`` host arrays), digest them, and park them in
        the cheapest tier with room.  Raises ``RuntimeError`` when no
        tier fits (caller falls back to destructive eviction)."""
        assert uid not in self._entries, f"uid {uid} already spilled"
        if not self.can_spill(n_pages):
            self.counters["spill_fallbacks"] += 1
            raise RuntimeError(
                f"kv tiers full: need {n_pages} pages, host "
                f"{self.host_budget - self._host_used}/{self.host_budget} "
                f"nvme {self.nvme_budget - self._nvme_used}/"
                f"{self.nvme_budget} free")
        with self.timers.stage("spill"):
            ent = _Entry(uid, n_pages)
            self._seq += 1
            ent.seq = self._seq
            with self.timers.stage("spill_pack"):
                buf = self._pack(arrs, n_pages)
            ent.buf = buf
            host_free = self.host_budget - self._host_used
            try:
                if n_pages <= self.host_budget:
                    # host tier (demote oldest entries to make room)
                    if n_pages > host_free:
                        self._demote(n_pages - host_free)
                    self._entries[uid] = ent
                    self._host_used += n_pages
                else:
                    # oversized for host RAM: straight to NVMe
                    self._entries[uid] = ent
                    self._nvme_spill(ent)
            except RuntimeError:
                self._entries.pop(uid, None)
                self.counters["spill_fallbacks"] += 1
                raise
            # write-side digests overlap the write-back IO: the packed
            # buffer is immutable until the entry is restored or its
            # write is joined, so the side job races nothing
            if self.verify:
                self._digests.submit(
                    uid, lambda: [sdc_digest(b, self.algo)
                                  for b in buf.reshape(
                                      n_pages, self.page_stride)])
            self.counters["spills"] += 1
            self.counters["pages_spilled"] += n_pages
            self.counters["bytes_spilled"] += buf.nbytes

    def _pack(self, arrs: List[np.ndarray], n_pages: int) -> np.ndarray:
        from deepspeed_tpu.io.aio import aligned_empty

        buf = aligned_empty(n_pages * self.page_stride)
        b2 = buf.reshape(n_pages, self.page_stride)
        b2[:, self._used_bytes:] = 0
        for a, off, w in zip(arrs, self._offsets, self._widths):
            a = np.ascontiguousarray(a)
            b2[:, off:off + w] = a.reshape(n_pages, -1).view(np.uint8)
        return buf

    def _unpack(self, buf: np.ndarray, n_pages: int) -> List[np.ndarray]:
        b2 = buf[:n_pages * self.page_stride].reshape(n_pages,
                                                      self.page_stride)
        out = []
        for s, d, off, w in zip(self._shapes, self._dtypes,
                                self._offsets, self._widths):
            raw = np.ascontiguousarray(b2[:, off:off + w])
            out.append(raw.view(d).reshape((n_pages,) + s))
        return out

    # -- NVMe write-back -------------------------------------------------

    def _fname(self, uid: Key) -> str:
        return os.path.join(self.spill_dir, f"kv-{uid}.bin")

    def _nvme_spill(self, ent: _Entry) -> None:
        """Queue ``ent``'s buffer for NVMe write-back on the bounded
        window (fallocate sizes the file up-front inside async_pwrite;
        the buffer stays alive until the window joins the op).  A hard
        IO error at submit (or injected at the ``kv.write`` fault site)
        feeds the degraded-mode failure streak and raises
        ``RuntimeError`` so callers take their existing no-room
        fallback paths."""
        assert self.spill_dir is not None
        if self.nvme_offline:
            raise RuntimeError(
                "kv tiering: NVMe tier offline (degraded mode)")
        path = self._fname(ent.uid)
        try:
            d = faults.hook("kv.write", uid=ent.uid, path=path)
            if d is not None and d[0] in ("hang", "slow"):
                time.sleep(float(d[1]))
            with self.timers.stage("spill_write_submit"):
                op = self._handle().async_pwrite(ent.buf, path)
        except OSError as e:
            self._nvme_failure(e, f"write-back submit for uid {ent.uid}")
            raise RuntimeError(
                f"kv tiering: NVMe write-back failed for uid "
                f"{ent.uid}: {e}") from e
        ent.path = path
        ent.state = "writing"
        self._nvme_used += ent.n_pages
        buf = ent.buf               # keep a ref until the join

        def _done(_st, ent=ent, buf=buf):
            del buf
            if ent.state == "writing":      # not restored meanwhile
                ent.state = "nvme"
                ent.buf = None
            return _st

        self._writes.submit(("w", ent.uid), op, on_done=_done)
        self.counters["nvme_spills"] += 1

    def _demote(self, need_pages: int) -> None:
        """Move the oldest host-resident entries to NVMe until
        ``need_pages`` of host budget are free."""
        if self.nvme_offline:
            raise RuntimeError(
                "kv tiering: cannot demote — NVMe tier offline "
                "(degraded mode)")
        moved = 0
        for ent in sorted((e for e in self._entries.values()
                           if e.state == "host"), key=lambda e: e.seq):
            if moved >= need_pages:
                break
            if self.nvme_budget - self._nvme_used < ent.n_pages:
                continue
            self._nvme_spill(ent)
            self._host_used -= ent.n_pages
            self.counters["demotions"] += 1
            moved += ent.n_pages
        if moved < need_pages:
            raise RuntimeError(
                f"kv tiering: could not demote {need_pages} pages to "
                "NVMe (budget full)")

    # -- prefetch --------------------------------------------------------

    def prefetch(self, uids: Sequence[Key]) -> int:
        """Issue async NVMe->host reads for predicted next-scheduled
        spilled sequences; returns how many were started.  Runs under
        the decode block so restores overlap device work."""
        if not self.prefetch_enabled:
            return 0
        started = 0
        for uid in uids:
            ent = self._entries.get(uid)
            if ent is None or ent.state != "nvme":
                continue
            if self._stage_ring().free == 0:
                break
            slot, sbuf = self._stage_ring().acquire()
            ent.slot = slot
            ent.state = "reading"
            with self.timers.stage("prefetch_submit"):
                op = self._handle().async_pread(
                    sbuf[:ent.n_pages * self.page_stride], ent.path)
            self._reads.submit(("r", uid), op)
            started += 1
        return started

    # -- restore ---------------------------------------------------------

    def restore(self, uid: Key) -> List[np.ndarray]:
        """Hand back ``uid``'s pages as per-leaf ``[n_pages, ...]``
        arrays, each page verified against its spill-time digest (when
        ``verify``).  Drops the entry on success — the pages are HBM's
        again.  Raises :class:`KVRestoreError` after quarantining on
        unrecoverable corruption (the caller re-prefills loudly)."""
        self._check_lost(uid)
        ent = self._entries.get(uid)
        assert ent is not None, f"uid {uid} not spilled"
        with self.timers.stage("restore"):
            work = self._fetch(ent)
            digests = self._digests.pop(uid) if self.verify else None
            if self.verify:
                with self.timers.stage("restore_verify"):
                    self._verify_pages(ent, work, digests)
            arrs = self._unpack(work, ent.n_pages)
        self._drop(ent)
        self.counters["restores"] += 1
        self.counters["pages_restored"] += ent.n_pages
        self.counters["bytes_restored"] += ent.n_pages * self.page_stride
        return arrs

    def peek(self, uid: Key) -> List[np.ndarray]:
        """Read ``uid``'s pages WITHOUT dropping the tier entry — the
        partial-residency page-in.  A parked middle group streams
        through the staging ring into the chunked attention scan every
        tick, while the tier copy (host buffer or NVMe file) stays
        authoritative, so nothing is re-spilled afterwards.  Pages are
        digest-verified exactly like :meth:`restore` (transient flips
        heal by re-read; persistent corruption quarantines and raises
        :class:`KVRestoreError`).  The blocking wait is observed as the
        ``pagein_wait`` stage (a ``cat="kv"`` trace span) and the
        ``dstpu_kv_pagein_stall_ms`` histogram."""
        self._check_lost(uid)
        ent = self._entries.get(uid)
        assert ent is not None, f"uid {uid} not spilled"
        was = ent.state
        t0 = time.perf_counter()
        with self.timers.stage("pagein_wait"):
            work = self._fetch(ent)
            digests = self._digests.pop(uid) if self.verify else None
            if self.verify:
                with self.timers.stage("pagein_verify"):
                    self._verify_pages(ent, work, digests)
                # the entry survives a peek: hand the (already joined)
                # digests back to the side pool for the next page-in
                self._digests.submit(uid, lambda d=digests: d)
            if ent.state == "reading":
                # prefetch landed this group: the staging slot is done
                # once the working copy exists; the file remains the
                # authoritative tier copy
                self._staging.release(ent.slot)
                ent.slot = None
                ent.state = "nvme"
            arrs = self._unpack(work, ent.n_pages)
        stall_ms = (time.perf_counter() - t0) * 1e3
        self.counters["pageins"] += 1
        self.counters["pagein_pages"] += ent.n_pages
        self.counters["pagein_bytes"] += ent.n_pages * self.page_stride
        if was == "reading":
            self.counters["pagein_prefetch_hits"] += 1
        elif was == "nvme":
            self.counters["pagein_prefetch_misses"] += 1
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics
        if _metrics.enabled:
            if self._pagein_hist is None or self._pagein_hist is not \
                    _metrics.get("dstpu_kv_pagein_stall_ms"):
                from deepspeed_tpu.telemetry import metrics as _mmod
                self._pagein_hist = _metrics.histogram(
                    "dstpu_kv_pagein_stall_ms",
                    "Partial-residency page-in stall (ms) — wall time a "
                    "chunked-scan tick blocked on a parked group",
                    buckets=_mmod.MS_BUCKETS)
            self._pagein_hist.observe(stall_ms)
        return arrs

    def _fetch(self, ent: _Entry) -> np.ndarray:
        """Materialize the entry's packed bytes into a private working
        buffer (the tier copy / file stays pristine, so a re-read can
        heal a transient flip in the working copy)."""
        n = ent.n_pages * self.page_stride
        if ent.state == "writing":
            # write-back still in flight: the in-memory bytes are
            # authoritative; grab them before the join (whose on_done
            # flips the entry to nvme and drops the buffer ref)
            buf = ent.buf
            self._writes.pop(("w", ent.uid))
            with self.timers.stage("restore_copy"):
                return buf[:n].copy()
        if ent.state == "host":
            with self.timers.stage("restore_copy"):
                return ent.buf[:n].copy()
        if ent.state == "reading":
            self._reads.pop(("r", ent.uid))
            self.counters["prefetch_hits"] += 1
            sbuf = self._staging.peek(ent.slot)
            with self.timers.stage("restore_copy"):
                return sbuf[:n].copy()
        # cold NVMe read (prefetch missed this one)
        self.counters["prefetch_misses"] += 1
        from deepspeed_tpu.io.aio import aligned_empty

        work = aligned_empty(n)
        with self.timers.stage("restore_read"):
            try:
                self._handle().sync_pread(work, ent.path)
            except OSError as e:
                self._nvme_failure(e, f"cold read of spilled uid "
                                      f"{ent.uid}")
                # the trip may already have folded this entry; if not,
                # fold it here — either way the session re-prefills
                if ent.uid in self._entries:
                    self._drop(ent)
                self._lost.discard(ent.uid)
                err = KVRestoreError(
                    ent.uid, -1,
                    f"kv tiering: NVMe read for spilled uid {ent.uid} "
                    f"failed ({e}) — payload unreachable, the session "
                    "must re-prefill")
                from deepspeed_tpu.telemetry import flight

                flight.dump_on_fault("kv_restore_error", err,
                                     extra={"uid": str(ent.uid),
                                            "page": -1})
                raise err from e
        return work

    def _verify_pages(self, ent: _Entry, work: np.ndarray,
                      digests: List[tuple]) -> None:
        w2 = work.reshape(ent.n_pages, self.page_stride)
        src = ent.buf if ent.buf is not None else (
            self._staging.peek(ent.slot) if ent.slot is not None else None)
        for i in range(ent.n_pages):
            page = w2[i]
            action = faults.hook("kv.read_page", uid=ent.uid, page=i,
                                 path=ent.path)
            if action and action[0] == "bitflip":
                faults.apply_bitflip(page, action[1])
            ok = sdc_digest(page, self.algo) == tuple(digests[i])
            tries = 0
            while not ok and tries < self.max_reread:
                tries += 1
                self.counters["rereads"] += 1
                # re-read from the authoritative copy: the spill file
                # (NVMe) or the resident tier buffer (host) — then give
                # the fault hook its next firing (a count=1 transient
                # flip stays healed; a persistent fault flips again)
                if src is not None:
                    page[:] = src.reshape(ent.n_pages,
                                          self.page_stride)[i]
                else:
                    self._handle().sync_pread(page, ent.path,
                                              offset=i * self.page_stride)
                action = faults.hook("kv.read_page", uid=ent.uid,
                                     page=i, path=ent.path)
                if action and action[0] == "bitflip":
                    faults.apply_bitflip(page, action[1])
                ok = sdc_digest(page, self.algo) == tuple(digests[i])
                if ok:
                    self.counters["reread_recovered"] += 1
            if not ok:
                self._quarantine(ent, i)
                err = KVRestoreError(
                    ent.uid, i,
                    f"kv tiering: page {i} of spilled uid {ent.uid} "
                    f"failed {self.algo} verification after "
                    f"{tries} re-read(s) — payload quarantined, the "
                    "session must re-prefill")
                from deepspeed_tpu.telemetry import flight

                flight.dump_on_fault("kv_restore_error", err,
                                     extra={"uid": str(ent.uid),
                                            "page": int(i)})
                raise err
            self.counters["pages_verified"] += 1

    def _quarantine(self, ent: _Entry, page: int) -> None:
        """Never decode on garbage, never delete the evidence."""
        self.counters["quarantined"] += 1
        where = ent.path if ent.path else "host tier"
        if ent.path and os.path.exists(ent.path):
            dst = ent.path + ".quarantine"
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = f"{ent.path}.quarantine.{n}"
            try:
                os.rename(ent.path, dst)
                where = dst
            except OSError:
                pass
        logger.error(
            f"kv tiering: QUARANTINED corrupt spilled page {page} of "
            f"uid {ent.uid} ({where}); session will re-prefill")
        self._drop(ent)
        if ent.path is not None:
            # a corrupt NVMe-backed payload counts toward the degraded-
            # mode streak (a dying device shows up as repeated
            # quarantines as readily as hard EIO)
            self._nvme_failure(None, f"quarantine of uid {ent.uid} "
                                     f"page {page}")

    def _drop(self, ent: _Entry) -> None:
        if self._entries.pop(ent.uid, None) is None:
            return
        if ent.state in ("host", "writing"):
            if ent.state == "writing":
                self._writes.pop(("w", ent.uid))
                self._nvme_used -= ent.n_pages
            else:
                self._host_used -= ent.n_pages
        elif ent.state in ("nvme", "reading"):
            if ent.state == "reading":
                self._reads.pop(("r", ent.uid))
            self._nvme_used -= ent.n_pages
        if ent.slot is not None:
            self._staging.release(ent.slot)
            ent.slot = None
        if ent.path and os.path.exists(ent.path):
            try:
                os.remove(ent.path)
            except OSError:
                pass
        self._digests.discard(ent.uid)
        ent.buf = None

    def drop(self, uid: Key) -> None:
        """Discard a spilled payload (session finished or re-prefills)."""
        self._lost.discard(uid)
        ent = self._entries.get(uid)
        if ent is not None:
            self._drop(ent)

    # -- degraded mode (NVMe tier offline) --------------------------------

    def _check_lost(self, uid: Key) -> None:
        """A payload folded at a degraded-mode trip is gone: raise the
        same typed error as a quarantine so the caller's existing
        re-prefill path takes over."""
        if uid in self._lost:
            self._lost.discard(uid)
            raise KVRestoreError(
                uid, -1,
                f"kv tiering: spilled uid {uid} was folded when the "
                "NVMe tier went offline (degraded mode) — the session "
                "must re-prefill")

    def _nvme_failure(self, exc: Optional[BaseException],
                      why: str) -> None:
        """Record one hard NVMe failure; trip the tier offline at
        ``nvme_fail_threshold`` failures since the last clean probe.
        Interleaved successful IO does NOT reset the streak — on a
        dying device reads of old data often keep succeeding while new
        writes fail, and only a full :meth:`probe_nvme` round-trip
        vouches for health."""
        self.counters["nvme_failures"] += 1
        self._nvme_streak += 1
        logger.error(
            f"kv tiering: NVMe failure {self._nvme_streak}/"
            f"{self.nvme_fail_threshold} ({why}): {exc}")
        if (not self.nvme_offline
                and self._nvme_streak >= self.nvme_fail_threshold):
            self._trip_nvme(why, exc)

    def _trip_nvme(self, why: str,
                   exc: Optional[BaseException]) -> None:
        """Take the NVMe tier offline: fold every parked NVMe-backed
        payload (each session re-prefills loudly via
        :class:`KVRestoreError`), stop demoting, and mark the trip in
        counters/metrics/trace/flight.  Host-tier payloads are
        untouched."""
        self.nvme_offline = True
        self._probe_backoff = 0
        folded: List[Key] = []
        for ent in list(self._entries.values()):
            if ent.state == "host":
                continue
            folded.append(ent.uid)
            if ent.state == "writing":
                # the in-flight write op targets a dead device: abandon
                # it un-joined (joining could wedge or re-raise EIO)
                self._writes.discard(("w", ent.uid))
            elif ent.state == "reading":
                self._reads.discard(("r", ent.uid))
                # deliberately LEAK the staging slot: the abandoned aio
                # read may still scribble into it, so reissuing the
                # buffer to a future read would race
                ent.slot = None
            self._nvme_used -= ent.n_pages
            self._entries.pop(ent.uid, None)
            self._digests.discard(ent.uid)
            ent.buf = None
            self._lost.add(ent.uid)
        self.counters["tier_degraded"] += 1
        self.counters["degraded_folds"] += len(folded)
        logger.error(
            f"kv tiering: NVMe tier OFFLINE after {self._nvme_streak} "
            f"consecutive failures ({why}); folded {len(folded)} parked "
            "payload(s) to re-prefill, demotions fall back host-only")
        from deepspeed_tpu.telemetry import flight
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics

        if _metrics.enabled:
            _metrics.counter(
                "dstpu_tier_degraded_total",
                "KV spill tiers tripped offline (degraded mode)",
                labels=("tier",)).labels(tier="nvme").inc()
        if trace.enabled:
            trace.event("tier_degraded", cat="resilience", tier="nvme",
                        streak=int(self._nvme_streak),
                        folded=len(folded))
        flight.dump_on_fault(
            "tier_degraded",
            exc if exc is not None else RuntimeError(why),
            extra={"tier": "nvme", "streak": int(self._nvme_streak),
                   "folded_uids": [str(u) for u in folded],
                   "why": why})

    def probe_nvme(self) -> bool:
        """Degraded-mode recovery probe: write, read back, and verify
        one page-stride block through the same ``kv.write`` fault site
        as the spill path.  A clean round-trip re-arms the NVMe tier;
        a failed probe leaves it offline (and does NOT feed the
        failure streak — the tier is already down)."""
        if self.nvme_budget <= 0 or self.spill_dir is None:
            return False
        self.counters["probes"] += 1
        path = os.path.join(self.spill_dir, "probe.bin")
        from deepspeed_tpu.io.aio import aligned_empty

        buf = aligned_empty(self.page_stride)
        buf[:] = (np.arange(self.page_stride) % 251).astype(np.uint8)
        back = aligned_empty(self.page_stride)
        try:
            d = faults.hook("kv.write", uid="probe", path=path)
            if d is not None and d[0] in ("hang", "slow"):
                time.sleep(float(d[1]))
            self._handle().sync_pwrite(buf, path)
            self._handle().sync_pread(back, path)
            if not np.array_equal(buf, back):
                raise OSError("probe read-back mismatch")
        except OSError as e:
            self.counters["probe_failures"] += 1
            logger.warning(f"kv tiering: NVMe revival probe failed: {e}")
            return False
        finally:
            try:
                os.remove(path)
            except OSError:
                pass
        self._nvme_streak = 0       # a clean round-trip vouches for it
        if self.nvme_offline:
            self.nvme_offline = False
            self.counters["tier_rearmed"] += 1
            logger.info("kv tiering: NVMe tier re-armed after clean "
                        "revival probe")
            if trace.enabled:
                trace.event("tier_rearmed", cat="resilience",
                            tier="nvme")
        return True

    # -- cross-replica handoff (elastic shrink) --------------------------

    def export_spilled(self, uid: Key) -> Dict[str, Any]:
        """Hand off ``uid``'s payload in SPILL FORMAT — the packed page
        bytes plus the spill-time digests — without unpacking.  The
        receiving store installs the donor digests alongside the bytes,
        so its ``restore()`` verifies the pages against what the DONOR
        computed at spill time: the handoff is integrity-checked
        end-to-end, not re-trusted at the import boundary.  Drops the
        entry (ownership moves with the bytes)."""
        self._check_lost(uid)
        ent = self._entries.get(uid)
        assert ent is not None, f"uid {uid} not spilled"
        n = ent.n_pages
        work = self._fetch(ent)
        digests = self._digests.pop(uid) if self.verify else None
        self._drop(ent)
        self.counters["exports"] += 1
        return {"n_pages": n,
                "page_stride": int(self.page_stride),
                "algo": self.algo,
                "payload": bytes(work[:n * self.page_stride]),
                "digests": ([tuple(d) for d in digests]
                            if digests is not None else None)}

    def import_spilled(self, uid: Key, blob: Dict[str, Any]) -> None:
        """Receiving half of the handoff: install an exported payload
        under ``uid`` as a host-tier entry (demoting/overflowing to
        NVMe exactly like a local spill).  Raises ``ValueError`` on a
        layout mismatch and ``RuntimeError`` when no tier has room —
        the caller falls back to a re-prefill continuation."""
        assert uid not in self._entries, f"uid {uid} already spilled"
        if int(blob["page_stride"]) != self.page_stride:
            raise ValueError(
                f"kv tiering: imported payload page_stride "
                f"{blob['page_stride']} != local {self.page_stride} — "
                "handoff requires homogeneous replica cache layouts")
        n = int(blob["n_pages"])
        if not self.can_spill(n):
            self.counters["spill_fallbacks"] += 1
            raise RuntimeError(
                f"kv tiers full: cannot import {n} pages "
                f"(free {self.free_pages()})")
        from deepspeed_tpu.io.aio import aligned_empty

        raw = np.frombuffer(blob["payload"], np.uint8)
        assert raw.size == n * self.page_stride, (raw.size, n)
        buf = aligned_empty(n * self.page_stride)
        buf[:] = raw
        ent = _Entry(uid, n)
        self._seq += 1
        ent.seq = self._seq
        ent.buf = buf
        host_free = self.host_budget - self._host_used
        try:
            if n <= self.host_budget:
                if n > host_free:
                    self._demote(n - host_free)
                self._entries[uid] = ent
                self._host_used += n
            else:
                self._entries[uid] = ent
                self._nvme_spill(ent)
        except RuntimeError:
            self._entries.pop(uid, None)
            self.counters["spill_fallbacks"] += 1
            raise
        if self.verify:
            donor = blob.get("digests")
            if donor is not None and str(blob.get("algo")) == self.algo:
                # the donor's spill-time digests ARE the reference
                self._digests.submit(
                    uid, lambda d=donor: [tuple(x) for x in d])
            else:
                # algo mismatch (or unverified donor): digest what we
                # received — integrity from here on, not end-to-end
                self._digests.submit(
                    uid, lambda: [sdc_digest(b, self.algo)
                                  for b in buf.reshape(n,
                                                       self.page_stride)])
        self.counters["imports"] += 1
        self.counters["pages_spilled"] += n
        self.counters["bytes_spilled"] += buf.nbytes

    # -- accounting / telemetry ------------------------------------------

    def audit(self) -> Dict[str, int]:
        """Tier-side conservation check (the spill-tier analogue of
        ``PageAllocator.audit``): recomputes per-tier usage from the
        entry table and asserts it matches the running counters."""
        host = sum(e.n_pages for e in self._entries.values()
                   if e.state == "host")
        nvme = sum(e.n_pages for e in self._entries.values()
                   if e.state in ("writing", "nvme", "reading"))
        assert host == self._host_used, (host, self._host_used)
        assert nvme == self._nvme_used, (nvme, self._nvme_used)
        assert host <= self.host_budget and nvme <= self.nvme_budget
        return {"sessions": len(self._entries), "host_pages_used": host,
                "nvme_pages_used": nvme, "host_budget": self.host_budget,
                "nvme_budget": self.nvme_budget}

    def stats(self) -> Dict[str, Any]:
        """Flat numeric stats (stage seconds + counters) — one level so
        ``MonitorMaster.write_serving_health`` flattens it to
        ``Serving/kv_tiering/<name>`` series."""
        out = dict(self.timers.snapshot())
        out.update(self.counters)
        out["resident_spilled_sessions"] = len(self._entries)
        out["host_pages_used"] = self._host_used
        out["nvme_pages_used"] = self._nvme_used
        out["nvme_offline"] = int(self.nvme_offline)
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics
        _metrics.sync_counters(
            "dstpu_kv_tiering_", self.counters,
            help="Tiered paged-KV store counters (cumulative)")
        if _metrics.enabled:
            g = _metrics.gauge("dstpu_kv_tiering_pages_used",
                               "Spilled pages resident per tier",
                               labels=("tier",))
            g.labels(tier="host").set(self._host_used)
            g.labels(tier="nvme").set(self._nvme_used)
        return out

    def close(self) -> None:
        for uid in list(self._entries):
            self.drop(uid)
        try:
            self._writes.drain()
            self._reads.drain()
        except Exception:
            pass
        self._digests.close()
        if self.spill_dir:
            shutil.rmtree(self.spill_dir, ignore_errors=True)
