from deepspeed_tpu.inference.common import HostStageStats
from deepspeed_tpu.inference.config import (InferenceV2Config,
                                            SpeculationConfig)
from deepspeed_tpu.inference.v2.ragged_engine import (RaggedInferenceEngineV2,
                                                      Request)

__all__ = ["RaggedInferenceEngineV2", "Request", "InferenceV2Config",
           "SpeculationConfig", "HostStageStats"]
