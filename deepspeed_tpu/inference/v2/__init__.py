from deepspeed_tpu.inference.v2.ragged_engine import (RaggedInferenceEngineV2,
                                                      Request)

__all__ = ["RaggedInferenceEngineV2", "Request"]
