"""Partial-residency long-context driver: the tiered KV store as
virtual memory for attention.

A live sequence whose KV exceeds the HBM pool keeps only the first
``sink_pages`` and the most recent ``window_pages`` of its page list
device-resident (the StreamingLLM observation: the hot set is sinks +
a recent window); the middle demotes in ``chunk_pages`` groups through
the existing host->NVMe tiers (digest-verified, quantized payloads
carried unchanged).  Parked columns become ``-1`` holes in the page
table — the attention references and the quantized Pallas kernel mask
holes while the surviving columns keep their true positions.

A full-attention tick over such a sequence is a chunked multi-dispatch
scan, LAYER-MAJOR (chunk-major orderings are mathematically inexact —
layer l+1's queries depend on layer l's FULL output):

    x = embed(tokens)
    for each layer l:
        carry = neutral
        for each parked group g:              # fixed [R] staging shape
            carry = fold(carry, stats(q_l(x), staged KV of g))
        x = block_l(x, carry)                 # resident rows + carry,
                                              # writes this tick's KV
    logits = lm_head(norm(x))

Chunk dispatches attend a STAGED dense KV block (the tier store's
``peek`` — a non-destructive verified page-in through the staging
ring) and sow the flash-attention ``(m, l, acc)`` carry; the finish
dispatch folds the accumulated carry into resident attention via the
explicit-carry paths of :mod:`deepspeed_tpu.inference.paged` /
:mod:`deepspeed_tpu.ops.ragged_paged_quant`.  With zero parked groups
the finish dispatch takes the plain softmax path — bit-identical to a
fully-resident engine, which is the parity contract the tests pin.

Exactly two query-shape families exist (prefill ticks take one page of
prompt tokens, decode ticks take one token), so the compiled-program
count is bounded and the steady state compiles nothing.

The driver owns no device state: pages live in the engine's pool, the
parked middle lives in the engine's :class:`TieredKVStore` under
``mid-<uid>-<g>`` keys, and sampling reuses the engine's position-keyed
sampler (seeded sampling is reproducible against a fully-resident
control).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.telemetry import trace

__all__ = ["LongContextDriver"]


class _ChunkScan(nn.Module):
    """One layer's q-projection + staged-KV stats dispatch: RMSNorm +
    attention under the same submodule names as ``LlamaBlock``, so the
    engine's ``params['model']['layers_<l>']`` subtree applies
    directly.  The attention output is discarded — the dispatch exists
    for the ``carry`` collection its staged branch sows."""

    config: Any

    @nn.compact
    def __call__(self, x, positions, ragged_meta):
        from deepspeed_tpu.models.llama import LlamaAttention, RMSNorm

        cfg = self.config
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    name="input_layernorm")(x)
        LlamaAttention(cfg, name="self_attn")(h, positions, True,
                                              ragged_meta)
        return 0.0


class LongContextDriver:
    """Ticks partially-resident (``Request.lc``) sequences for a
    :class:`RaggedInferenceEngineV2` — one driver per engine, created
    lazily on the first long-context admission."""

    def __init__(self, engine):
        from deepspeed_tpu.models.llama import LlamaForCausalLM

        eng = engine
        t = eng._tier_cfg
        assert eng.tiering is not None and t.long_context, (
            "LongContextDriver needs kv_tiering.long_context=True")
        if eng.tp > 1:
            raise NotImplementedError(
                "long-context partial residency does not compose with "
                "tensor-parallel serving yet (the chunked scan threads "
                "an explicit attention carry the TP shard_map path "
                "does not)")
        if eng._wq:
            raise NotImplementedError(
                "long-context partial residency does not compose with "
                "quantize_weights yet — the per-layer dispatch applies "
                "raw param subtrees")
        assert not eng._unroll_params, (
            "long-context needs unrolled layers_<i> params (the engine "
            "unrolls scan params itself in-jit; pass unrolled params)")
        assert isinstance(eng.model, LlamaForCausalLM), (
            "long-context partial residency supports llama-family "
            "models (the per-layer chunked scan mirrors LlamaBlock)")
        assert ("model" in eng.params
                and "layers_0" in eng.params["model"]), (
            "params must be llama-shaped: model/layers_<i>/...")
        self.eng = eng
        self.cfg = eng.cfg
        self.L = int(self.cfg.num_hidden_layers)
        self.H = int(self.cfg.num_attention_heads)
        self.D = int(self.cfg.head_dim)
        self.sink = int(t.sink_pages)
        self.chunk = int(t.chunk_pages)       # compiled staging shape
        self.R = self.chunk * eng.page_size   # staged rows per dispatch
        self._quant = eng.kv_cache_dtype != "none"
        self._fns: Dict[Tuple, Any] = {}
        self._neutrals: Dict[int, Tuple] = {}
        self._kpos_cache: Dict[int, jax.Array] = {}
        # map layer index -> position of its kv_pages / kv_scales leaf
        # in the cache's tree_leaves order (spill payloads travel as
        # flat leaf lists; dict keys sort "layers_10" before "layers_2")
        self._leaf_idx: Dict[int, List[Optional[int]]] = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(eng.cache)
        for i, (path, _leaf) in enumerate(flat):
            keys = [str(getattr(k, "key", k)) for k in path]
            layer = next((k for k in keys if k.startswith("layers_")),
                         None)
            if layer is None:
                continue
            li = int(layer.split("_", 1)[1])
            slot = self._leaf_idx.setdefault(li, [None, None])
            if keys[-1] == "kv_pages":
                slot[0] = i
            elif keys[-1] == "kv_scales":
                slot[1] = i
        assert all(v[0] is not None for v in self._leaf_idx.values())

    # -- residency bookkeeping -------------------------------------------

    def _window(self) -> int:
        # read fresh each tick: kv.window_pages is an online knob
        return max(int(self.eng._tier_cfg.window_pages), 1)

    def _key(self, r, g: int) -> str:
        return f"mid-{r.uid}-{g}"

    def _ensure_col(self, r, col: int) -> bool:
        eng = self.eng
        if eng.page_table[r.slot, col] >= 0:
            return True
        eng._reclaim_for(1)
        if eng.allocator.free_pages < 1:
            return False
        page = eng.allocator.grow(r.slot, 1)[0]
        eng.page_table[r.slot, col] = page
        return True

    def _grow(self, r, lo: int, hi: int) -> bool:
        """Pages for write positions ``[lo, hi)`` — always at/past the
        frontier, never a parked column.  False = pool dry this tick."""
        eng = self.eng
        for col in range(lo // eng.page_size,
                         (hi - 1) // eng.page_size + 1):
            if not self._ensure_col(r, col):
                others = any(s is not None and s is not r and not s.done
                             for s in eng.slots)
                if others or eng.waiting:
                    return False           # a reap may free pages; wait
                raise RuntimeError(
                    f"long-context resident window cannot grow for "
                    f"uid={r.uid}: the HBM pool "
                    f"({eng.num_pages - 1} usable pages) is exhausted "
                    f"and the spill tiers can't take the parked middle "
                    "— raise num_pages, raise kv_tiering host_pages/"
                    "nvme_pages, or shrink sink_pages/window_pages/"
                    "chunk_pages")
        return True

    def _park(self, r, written: int, frontier_col: int) -> None:
        """Demote every FULLY-WRITTEN group whose columns sit entirely
        below ``frontier_col - window_pages`` into the tiers (group g =
        columns ``[sink + g*chunk, sink + (g+1)*chunk)``; parked groups
        are always a contiguous prefix of the middle)."""
        eng = self.eng
        window = self._window()
        while True:
            g = r.lc_parked
            col0 = self.sink + g * self.chunk
            end = col0 + self.chunk
            if end * eng.page_size > written:
                return                      # group not fully written yet
            if end > frontier_col - window:
                return                      # inside the resident window
            if not eng.tiering.can_spill(self.chunk):
                return                      # tiers full: stay resident
            gather, _ = eng._tier_jits()
            idx = np.zeros((eng.pages_per_seq,), np.int32)  # pad: trash
            idx[:self.chunk] = eng.page_table[r.slot, col0:end]
            rows = jax.device_get(gather(eng.cache, jnp.asarray(idx)))
            eng.tiering.spill(
                self._key(r, g),
                [np.asarray(leaf[:self.chunk]) for leaf in
                 jax.tree_util.tree_leaves(rows)],
                self.chunk)
            pages = [int(p) for p in eng.page_table[r.slot, col0:end]]
            eng.allocator.release_pages(r.slot, pages)
            eng.page_table[r.slot, col0:end] = -1
            r.lc_parked += 1
            if trace.enabled:
                trace.event("lc_park", cat="kv", uid=r.uid, group=g,
                            pages=self.chunk,
                            parked_pages=r.lc_parked * self.chunk)

    def residency(self, r) -> Dict[str, int]:
        """Resident vs parked page split for ``r`` (bench/monitor
        surface)."""
        resident = int((self.eng.page_table[r.slot] >= 0).sum())
        return {"resident_pages": resident,
                "parked_pages": r.lc_parked * self.chunk}

    # -- compiled dispatch family ----------------------------------------

    def _neutral(self, Tq: int):
        if Tq not in self._neutrals:
            from deepspeed_tpu.inference.paged import neutral_carry
            self._neutrals[Tq] = tuple(
                jnp.asarray(a) for a in neutral_carry(Tq, self.H,
                                                      self.D))
        return self._neutrals[Tq]

    def _kpos(self, g: int) -> jax.Array:
        if g not in self._kpos_cache:
            lo = (self.sink + g * self.chunk) * self.eng.page_size
            self._kpos_cache[g] = jnp.arange(lo, lo + self.R,
                                             dtype=jnp.int32)
        return self._kpos_cache[g]

    def _embed_fn(self, Tq: int):
        key = ("embed", Tq)
        if key not in self._fns:
            cfg = self.cfg
            mod = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=cfg.param_dtype)

            def run(ep, ids):
                return mod.apply({"params": ep}, ids)

            run.__name__ = run.__qualname__ = f"lc_embed_t{Tq}"
            self._fns[key] = jax.jit(run)
        return self._fns[key]

    def _chunk_fn(self, Tq: int):
        key = ("chunk", Tq)
        if key not in self._fns:
            mod = _ChunkScan(self.cfg)
            quant = self._quant

            def run(lp, x, positions, staged_kv, staged_scales, kpos,
                    qpos, cm, cl, cacc):
                meta = {"staged_kv": staged_kv, "staged_kpos": kpos,
                        "staged_qpos": qpos, "carry_m": cm,
                        "carry_l": cl, "carry_acc": cacc}
                if quant:
                    meta["staged_scales"] = staged_scales
                _, vars_ = mod.apply({"params": lp}, x, positions,
                                     meta, mutable=["carry"])
                return vars_["carry"]["self_attn"]["stats"][0]

            run.__name__ = run.__qualname__ = f"lc_chunk_t{Tq}"
            self._fns[key] = jax.jit(run)
        return self._fns[key]

    def _finish_fn(self, Tq: int, has_carry: bool):
        key = ("finish", Tq, has_carry)
        if key not in self._fns:
            from deepspeed_tpu.models.llama import LlamaBlock
            mod = LlamaBlock(self.cfg)

            def run(lp, cache_l, x, positions, kv_lens, page_indices,
                    cu_q_lens, num_seqs, new_kv_dest, *carry):
                meta = {"kv_lens": kv_lens,
                        "page_indices": page_indices,
                        "cu_q_lens": cu_q_lens, "num_seqs": num_seqs,
                        "new_kv_dest": new_kv_dest}
                if has_carry:
                    meta["carry_m"], meta["carry_l"], \
                        meta["carry_acc"] = carry
                out, vars_ = mod.apply(
                    {"params": lp, "cache": cache_l}, x, positions,
                    True, meta, mutable=["cache"])
                return out, vars_["cache"]

            run.__name__ = run.__qualname__ = (
                f"lc_finish_t{Tq}{'_carry' if has_carry else ''}")
            self._fns[key] = jax.jit(run, donate_argnums=(1,))
        return self._fns[key]

    def _head_fn(self, Tq: int):
        key = ("head", Tq)
        if key not in self._fns:
            from deepspeed_tpu.models.llama import RMSNorm
            cfg = self.cfg
            norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype)
            dense = nn.Dense(cfg.vocab_size, use_bias=False,
                             dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype)

            def run(norm_p, head_p, x, row):
                xr = jnp.take(x, row, axis=1)           # [1, E]
                h = norm.apply({"params": norm_p}, xr)
                return dense.apply({"params": head_p}, h)   # [1, V]

            run.__name__ = run.__qualname__ = f"lc_head_t{Tq}"
            self._fns[key] = jax.jit(run)
        return self._fns[key]

    def _layer_cache(self, l: int):
        return self.eng.cache["model"][f"layers_{l}"]

    def _set_layer_cache(self, l: int, sub) -> None:
        c = self.eng.cache
        name = f"layers_{l}"
        if isinstance(c, dict):
            m = dict(c["model"])
            m[name] = sub
            self.eng.cache = {**c, "model": m}
        else:                                  # flax FrozenDict
            self.eng.cache = c.copy(
                {"model": c["model"].copy({name: sub})})

    # -- the tick ---------------------------------------------------------

    def tick(self, r) -> int:
        """One prefill chunk (``page_size`` prompt tokens) or one
        decode token for a partially-resident sequence; returns tokens
        produced (0 for non-final prefill ticks or a page-stalled
        wait)."""
        eng = self.eng
        page = eng.page_size
        prefilling = r.prefill_done < r.ctx_len
        if prefilling:
            lo = r.prefill_done
            take = min(page, r.ctx_len - lo)
            Tq = page
            written = lo
            tokens = np.zeros((Tq,), np.int32)
            tokens[:take] = r.ctx[lo:lo + take]
        else:
            lo = r.length - 1                 # this tick's write position
            take = 1
            Tq = 1
            written = lo
            tokens = np.asarray([eng._last_tokens[r.slot]], np.int32)
        hi = lo + take                        # tokens written after tick
        kv_len = hi
        if not self._grow(r, lo, hi):
            return 0                          # pool dry: sit the tick out
        frontier_col = (hi - 1) // page
        self._park(r, written, frontier_col)

        qpos = np.full((Tq,), -1, np.int32)   # pad rows mask every key
        qpos[:take] = np.arange(lo, hi)
        positions = np.zeros((Tq,), np.int32)
        positions[:take] = np.arange(lo, hi)

        n_parked = r.lc_parked
        groups: List[int] = []
        w = self.cfg.sliding_window
        for g in range(n_parked):
            if w is not None:
                kmax = (self.sink + (g + 1) * self.chunk) * page - 1
                if kmax <= lo - int(w):
                    continue                  # sliding window: out of reach
            groups.append(g)
        if n_parked:
            # read-ahead for THIS tick's peeks, bounded by the staging
            # ring; the tail re-issue below overlaps the NEXT tick
            eng.tiering.prefetch([self._key(r, g) for g in groups])
        staged: Dict[int, List[np.ndarray]] = {
            g: eng.tiering.peek(self._key(r, g)) for g in groups}

        params = eng.params
        x = self._embed_fn(Tq)(params["model"]["embed_tokens"],
                               eng._upload(tokens)[None])
        qpos_dev = eng._upload(qpos)
        pos_dev = eng._upload(positions)
        kv_lens = eng._upload(np.asarray([kv_len], np.int32))
        page_indices = eng._upload(eng.page_table[r.slot][None])
        cu_q_lens = eng._upload(np.asarray([0, take], np.int32))
        num_seqs = eng._upload(np.asarray([1], np.int32))
        dest = np.zeros((Tq,), np.int32)      # pad rows -> trash page 0
        pos_r = np.arange(lo, hi)
        pg = eng.page_table[r.slot, pos_r // page]
        assert (pg > 0).all(), "write into unallocated page"
        dest[:take] = pg * page + pos_r % page
        dest_dev = eng._upload(dest)

        chunk_fn = self._chunk_fn(Tq)
        for l in range(self.L):
            lp = params["model"][f"layers_{l}"]
            carry = None
            for g in groups:
                arrs = staged[g]
                kv_i, sc_i = self._leaf_idx[l]
                staged_kv = eng._upload(
                    arrs[kv_i].reshape(self.R, -1, self.D))
                scales = (eng._upload(
                    arrs[sc_i].reshape(self.R, -1))
                    if self._quant else None)
                c = carry if carry is not None else self._neutral(Tq)
                lp_attn = {"input_layernorm": lp["input_layernorm"],
                           "self_attn": lp["self_attn"]}
                carry = chunk_fn(lp_attn, x, pos_dev, staged_kv,
                                 scales, self._kpos(g), qpos_dev, *c)
            if carry is None:
                x, sub = self._finish_fn(Tq, False)(
                    lp, self._layer_cache(l), x, pos_dev, kv_lens,
                    page_indices, cu_q_lens, num_seqs, dest_dev)
            else:
                x, sub = self._finish_fn(Tq, True)(
                    lp, self._layer_cache(l), x, pos_dev, kv_lens,
                    page_indices, cu_q_lens, num_seqs, dest_dev,
                    *carry)
            self._set_layer_cache(l, sub)

        if trace.enabled:
            trace.event("lc_tick", cat="kv", uid=r.uid, q_tokens=take,
                        kv_len=int(kv_len), parked_groups=len(groups),
                        staged_dispatches=len(groups) * self.L)
        eng.host_stats.dispatches += 1 + len(groups) * self.L
        eng.host_stats.ticks += 1

        finishes = prefilling and hi >= r.ctx_len
        produced = 0
        if prefilling:
            r.prefill_done = hi
            if finishes:
                eng.request_latency.on_prefill_done(r.uid, r.ctx_len, 0)
        if finishes or not prefilling:
            sel = self._head_fn(Tq)(params["model"]["norm"],
                                    params["lm_head"], x,
                                    jnp.int32(take - 1))
            produced = eng._sample(sel, [(r, 0, True)])
        # read-ahead for the NEXT tick: decode revisits the same groups,
        # so the NVMe->host copies overlap host-side sampling/planning
        if n_parked:
            eng.tiering.prefetch(
                [self._key(r, g) for g in groups]
                [:max(eng.prefetch_lookahead, 1)])
        return produced
