"""FastGen-equivalent ragged / continuous-batching inference engine.

TPU-native re-design of the reference InferenceEngineV2 stack
(``inference/v2/engine_v2.py:30``, ragged batching
``inference/v2/ragged/``, Dynamic SplitFuse scheduling from the FastGen
blog): requests of different lengths share one running decode batch —
sequences join the moment a slot frees, never waiting for the batch to
drain.  Where the reference manages blocked KV memory with a C++
allocator + custom ragged CUDA kernels, the TPU version keeps shapes
STATIC for XLA:

- the KV cache is ONE [max_seqs, ...] buffer set; every sequence owns a
  slot row and its own length (per-row write offsets in
  ``kv_cache.update_kv_cache``, positions-masked reads);
- the decode step is a single compiled program over ALL slots every
  iteration — empty/finished slots compute masked garbage (the price of
  static shapes, bounded by max_seqs) and their cache rows are
  overwritten by the next admission before anything reads them;
- prompt prefill is CHUNKED (Dynamic SplitFuse): each ``step()`` runs at
  most ``prefill_chunk`` prompt tokens of one admitted request alongside
  the decode step, bounding per-step latency so decoding sequences never
  stall behind a long prompt.

Host-side scheduling (admission, chunk bookkeeping, finish detection) is
plain Python — the reference's scheduler is host-side C++/Python too.
Models: the Llama family (Llama, Mixtral — attention threads per-token
positions, which the ragged path requires).
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.kv_cache import init_cache
from deepspeed_tpu.inference.sampling import sample_logits
from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class Request:
    uid: int
    prompt: np.ndarray                    # [P] int32
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    # runtime state
    slot: int = -1
    prefill_done: int = 0                 # prompt tokens already cached
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False

    @property
    def length(self) -> int:
        return self.prefill_done + len(self.generated)


class RaggedInferenceEngineV2:
    """``put_request`` -> repeated ``step()`` -> ``get_outputs``.

    One ``step()`` = (admit waiting requests into free slots) + (one
    prefill chunk for the oldest admitted request that still has prompt
    left) + (one decode token for every sequence whose prompt is fully
    cached).
    """

    def __init__(self, model, params: Any = None, max_seqs: int = 8,
                 max_seq_len: int = 512, prefill_chunk: int = 128,
                 rng: Optional[jax.Array] = None):
        mcfg = getattr(model, "config", None)
        assert dataclasses.is_dataclass(mcfg) and hasattr(mcfg, "decode"), \
            "ragged engine needs a model-zoo module with a decode config"
        assert hasattr(mcfg, "rope_theta"), (
            "ragged batching requires per-token positions through "
            "attention — supported by the Llama family models")
        assert hasattr(mcfg, "ragged_decode"), (
            "model config predates ragged decode support")
        # unrolled layers: each layer's cache aliases independently (see
        # inference/common.unroll_scan_params); stacked params convert
        # in-jit inside the prefill/decode programs
        self._unroll_params = bool(getattr(mcfg, "scan_layers", False))
        self.cfg = dataclasses.replace(mcfg, decode=True,
                                       ragged_decode=True,
                                       max_cache_len=max_seq_len,
                                       scan_layers=False)
        self.model = type(model)(self.cfg)
        self.max_seqs = max_seqs
        self.max_seq_len = max_seq_len
        self.prefill_chunk = prefill_chunk
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)

        from deepspeed_tpu.inference.common import normalize_params

        self.params = normalize_params(
            model, params,
            plain_model=type(model)(dataclasses.replace(mcfg,
                                                        decode=False)))

        # one global slot cache [max_seqs, ...]
        self.cache = init_cache(self.model,
                                np.zeros((max_seqs, 1), np.int32),
                                positions=jnp.zeros((max_seqs, 1),
                                                    jnp.int32))
        self._uid = itertools.count()
        self.waiting: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * max_seqs
        self.finished: List[Request] = []
        self._unclaimed: Dict[int, np.ndarray] = {}
        self._decode_fn = None
        self._prefill_fns: Dict[int, Any] = {}
        self._last_tokens = np.zeros((max_seqs,), np.int32)
        log_dist(f"RaggedInferenceEngineV2: max_seqs={max_seqs} "
                 f"max_seq_len={max_seq_len} "
                 f"prefill_chunk={prefill_chunk}", ranks=[0])

    # -- request API ----------------------------------------------------

    def put_request(self, prompt, **kw) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        assert prompt.size > 0
        assert kw.get("max_new_tokens", 64) >= 1, (
            "max_new_tokens must be >= 1 (prefill seeds the first token)")
        assert prompt.size + kw.get("max_new_tokens", 64) <= \
            self.max_seq_len, "prompt + max_new_tokens exceeds max_seq_len"
        req = Request(uid=next(self._uid), prompt=prompt, **kw)
        self.waiting.append(req)
        return req.uid

    def get_outputs(self) -> List[Tuple[int, np.ndarray]]:
        out = list(self._unclaimed.items())
        self._unclaimed = {}
        out += [(r.uid, np.concatenate([r.prompt,
                                        np.asarray(r.generated, np.int32)]))
                for r in self.finished]
        self.finished = []
        return out

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)

    # -- compiled pieces -------------------------------------------------

    def _prefill_fn(self, chunk: int):
        """Jitted prefill of one [1, chunk] slice against one slot row."""
        if chunk in self._prefill_fns:
            return self._prefill_fns[chunk]
        from deepspeed_tpu.inference.common import (logits_of,
                                                    unroll_scan_params)

        model = self.model
        unroll = self._unroll_params

        # time-major KV buffers end with [..., max_len, B, Hkv, D]: the
        # slot (batch) axis is ndim-3.  Smaller leaves (cache_index) are
        # slot-independent bookkeeping.
        def slot_axis(b):
            return b.ndim - 3 if getattr(b, "ndim", 0) >= 4 else None

        def run(params, cache, slot, ids, start):
            if unroll:
                params = unroll_scan_params(params)
            row = jax.tree_util.tree_map(
                lambda b: (jax.lax.dynamic_slice_in_dim(
                    b, slot, 1, slot_axis(b))
                    if slot_axis(b) is not None else b), cache)
            positions = (start + jnp.arange(chunk))[None]     # [1, chunk]
            out, vars_ = model.apply(
                {"params": params, "cache": row}, ids,
                positions=positions, mutable=["cache"])
            new_cache = jax.tree_util.tree_map(
                lambda g, l: (jax.lax.dynamic_update_slice_in_dim(
                    g, l, slot, slot_axis(g))
                    if slot_axis(g) is not None else l),
                cache, vars_["cache"])
            return logits_of(out)[0], new_cache       # [chunk, V]

        fn = jax.jit(run, donate_argnums=(1,))
        self._prefill_fns[chunk] = fn
        return fn

    def _decode_step_fn(self):
        """Jitted one-token step over ALL slots."""
        if self._decode_fn is not None:
            return self._decode_fn
        from deepspeed_tpu.inference.common import (logits_of,
                                                    unroll_scan_params)

        model = self.model
        unroll = self._unroll_params

        def run(params, cache, tokens, positions):
            if unroll:
                params = unroll_scan_params(params)
            out, vars_ = model.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions=positions[:, None], mutable=["cache"])
            return logits_of(out)[:, -1], vars_["cache"]

        self._decode_fn = jax.jit(run, donate_argnums=(1,))
        return self._decode_fn

    # -- the scheduler tick ----------------------------------------------

    def step(self) -> int:
        """One engine iteration; returns the number of tokens produced."""
        self._admit()
        self._prefill_tick()
        return self._decode_tick()

    def _admit(self) -> None:
        for i in range(self.max_seqs):
            if self.slots[i] is None and self.waiting:
                req = self.waiting.popleft()
                req.slot = i
                self.slots[i] = req

    def _prefill_tick(self) -> None:
        # oldest admitted request (by uid, NOT slot index — index order
        # could starve a high slot under churn) with prompt remaining;
        # SplitFuse: one bounded chunk per step
        pending = [r for r in self.slots
                   if r is not None and r.prefill_done < r.prompt.size]
        if not pending:
            return
        req = min(pending, key=lambda r: r.uid)
        chunk = min(self.prefill_chunk,
                    self.max_seq_len - req.prefill_done)
        ids = np.zeros((1, chunk), np.int32)
        real = min(chunk, req.prompt.size - req.prefill_done)
        ids[0, :real] = req.prompt[req.prefill_done:
                                   req.prefill_done + real]
        fn = self._prefill_fn(chunk)
        logits, self.cache = fn(self.params, self.cache,
                                jnp.int32(req.slot), jnp.asarray(ids),
                                jnp.int32(req.prefill_done))
        req.prefill_done += real
        if req.prefill_done >= req.prompt.size:
            # last real token's logits seed the first generated token
            self.rng, sub = jax.random.split(self.rng)
            tok = int(np.asarray(sample_logits(
                logits[None, real - 1], sub, do_sample=req.do_sample,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p))[0])
            req.generated.append(tok)
            self._last_tokens[req.slot] = tok
            self._maybe_finish(req)

    def _decode_tick(self) -> int:
        active = [r for r in self.slots
                  if r is not None and not r.done
                  and r.prefill_done >= r.prompt.size]
        if not active:
            self._reap()
            return 0
        tokens = np.asarray(self._last_tokens)
        positions = np.zeros((self.max_seqs,), np.int32)
        for r in self.slots:
            if r is None:
                continue
            if r.prefill_done < r.prompt.size:
                # mid-prefill slot: this step's write is garbage — park it
                # at prefill_done, where the next prompt chunk overwrites
                positions[r.slot] = min(r.prefill_done,
                                        self.max_seq_len - 1)
            else:
                # the fed token is the LAST generated one: its absolute
                # position (and cache write offset) is length - 1
                positions[r.slot] = int(np.clip(r.length - 1, 0,
                                                self.max_seq_len - 1))
        logits, self.cache = self._decode_step_fn()(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(positions))
        produced = 0
        # one device call per distinct sampling config (typically one),
        # one host sync per step — not per request
        groups: Dict[Tuple, List[Request]] = {}
        for r in active:
            key = (r.do_sample, r.temperature, r.top_k, r.top_p)
            groups.setdefault(key, []).append(r)
        for (do_sample, temp, top_k, top_p), reqs in groups.items():
            slots = [r.slot for r in reqs]
            sub = None
            if do_sample:
                self.rng, sub = jax.random.split(self.rng)
            toks = np.asarray(sample_logits(
                logits[np.asarray(slots)], sub, do_sample=do_sample,
                temperature=temp, top_k=top_k, top_p=top_p))
            for r, tok in zip(reqs, toks):
                r.generated.append(int(tok))
                self._last_tokens[r.slot] = int(tok)
                produced += 1
                self._maybe_finish(r)
        self._reap()
        return produced

    def _maybe_finish(self, req: Request) -> None:
        if (len(req.generated) >= req.max_new_tokens or
                (req.eos_token_id is not None and req.generated and
                 req.generated[-1] == req.eos_token_id) or
                req.length >= self.max_seq_len):
            req.done = True

    def _reap(self) -> None:
        for i, r in enumerate(self.slots):
            if r is not None and r.done:
                self.finished.append(r)
                self.slots[i] = None

    # -- convenience ------------------------------------------------------

    def generate_all(self, prompts: List[np.ndarray], **kw
                     ) -> Dict[int, np.ndarray]:
        """Submit everything, run until drained (batch convenience API —
        the serving loop calls ``step`` itself)."""
        uids = set(self.put_request(p, **kw) for p in prompts)
        outs: Dict[int, np.ndarray] = {}
        while self.has_work():
            self.step()
            for uid, toks in self.get_outputs():
                if uid in uids:
                    outs[uid] = toks
                else:
                    # foreign request (submitted outside this call): keep
                    # it claimable by the caller's own get_outputs()
                    self._unclaimed[uid] = toks
        return outs
