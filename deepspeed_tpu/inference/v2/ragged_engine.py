"""FastGen-equivalent ragged / continuous-batching inference engine.

TPU-native re-design of the reference InferenceEngineV2 stack
(``inference/v2/engine_v2.py:30``, ragged batching ``inference/v2/ragged/``,
Dynamic SplitFuse scheduling from the FastGen blog): requests of different
lengths share one running decode batch — sequences join the moment a slot
frees, never waiting for the batch to drain.

Round-3 architecture (replacing the slot-row cache + split prefill/decode
dispatches of round 2):

- **Blocked KV** (reference ``ragged/blocked_allocator.py:1``,
  ``ragged/kv_cache.py``): KV lives in fixed-size pages addressed by a
  per-sequence page table; device memory scales with pages, not
  ``max_seqs x max_seq_len``.  Allocation is host-side
  (:class:`deepspeed_tpu.inference.paged.PageAllocator`), worst-case
  reserved at admission.
- **One fused compiled program per tick** (Dynamic SplitFuse,
  ``engine_v2.py:107``): a single static ``[1, T]`` token batch carries one
  decode token for EVERY ready sequence AND this tick's prefill chunk(s) —
  multiple prefilling requests share the chunk budget.  Shapes never vary,
  so exactly one XLA program is compiled; raggedness lives in int32
  metadata (``cu_q_lens`` et al.).
- **Attention** is the vLLM-TPU ragged paged Pallas kernel on TPU and an
  XLA-compilable reference on CPU (``inference/paged.py``).

Round-4 additions:

- **Tensor-parallel serving** (reference v2 TP sharding,
  ``inference/v2/model_implementations/sharding/attn.py`` + engine TP
  groups ``inference/engine.py:247``): pass a ``topology`` with a >1
  ``tensor`` axis — weights shard by AutoTP name rules, the paged KV pool
  shards over its head dim, and the fused tick runs under GSPMD with the
  paged attention shard_map-manual over ``tensor``.
- **On-device multi-tick decode**: when every active sequence is past
  prefill, ``step()`` dispatches ONE compiled program that runs
  ``decode_block_size`` decode ticks in a ``lax.scan`` with on-device
  per-sequence sampling (``sampling.sample_logits_batched``) — amortizing
  the host round trip the reference's FastGen scheduler pays per tick to
  1/K.  Finished sequences park on the trash page mid-block; the host
  reconstructs outputs from the per-tick produced mask.

Round-5/6 addition — the **pipelined serving host path** (this repo's
software-pipeline treatment, same shape as the NVMe moment stream): the
round-5 verdict measured the ragged engine at 23.3k device tok/s but 295
WALL tok/s — ~99% of serving wall time was host planning, per-tick
``jnp.asarray`` metadata uploads, and a blocking ``device_get`` per
dispatch.  With ``pipeline=True`` (default) the decode steady state runs
as a software pipeline:

- the decode-block carry (``last_tok``/``pos``/``active``/``remaining``)
  and the per-tick metadata (``page_indices``/``kv_lens`` derivation
  inputs, sampler configs, eos ids) stay RESIDENT on device across
  dispatches — uploaded once at loop entry, re-uploaded only when the
  page table actually grows;
- while block *k* executes on device, the host plans block *k+1* from an
  exact projection of each sequence's length/remaining budget (JAX
  dispatch is async — the host never synchronizes per block, bounded by
  ``async_depth`` blocks in flight);
- sampled tokens accumulate on device and are harvested (one
  ``device_get``) every ``harvest_interval`` blocks.  EOS/finish
  detection stays device-side (the decode block's ``active`` carry).

Harvests are FORCED at every point where the unpipelined engine could
have reaped, admitted, or evicted (a possible finish, a newly admittable
request, page-growth failure), so the dispatch sequence — programs,
metadata values, and rng splits — is identical to ``pipeline=False`` and
outputs are bit-identical, greedy or seeded-sampling.  ``host_stats``
(:class:`~deepspeed_tpu.inference.common.HostStageStats`) breaks the
host path into plan/upload/dispatch/device/harvest per dispatch.

Round-6 addition — **speculative decoding on the pipelined decode
path**: decode is memory-bound (every dispatch re-reads the weights from
HBM for ONE token per sequence), so with ``speculation.mode != off`` each
decode-block tick drafts ``k`` tokens per slot and the target model
scores all ``k+1`` positions in ONE ragged dispatch (the drafted tokens
enter as a short prefill-like chunk against the paged KV — the same
SplitFuse machinery that mixes prefill chunks into decode ticks).  A
device-resident accept/rollback step then compares draft vs target:

- **greedy** slots accept the longest exact-match prefix and emit the
  target's argmax everywhere, so speculative greedy output is
  bit-identical to non-speculative decode regardless of draft quality;
- **sampled** slots use standard rejection sampling with
  residual-distribution resampling
  (:func:`~deepspeed_tpu.inference.sampling.speculative_verify`), so
  the output distribution provably equals the non-speculative one.

Two draft modes share the interface: ``ngram`` (prompt-lookup over a
device-resident token-history buffer — no second model) and ``draft``
(a small same-vocab family member runs its own decode carry against the
SAME page table; its KV pool is separate, its page cursors are shared).
Rollback is pure position rollback: KV rows written for rejected draft
positions are provably overwritten by the next block before any query
can attend to them, and the pages stay owned (the next block writes the
same span).  Accepted length, rolled-back cursors, and the corrected
bonus token all live in the decode carry, so speculation composes with
the pipelined host path — the host projects per-slot advance as
``[1, k+1]``-per-tick BOUNDS instead of exact counts, grows pages to
the worst case, and forces a harvest whenever a finish is possible
under the fast bound.

Host-side scheduling (admission, chunk budgeting, finish detection) is
plain Python — the reference's scheduler tier is host-side too.  Models:
anything llama-shaped in the zoo (Llama, Mistral, Qwen2, Mixtral, ... —
per-token positions thread through attention, which the ragged path
requires).
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.common import HostStageStats
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.telemetry import RequestLatencyTracker, trace
from deepspeed_tpu.utils.async_stage import BoundedAsyncStage, StageTimers
from deepspeed_tpu.inference.paged import (PageAllocator,
                                           pages_for)
from deepspeed_tpu.inference.prefix_cache import (ROOT_HASH,
                                                  PrefixCacheIndex)
from deepspeed_tpu.inference.sampling import (filter_logits_batched,
                                              position_keys,
                                              sample_logits,
                                              sample_logits_batched,
                                              speculative_verify)
from deepspeed_tpu.utils.logging import log_dist


def _paged_kv_page_bytes(model, mcfg, page_size: int,
                         kv_cache_dtype: str) -> int:
    """Exact device bytes ONE KV page costs across every cache leaf —
    for a quantized pool that is the 1-byte payload page plus its fp32
    scale rows.  Measured by an eval_shape probe of a 2-page pool
    rather than guessed from the layer count, so any model-zoo cache
    layout (extra leaves, fused layers) is accounted automatically."""
    probe_cfg = dataclasses.replace(
        mcfg, decode=True, ragged_decode=False, paged_decode=True,
        max_cache_len=2 * page_size, scan_layers=False,
        kv_page_size=page_size, kv_num_pages=2,
        tensor_parallel=False, kv_cache_dtype=kv_cache_dtype)
    probe = type(model)(probe_cfg)
    meta = {"kv_lens": jnp.zeros((1,), jnp.int32),
            "page_indices": jnp.full((1, 2), -1, jnp.int32),
            "cu_q_lens": jnp.zeros((2,), jnp.int32),
            "num_seqs": jnp.zeros((1,), jnp.int32),
            "new_kv_dest": jnp.zeros((4,), jnp.int32)}
    ids = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.zeros((1, 4), jnp.int32)
    shapes = jax.eval_shape(lambda: probe.init(
        jax.random.PRNGKey(0), ids, positions=pos, ragged_meta=meta))
    total = sum(int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree_util.tree_leaves(shapes["cache"]))
    assert total % 2 == 0, total
    return total // 2


@dataclasses.dataclass
class Request:
    uid: int
    prompt: np.ndarray                    # [P] int32
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    # runtime state
    slot: int = -1
    prefill_done: int = 0                 # context tokens already cached
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # prefill SOURCE: the prompt, or prompt + already-generated tokens
    # after an eviction (the continuation re-prefills its own output)
    ctx: Optional[np.ndarray] = None
    # tiered-KV spill payload metadata while the sequence's pages sit
    # in host RAM / NVMe (None <=> not spilled); the page bytes live in
    # the engine's TieredKVStore keyed by uid.  With a prefix cache,
    # "shared_pages" records shared-prefix pages held resident by
    # spill-holds instead of spilling (only private pages hit the tiers)
    spilled: Optional[Dict[str, Any]] = None
    # prefix-cache registration cursor: pc_pages full pages of ctx are
    # in the index, pc_parent is the chain hash at that point, and
    # pc_cached counts prefill tokens this admission skipped via attach
    pc_parent: int = ROOT_HASH
    pc_pages: int = 0
    pc_cached: int = 0
    # partial residency (kv_tiering.long_context): the sequence's KV
    # exceeds the HBM pool, so only sinks + the recent window stay
    # resident and LongContextDriver ticks it outside the fused batch.
    # lc_parked counts middle page GROUPS demoted to the tiers under
    # "mid-<uid>-<g>" keys (always a contiguous prefix of the middle)
    lc: bool = False
    lc_parked: int = 0
    # disaggregated serving: the router marked this request for a
    # prefill->decode handoff — the engine runs its prefill, lets the
    # first token land (it is sampled in the same fused tick the last
    # prompt chunk runs in), then parks the session for
    # ``export_handoff`` instead of decoding it locally
    handoff: bool = False

    @property
    def ctx_len(self) -> int:
        return int(self.ctx.size if self.ctx is not None
                   else self.prompt.size)

    @property
    def length(self) -> int:
        # tokens in the KV cache: prefilled context + tokens generated
        # AFTER that context (an evicted continuation's ctx already
        # contains its earlier output)
        return self.prefill_done + len(self.generated) - \
            (self.ctx_len - self.prompt.size)


class RaggedInferenceEngineV2:
    """``put_request`` -> repeated ``step()`` -> ``get_outputs``.

    One ``step()`` = (admit waiting requests into free slots, reserving
    KV pages) + EITHER one fused SplitFuse tick (any sequence still
    prefilling: a decode token for every ready sequence plus prompt
    chunks, in one ``T = max_seqs + prefill_chunk`` batch) OR one
    ``decode_block_size``-tick on-device decode block (everyone
    decoding).
    """

    def __init__(self, model, params: Any = None, max_seqs: int = 8,
                 max_seq_len: int = 512, prefill_chunk: int = 128,
                 rng: Optional[jax.Array] = None, page_size: int = 64,
                 num_pages: Optional[int] = None, topology=None,
                 decode_block_size: int = 8,
                 kv_cache_dtype: Optional[str] = None,
                 kv_pool_bytes: Optional[int] = None,
                 quantize_weights: Optional[str] = None,
                 kv_reserve: str = "on_demand",
                 pipeline: Optional[bool] = None,
                 async_depth: Optional[int] = None,
                 harvest_interval: Optional[int] = None,
                 speculation: Any = None,
                 draft_model=None, draft_params: Any = None,
                 draft_kv_cache_dtype: Optional[str] = None,
                 kv_tiering: Any = None,
                 prefix_cache: Any = None,
                 slo: Any = None,
                 trace_sample: Optional[int] = None,
                 replica: Optional[str] = None,
                 control: Any = None,
                 config: Any = None):
        """``kv_cache_dtype``: ``None`` (config subtree
        ``v2.kv_cache_dtype`` decides; "none" by default) | "none" |
        "fp8" | "int8" — paged KV pool storage format (reference
        fp_quantizer KV quantization).  Quantized pools are read
        dequant-free: the Pallas quantized-pages kernel on TPU at
        head_dim 128, the gathered-pages XLA reference elsewhere
        (:func:`~deepspeed_tpu.inference.paged.kv_dequant_path`).
        ``kv_pool_bytes``: size the pool by a device byte budget instead
        of page count — ``num_pages`` becomes the exact number of pages
        (payload + scale rows) that fit, so the same HBM budget holds
        ~2x the pages when quantized.  Ignored when ``num_pages`` is
        given explicitly.
        ``draft_kv_cache_dtype``: storage format for the draft model's
        pool under ``speculation.mode='draft'``; default ``None``
        follows the target pool's resolved ``kv_cache_dtype``.
        ``quantize_weights``: None | "int8" | "fp8" | "fp6" | "w8a8" —
        weights persist quantized in HBM and dequantize in-jit at use
        (reference FP6-LLM cuda_linear / int8 quantized inference);
        "w8a8" additionally quantizes activations per row and dots
        int8 x int8 on the MXU (reference W8A8 GEMMs,
        ``csrc/quantization``) — Llama-family models only, and the
        faster choice whenever decode is weight-bandwidth-bound.
        ``kv_reserve``: "on_demand" (reference blocked-allocator model —
        admit on prompt-size pages, grow per decode block, evict +
        requeue as a continuation when the pool runs dry) or
        "worst_case" (reserve prompt + max_new_tokens at admission; no
        mid-flight out-of-pages state, lower concurrency per byte).
        ``pipeline``/``async_depth``/``harvest_interval``: the serving
        host-path pipeline knobs (module docstring).  Defaults come from
        ``config`` (a ``DeepSpeedInferenceConfig``/dict with a ``v2``
        subtree: ``inference.v2.pipeline`` default-on, ``async_depth``
        2, ``harvest_interval`` 4); explicit kwargs win.
        ``pipeline=False`` preserves the unpipelined host loop exactly
        — one blocking harvest and a fresh metadata upload per
        dispatch.
        ``speculation``: ``None`` (config subtree decides; off by
        default), a mode string (``"ngram"``/``"draft"``/``"off"``), a
        dict, or a :class:`~deepspeed_tpu.inference.config.SpeculationConfig`
        — speculative decoding on the decode-block path (module
        docstring).  ``mode="draft"`` additionally needs ``draft_model``
        (a small same-vocab llama-family zoo module) and its
        ``draft_params``.
        ``kv_tiering``: ``None`` (config subtree decides; off by
        default), a dict (implies ``enabled=True`` unless it says
        otherwise), or a
        :class:`~deepspeed_tpu.inference.config.KVTieringConfig` —
        host-RAM + NVMe spill tiers for the paged-KV pool
        (:mod:`deepspeed_tpu.inference.kv_tiering`).  With tiering
        disabled the engine is byte-for-byte the untiered engine.
        ``prefix_cache``: ``None`` (config subtree decides; off by
        default), a bool, a dict (implies ``enabled=True``), or a
        :class:`~deepspeed_tpu.inference.config.PrefixCacheConfig` —
        cross-request KV sharing over the paged pool
        (:mod:`deepspeed_tpu.inference.prefix_cache`): admission
        attaches fully-matched resident prefix pages read-only and
        prefills only the non-cached suffix; the first divergent write
        copy-on-writes.  Greedy outputs are bit-identical to
        cache-off, and seeded sampling too, because sampling keys are
        position-keyed (:func:`~deepspeed_tpu.inference.sampling.position_keys`)
        rather than drawn from a dispatch-ordered stream.
        ``slo``: ``None`` (config subtree ``v2.slo`` decides; off by
        default), a list of objective strings like
        ``"ttft_ms_p99 <= 150"``, or a prebuilt
        :class:`~deepspeed_tpu.telemetry.slo.SLOSet` — every reaped
        request feeds its summary record; ``serving_stages()["slo"]``
        carries the rolling error-budget burn per objective.
        ``trace_sample``: tail-based trace sampling N (kwarg > config
        ``v2.trace_sample`` > env ``DSTPU_TRACE_SAMPLE``).  When the
        tracer's sampling mode is armed, a reaped request's spans are
        promoted to the retained ring only on SLO breach, error, or a
        deterministic 1-in-N draw.
        ``replica``: metric-label identity for scale-out serving — each
        replica engine's registry children (``dstpu_request_*``,
        ``dstpu_serving_stage_seconds``) carry ``replica="<value>"`` so
        ``export_text()`` distinguishes replicas; solo engines keep the
        empty label value."""
        mcfg = getattr(model, "config", None)
        assert dataclasses.is_dataclass(mcfg) and hasattr(mcfg, "decode"), \
            "ragged engine needs a model-zoo module with a decode config"
        assert hasattr(mcfg, "rope_theta"), (
            "ragged batching requires per-token positions through "
            "attention — supported by the Llama family models")
        assert hasattr(mcfg, "paged_decode"), (
            "model config predates paged ragged decode support")

        import deepspeed_tpu.comm as dist

        if topology is not None:
            dist.set_topology(topology)
        else:
            topology = dist.peek_topology()
        self.topology = topology
        self.mesh = topology.mesh if topology is not None else None
        self.tp = (topology.tensor_parallel_size
                   if topology is not None else 1)

        # config-sourced knobs resolve BEFORE pool sizing: the resolved
        # kv_cache_dtype decides the per-page byte cost a kv_pool_bytes
        # budget divides by (kwarg > config > default, as for every
        # other v2 knob)
        if config is not None:
            from deepspeed_tpu.inference.config import \
                load_inference_config

            v2cfg = load_inference_config(config).v2
            pipeline = v2cfg.pipeline if pipeline is None else pipeline
            async_depth = (v2cfg.async_depth if async_depth is None
                           else async_depth)
            harvest_interval = (v2cfg.harvest_interval
                                if harvest_interval is None
                                else harvest_interval)
            speculation = (v2cfg.speculation if speculation is None
                           else speculation)
            kv_tiering = (v2cfg.kv_tiering if kv_tiering is None
                          else kv_tiering)
            prefix_cache = (v2cfg.prefix_cache if prefix_cache is None
                            else prefix_cache)
            kv_cache_dtype = (v2cfg.kv_cache_dtype
                              if kv_cache_dtype is None
                              else kv_cache_dtype)
            slo = (v2cfg.slo if slo is None else slo)
            trace_sample = (v2cfg.trace_sample if trace_sample is None
                            else trace_sample)
            control = v2cfg.control if control is None else control
        kv_cache_dtype = ("none" if kv_cache_dtype is None
                          else str(kv_cache_dtype))
        assert kv_cache_dtype in ("none", "int8", "fp8", "fp8_e4m3"), (
            f"kv_cache_dtype must be none|int8|fp8|fp8_e4m3, got "
            f"{kv_cache_dtype!r}")
        self.kv_cache_dtype = kv_cache_dtype

        self.page_size = int(page_size)
        self.pages_per_seq = pages_for(max_seq_len, self.page_size)
        if num_pages is None and kv_pool_bytes is not None:
            # byte-accounted sizing: probe the exact per-page device
            # cost (quantized pools count the 1-byte payload AND the
            # fp32 scale rows) and fit as many pages as the budget holds
            # — page 0 is the trash page, so >= 2 keeps one usable
            page_bytes = _paged_kv_page_bytes(
                model, mcfg, self.page_size, kv_cache_dtype)
            num_pages = max(2, int(kv_pool_bytes) // page_bytes)
        if num_pages is None:
            # full provisioning: every slot can reach max_seq_len. Callers
            # serving long-max_len traffic shrink this — memory then
            # scales with tokens in flight (admission backpressure).
            num_pages = 1 + max_seqs * self.pages_per_seq
        self.num_pages = int(num_pages)

        self._unroll_params = bool(getattr(mcfg, "scan_layers", False))
        self.cfg = dataclasses.replace(
            mcfg, decode=True, ragged_decode=False, paged_decode=True,
            max_cache_len=max_seq_len, scan_layers=False,
            kv_page_size=self.page_size, kv_num_pages=self.num_pages,
            tensor_parallel=self.tp > 1, kv_cache_dtype=kv_cache_dtype)
        self.model = type(model)(self.cfg)
        self.max_seqs = max_seqs
        self.max_seq_len = max_seq_len
        self.prefill_chunk = prefill_chunk
        self.T = max_seqs + prefill_chunk          # fused batch width
        self.decode_block_size = max(int(decode_block_size), 1)
        assert kv_reserve in ("on_demand", "worst_case"), kv_reserve
        self.kv_reserve = kv_reserve
        self.evictions = 0
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        # base key for position-keyed sampling (sampling.position_keys):
        # derived from the pristine engine rng BEFORE any split, so a
        # drawn token's key depends only on (engine seed, uid, position)
        # — never on how dispatches happened to be scheduled
        self._sample_base = jax.random.fold_in(self.rng, 0x5EED)

        self.pipeline = True if pipeline is None else bool(pipeline)
        self.async_depth = max(
            int(async_depth) if async_depth is not None else 2, 1)
        self.harvest_interval = max(
            int(harvest_interval) if harvest_interval is not None else 4,
            1)
        self.replica = "" if replica is None else str(replica)
        self.host_stats = HostStageStats(replica=self.replica)
        # substrate timers for the pipelined decode window (submitted/
        # completed counters + submit_wait brackets; serving_stages()
        # exposes the snapshot as ``pipeline_window``)
        self._pipe_timers = StageTimers(cat="serving")
        # per-request lifecycle latency (TTFT/TPOT/queue-wait/spill-
        # stall percentiles) — always on; independent of the tracer
        self.request_latency = RequestLatencyTracker(replica=self.replica)

        # -- SLO objectives + tail-based trace sampling --
        # All evaluation happens at reap time on the host — the traced
        # dispatch path never sees the registry or the sampler, so the
        # zero-new-compilations guarantee is structural, not incidental.
        from deepspeed_tpu.telemetry.slo import SLOSet, TailSampler

        if slo is None or slo is False or (isinstance(slo, (list, tuple))
                                           and not slo):
            self.slo = None
        elif isinstance(slo, SLOSet):
            self.slo = slo
        else:
            self.slo = SLOSet(list(slo))
        n = (int(trace_sample) if trace_sample is not None
             else trace.sample_n)
        self._tail_sampler = (TailSampler(n=n)
                              if (trace.sampling or n > 0) else None)
        if n > 0 and not trace.sampling:
            # an explicit engine/config N arms the tracer's sampling
            # mode the same way DSTPU_TRACE_SAMPLE does
            trace.configure(enabled=True, sampling=True, sample_n=n)
        # device-resident decode-loop state while the pipeline runs
        # ahead of the host (None <=> host state is authoritative)
        self._dev: Optional[Dict[str, Any]] = None

        # -- speculative decoding config (module docstring) --
        from deepspeed_tpu.inference.config import SpeculationConfig

        if speculation is None:
            speculation = SpeculationConfig()
        elif isinstance(speculation, str):
            speculation = SpeculationConfig(mode=speculation)
        elif isinstance(speculation, dict):
            speculation = SpeculationConfig(**speculation)
        self.spec_mode = speculation.mode
        self.spec_k = int(speculation.k)
        self.spec_ngram = int(speculation.ngram)
        self._spec_block_cache: Dict[bool, Any] = {}
        self._draft = None
        self._draft_params: Any = {}
        self._draft_cache: Any = {}
        self._draft_unroll = False
        self._draft_prefill = None
        # host tracker: draft KV coverage per slot (positions < value
        # hold correct draft K/V; reset on admit/evict/reap)
        self._draft_len = np.zeros((max_seqs,), np.int64)

        from deepspeed_tpu.inference.common import normalize_params

        params = normalize_params(
            model, params,
            plain_model=type(model)(dataclasses.replace(mcfg,
                                                        decode=False)))
        self._wq = quantize_weights
        self._wq_native = False
        if quantize_weights is not None:
            assert self.tp <= 1, (
                "quantize_weights does not compose with tensor-parallel "
                "serving yet — quantized leaves carry their own "
                "group-scale layout")
            from deepspeed_tpu.inference.quantization import \
                quantize_param_tree
            from deepspeed_tpu.parallel import tensor_parallel as tp_lib

            # "w8a8" (explicit opt-in — it quantizes ACTIVATIONS too, so
            # numerics differ from weight-only "int8") runs the NATIVE
            # path on models whose Dense layers consume quantized
            # kernels: int8 stays on the per-tick read path (decode is
            # weight-bandwidth-bound — tree-level dequant reads 2x the
            # bytes), dotted on the MXU's int8 path with dynamic
            # per-row activation scales
            if quantize_weights == "w8a8":
                assert getattr(type(model), "w8a8_native", False), (
                    f"quantize_weights='w8a8' needs a model whose Dense "
                    f"layers consume w8a8 kernels natively (the Llama "
                    f"family: llama/mistral/qwen2); "
                    f"{type(model).__name__} does not — use weight-only "
                    f"'int8' instead")
                self._wq_native = True
                self.cfg = dataclasses.replace(self.cfg,
                                               weight_quant="w8a8")
                self.model = type(model)(self.cfg)
                if self._unroll_params:
                    # unroll scan-stacked [L, ...] kernels NOW: the
                    # per-channel w8a8 format is 2-D-kernel only, so a
                    # stacked tree would silently fall back to the
                    # dequant path for every block kernel
                    from deepspeed_tpu.inference.common import \
                        unroll_scan_params

                    params = (
                        {"params": unroll_scan_params(params["params"])}
                        if isinstance(params, dict) and "params" in params
                        else unroll_scan_params(params))
                    self._unroll_params = False
            # unbox flax Partitioned metadata FIRST: the quantizer's
            # leaf-name check reads path tails, which inside a metadata
            # box are the box's own keys — boxed trees would silently
            # pass through unquantized
            if tp_lib.has_partitioning(params):
                params = tp_lib.unbox_params(params)
            params, b0, b1 = quantize_param_tree(params, quantize_weights)
            params = jax.device_put(params)
            log_dist(f"ragged engine weights -> {quantize_weights}"
                     f"{' (native int8 dots)' if self._wq_native else ''}: "
                     f"{b0 / 2**20:.1f} MiB -> {b1 / 2**20:.1f} MiB "
                     f"({b0 / max(b1, 1):.2f}x)", ranks=[0])
        self.params = self._place_params(params)

        self.allocator = PageAllocator(self.num_pages, self.page_size)
        self.page_table = np.full((max_seqs, self.pages_per_seq), -1,
                                  np.int32)
        self.cache = self._init_cache()
        if self.spec_mode == "draft":
            if draft_model is None:
                raise ValueError(
                    "speculation.mode='draft' needs a draft model: pass "
                    "draft_model=<small same-vocab llama-family module> "
                    "and draft_params=... (the config's "
                    "speculation.draft_model preset name is for CLIs to "
                    "construct one)")
            assert self.tp <= 1, (
                "draft-model speculation does not compose with "
                "tensor-parallel serving yet")
            dmcfg = getattr(draft_model, "config", None)
            assert (dataclasses.is_dataclass(dmcfg) and
                    hasattr(dmcfg, "rope_theta") and
                    hasattr(dmcfg, "paged_decode")), (
                "draft model must be a llama-family model-zoo module "
                "(the ragged paged decode path's requirement)")
            assert dmcfg.vocab_size == mcfg.vocab_size, (
                f"draft vocab {dmcfg.vocab_size} != target vocab "
                f"{mcfg.vocab_size} — speculative verify compares token "
                "ids, the models must share a tokenizer")
            self._draft_unroll = bool(getattr(dmcfg, "scan_layers",
                                              False))
            # the draft pool defaults to the target pool's storage
            # format — self-draft speculation gets the same capacity
            # win unless the caller overrides draft_kv_cache_dtype
            draft_fmt = (self.kv_cache_dtype
                         if draft_kv_cache_dtype is None
                         else str(draft_kv_cache_dtype))
            assert draft_fmt in ("none", "int8", "fp8", "fp8_e4m3"), (
                f"draft_kv_cache_dtype must be none|int8|fp8|fp8_e4m3, "
                f"got {draft_fmt!r}")
            self.draft_kv_cache_dtype = draft_fmt
            self._draft_cfg = dataclasses.replace(
                dmcfg, decode=True, ragged_decode=False,
                paged_decode=True, max_cache_len=max_seq_len,
                scan_layers=False, kv_page_size=self.page_size,
                kv_num_pages=self.num_pages, tensor_parallel=False,
                kv_cache_dtype=draft_fmt)
            self._draft = type(draft_model)(self._draft_cfg)
            from deepspeed_tpu.parallel import tensor_parallel as tp_lib
            dparams = normalize_params(
                draft_model, draft_params,
                plain_model=type(draft_model)(dataclasses.replace(
                    dmcfg, decode=False)))
            if tp_lib.has_partitioning(dparams):
                dparams = tp_lib.unbox_params(dparams)
            self._draft_params = jax.device_put(dparams)
            self._draft_cache = self._init_cache(self._draft)
        self._uid = itertools.count()
        self.waiting: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * max_seqs
        self.finished: List[Request] = []
        self._unclaimed: Dict[int, np.ndarray] = {}
        self._step_fn = None
        self._decode_block_cache: Dict[bool, Any] = {}
        self._last_tokens = np.zeros((max_seqs,), np.int32)
        # streaming cursor: generated-token count already reported per
        # uid (stream_deltas); cancels counts cancellations at any stage
        self._stream_cursor: Dict[int, int] = {}
        self.cancels = 0
        # disaggregated serving: sessions whose prefill (+ first token)
        # finished and which now wait for the router to pull them via
        # export_handoff — out of slots and out of the waiting queue
        self._handoff_ready: List[Request] = []
        self.handoffs = 0              # sessions exported to a decoder
        self.handoff_folds = 0         # handoffs degraded to re-prefill

        # -- tiered KV spill store (HBM -> host RAM -> NVMe) --
        from deepspeed_tpu.inference.config import KVTieringConfig

        if kv_tiering is None:
            kv_tiering = KVTieringConfig()
        elif isinstance(kv_tiering, dict):
            kv_tiering = KVTieringConfig(**{"enabled": True, **kv_tiering})
        self._tier_cfg = kv_tiering
        self.tiering = None
        self._tier_gather = None       # jitted fixed-shape page gather
        self._tier_scatter = None      # jitted fixed-shape page scatter
        self._lc = None                # lazy LongContextDriver
        # queued spilled sequences prefetched ahead of a possible reap
        # (config field, online-tunable via the kv.prefetch_lookahead
        # knob — was a hardcoded islice(waiting, 8))
        self.prefetch_lookahead = max(
            int(getattr(kv_tiering, "prefetch_lookahead", 8)), 1)
        self._sched_seq = 0            # step counter for victim coldness
        self._last_sched = np.zeros((max_seqs,), np.int64)
        self.spills = 0                # sessions spilled to the tiers
        self.restores = 0              # sessions restored bit-identically
        if kv_tiering.enabled:
            assert self.kv_reserve == "on_demand", (
                "kv_tiering requires kv_reserve='on_demand' — the spill "
                "tiers ARE the on-demand model's overflow story; a "
                "worst-case reservation could never admit what tiering "
                "holds")
            from deepspeed_tpu.inference.kv_tiering import TieredKVStore

            leaves, self._cache_treedef = jax.tree_util.tree_flatten(
                self.cache)
            assert all(leaf.shape[0] == self.num_pages
                       for leaf in leaves), (
                "every paged-KV cache leaf must lead with the page dim")
            self.tiering = TieredKVStore(
                page_shapes=[leaf.shape[1:] for leaf in leaves],
                page_dtypes=[np.dtype(leaf.dtype) for leaf in leaves],
                pages_per_seq=self.pages_per_seq,
                host_pages=kv_tiering.host_pages,
                nvme_pages=kv_tiering.nvme_pages,
                nvme_dir=kv_tiering.nvme_dir,
                use_odirect=kv_tiering.use_odirect,
                prefetch=kv_tiering.prefetch,
                verify=kv_tiering.verify,
                checksum=kv_tiering.checksum,
                max_reread=kv_tiering.max_reread,
                nvme_fail_threshold=kv_tiering.nvme_fail_threshold,
                probe_every=kv_tiering.probe_every)
        # -- cross-request prefix cache over the paged pool --
        from deepspeed_tpu.inference.config import PrefixCacheConfig

        if prefix_cache is None:
            prefix_cache = PrefixCacheConfig()
        elif isinstance(prefix_cache, bool):
            prefix_cache = PrefixCacheConfig(enabled=prefix_cache)
        elif isinstance(prefix_cache, dict):
            prefix_cache = PrefixCacheConfig(
                **{"enabled": True, **prefix_cache})
        self._pfx_cfg = prefix_cache
        self._pfx: Optional[PrefixCacheIndex] = None
        self._cow_jit = None           # jitted fixed-shape page copy
        if prefix_cache.enabled:
            self._pfx = PrefixCacheIndex(
                self.allocator, self.page_size,
                max_entries=prefix_cache.max_index_entries,
                min_match_pages=prefix_cache.min_match_pages)
            if self.tiering is not None:
                # under pool pressure, cold single-ref prefix pages
                # demote into the tier store keyed by prefix hash (one
                # restore serves every waiter) instead of being dropped
                self._pfx.demote = self._pfx_demote
                self._pfx.drop_spilled = self.tiering.drop
        tier_note = ""
        if self.tiering is not None:
            tier_note = (f" kv_tiering=host:{kv_tiering.host_pages}"
                         f"+nvme:{kv_tiering.nvme_pages}p")
        if self._pfx is not None:
            tier_note += (f" prefix_cache=max:"
                          f"{prefix_cache.max_index_entries}"
                          f"/min:{prefix_cache.min_match_pages}p")
        log_dist(
            f"RaggedInferenceEngineV2: max_seqs={max_seqs} "
            f"max_seq_len={max_seq_len} prefill_chunk={prefill_chunk} "
            f"pages={self.num_pages}x{self.page_size} tp={self.tp} "
            f"decode_block={self.decode_block_size} "
            f"pipeline={self.pipeline} depth={self.async_depth} "
            f"harvest={self.harvest_interval} "
            f"spec={self.spec_mode}"
            f"{f'/k={self.spec_k}' if self.spec_mode != 'off' else ''}"
            f"{tier_note} "
            f"(paged KV, fused SplitFuse step)", ranks=[0])

        # -- closed-loop control plane (deepspeed_tpu.control) --
        # Ticks on this host loop (step() counts engine steps); no
        # thread of its own.  DSTPU_CONTROL=0 disarms regardless of
        # config, leaving the structurally pre-control engine.
        from deepspeed_tpu.inference.config import ControlConfig

        if control is None:
            control = ControlConfig()
        elif isinstance(control, bool):
            control = ControlConfig(enabled=control)
        elif isinstance(control, dict):
            control = ControlConfig(**{"enabled": True, **control})
        self._control_cfg = control
        self._controller = None
        self._control_steps = 0
        if control.enabled:
            from deepspeed_tpu.control import (Controller, control_enabled,
                                               engine_signal_feed,
                                               load_profile, prefetch_rule)
            if control_enabled():
                knobs = self.knob_registry()
                prof = load_profile(control.profile)
                if prof is not None:
                    # profile seeding runs pre-warmup, so recompiling
                    # knobs (decode_block, spec k) are still fair game
                    applied = knobs.apply_profile(prof.knobs)
                    if applied:
                        log_dist(
                            f"control plane seeded from host profile "
                            f"{prof.key}: {applied}", ranks=[0])
                rules = []
                if self.tiering is not None:
                    rules.append(prefetch_rule())
                self._controller = Controller(
                    knobs, engine_signal_feed(self),
                    objective=control.objective,
                    settle=control.settle,
                    hysteresis=control.hysteresis,
                    cooldown=control.cooldown,
                    guard_window=control.guard_window,
                    guard_reverts=control.guard_reverts,
                    freeze=control.freeze, smooth=control.smooth,
                    rules=rules)

    # -- parameter / cache placement (TP) --------------------------------

    def _place_params(self, params):
        """TP-shard (AutoTP name rules / flax metadata) over the `tensor`
        mesh axis, mirroring the v1 engine; replicate otherwise."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deepspeed_tpu.parallel import tensor_parallel as tp_lib

        if self.tp <= 1:
            if tp_lib.has_partitioning(params):
                params = tp_lib.unbox_params(params)
            return params
        if tp_lib.has_partitioning(params):
            specs = tp_lib.extract_partition_specs(
                {"params": params}, self.mesh.axis_names)["params"]
            params = tp_lib.unbox_params(params)
            # training-oriented metadata (e.g. a MoE bank's `expert` axis
            # with no `tensor` entries) doesn't shard a TP serving mesh —
            # fall back to AutoTP name rules
            if not any("tensor" in tuple(s)
                       for s in jax.tree_util.tree_leaves(
                           specs, is_leaf=lambda x: isinstance(x, P))):
                specs = None
        else:
            specs = None
        if specs is None:
            specs = tp_lib.auto_tp_specs(params, self.tp)
            log_dist("ragged engine AutoTP: inferred tensor-parallel "
                     "sharding from parameter names", ranks=[0])
        self._param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        return jax.tree_util.tree_map(
            lambda x, sh: jax.device_put(jnp.asarray(x), sh), params,
            self._param_shardings)

    def _cache_sharding(self, leaf_shape):
        """KV page pools shard their combined-head dim over `tensor`
        (reference v2 KV sharding: heads split over the TP group); the
        quantized pools' [P, page, 2Hkv] scale buffers shard the same
        head dim."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.tp <= 1:
            return None
        if len(leaf_shape) == 4:
            return NamedSharding(self.mesh, P(None, None, "tensor", None))
        if len(leaf_shape) == 3:
            return NamedSharding(self.mesh, P(None, None, "tensor"))
        return None

    # -- request API ----------------------------------------------------

    def set_replica(self, replica: str) -> None:
        """Assign the scale-out metric-label identity after
        construction (``ReplicaSet`` labels engines built without
        one); re-labels the stage/latency emitters in place."""
        self.replica = str(replica)
        self.host_stats.set_replica(self.replica)
        self.request_latency.set_replica(self.replica)

    def validate_request(self, prompt, max_new_tokens: int = 64) -> None:
        """The submit-time schedulability checks, without enqueuing —
        raises ``ValueError`` for a request that could never run on
        THIS engine.  The scale-out router calls this before accepting
        a request (its typed rejection wraps the message), so loud
        rejection happens at the front door rather than deep inside a
        replica's feed queue."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (prefill seeds the first "
                "token)")
        total = prompt.size + max_new
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) = "
                f"{total} exceeds the engine token budget "
                f"max_seq_len={self.max_seq_len} — the request can never "
                "be scheduled; shorten the prompt or raise max_seq_len")
        if self.tiering is None:
            if self.allocator.pages_for(total) > self.num_pages - 1:
                raise ValueError(
                    f"request needs {self.allocator.pages_for(total)} KV "
                    f"pages but the engine owns {self.num_pages - 1} "
                    "usable pages — even after evicting every other "
                    "sequence it could never be scheduled; raise "
                    "num_pages")
        else:
            # two separate bounds, named separately in the rejection:
            # (1) with long_context, the RESIDENT-WINDOW need (sinks +
            # recent window + staging slack) must fit HBM — without it
            # the working-set bound stays an admission-time check, so
            # tiering keeps accepting requests beyond HBM whose others
            # spill (max_new_tokens is a budget, not a promise); (2)
            # the COMBINED-TIER total must fit HBM + host + NVMe.
            total_pages = self.allocator.pages_for(total)
            usable = self.num_pages - 1
            lc = bool(self._tier_cfg.long_context)
            if lc:
                resident_need = min(total_pages,
                                    self._lc_resident_pages())
                if resident_need > usable:
                    raise ValueError(
                        f"request needs {resident_need} HBM-resident KV "
                        "pages (the partial-residency window: "
                        "sink_pages + window_pages + chunk_pages + 1) "
                        f"but the HBM tier owns {usable} usable pages — "
                        "raise num_pages or shrink the kv_tiering "
                        "sink_pages/window_pages/chunk_pages knobs")
            cap = usable + self.tiering.budget_pages
            if total_pages > cap:
                raise ValueError(
                    f"request needs {total_pages} KV pages in total but "
                    f"the combined tiers — HBM ({usable} usable) + host "
                    f"({self.tiering.host_budget}) + NVMe "
                    f"({self.tiering.nvme_budget}) — hold only {cap} "
                    "— it could never be scheduled; raise num_pages or "
                    "the kv_tiering host_pages/nvme_pages budgets")

    def put_request(self, prompt, **kw) -> int:
        """Queue a request; raises ``ValueError`` AT SUBMIT TIME for a
        request that could never be scheduled (a prompt + budget beyond
        ``max_seq_len``, or needing more KV pages than the whole pool
        holds even after evicting every other sequence) — admitting one
        would deadlock the FIFO queue behind an unschedulable head.
        (``ValueError``, not ``assert``: these guard USER input and must
        stay loud under ``python -O``.)"""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(kw.get("max_new_tokens", 64))
        self.validate_request(prompt, max_new)
        req = Request(uid=next(self._uid), prompt=prompt, **kw)
        if (self.tiering is not None and self._tier_cfg.long_context
                and self.allocator.pages_for(prompt.size + max_new)
                > self.num_pages - 1):
            # the full KV cannot be device-resident: decode under the
            # windowed partial-residency policy (LongContextDriver)
            req.lc = True
        self.waiting.append(req)
        self.request_latency.on_submit(req.uid)
        if trace.enabled:
            trace.event("request_submit", cat="request", uid=req.uid,
                        prompt_len=int(prompt.size), max_new=max_new)
        return req.uid

    def get_outputs(self) -> List[Tuple[int, np.ndarray]]:
        out = list(self._unclaimed.items())
        self._unclaimed = {}
        out += [(r.uid, np.concatenate([r.prompt,
                                        np.asarray(r.generated, np.int32)]))
                for r in self.finished]
        self.finished = []
        for uid, _ in out:
            self._stream_cursor.pop(uid, None)
        return out

    def stream_deltas(self) -> List[Tuple[int, List[int], int, bool]]:
        """Incremental token harvest for streaming front ends: one
        ``(uid, new_tokens, total_generated, done)`` tuple per request
        whose generated-token count grew since the last call, plus
        every newly finished request (even with no fresh tokens).
        Tokens appear here exactly when they fold into host request
        state — HARVEST granularity, the honest streaming grain under
        the deferred-harvest pipeline.  Cursors are engine-side;
        callers that re-route across replicas de-duplicate with the
        cumulative ``total_generated`` (a re-routed request replays
        its tokens from zero on the new engine).

        Call BEFORE :meth:`get_outputs` in the same tick — collecting
        an output clears its cursor."""
        out: List[Tuple[int, List[int], int, bool]] = []
        cur = self._stream_cursor
        live = [r for r in self.slots if r is not None]
        for r in itertools.chain(live, self.waiting, self._handoff_ready):
            n = len(r.generated)
            seen = cur.get(r.uid, 0)
            if n > seen:
                out.append((r.uid, [int(t) for t in r.generated[seen:]],
                            n, False))
                cur[r.uid] = n
        for r in self.finished:
            n = len(r.generated)
            seen = cur.pop(r.uid, 0)
            out.append((r.uid, [int(t) for t in r.generated[seen:]],
                        n, True))
        return out

    def cancel(self, uid: int) -> Optional[str]:
        """Cancel one request at ANY lifecycle stage, releasing every
        resource it holds: slot + pool pages (mid-prefill or
        mid-decode, including inside a pipelined decode carry), tiered
        spill payloads and their shared-prefix spill-holds (parked
        requests), LC middle-group parkings, and per-slot draft state.
        ``audit_kv_sharing()`` stays clean across any interleaving —
        the front door's client-disconnect path depends on it.

        Returns the stage the request was cancelled at (``"queued"`` /
        ``"spilled"`` / ``"prefill"`` / ``"decode"`` / ``"lc"`` /
        ``"finished"``) or ``None`` for an unknown uid (never
        submitted, or already collected)."""
        stage: Optional[str] = None
        # fold an active pipelined carry first: the target may be
        # mid-decode inside it, and teardown re-anchors host state so
        # the slot release below is authoritative (the target may
        # FINISH during this harvest — then it lands in ``finished``)
        dv = self._dev
        if dv is not None and any(r.uid == uid for r in dv["reqs"]):
            self._pipeline_harvest(teardown=True)
        # parked in the waiting queue: never admitted, an evicted
        # continuation, or spilled out to the tiers
        for r in list(self.waiting):
            if r.uid != uid:
                continue
            self.waiting.remove(r)
            if r.spilled is not None:
                # release the spill-holds pinning shared prefix pages
                # resident, then the tiered payload itself (the same
                # cleanup export_parked runs when folding a session)
                for p in r.spilled.get("shared_pages", ()):
                    self.allocator.decref(p)
                if self.tiering is not None:
                    self.tiering.drop(r.uid)
                stage = "spilled"
            else:
                stage = "queued"
            self._drop_lc_parked(r)
            break
        if stage is None:
            # parked for a prefill->decode handoff the router never
            # collected: release like a waiting spilled session
            for r in list(self._handoff_ready):
                if r.uid != uid:
                    continue
                self._handoff_ready.remove(r)
                if r.spilled is not None:
                    for p in r.spilled.get("shared_pages", ()):
                        self.allocator.decref(p)
                    if self.tiering is not None:
                        self.tiering.drop(r.uid)
                stage = "handoff"
                break
        if stage is None:
            # resident in a slot (prefill or decode phase; LC sequences
            # tick outside the fused batch but park in slots the same)
            for i, r in enumerate(self.slots):
                if r is None or r.uid != uid:
                    continue
                stage = ("lc" if r.lc
                         else "prefill" if r.prefill_done < r.ctx_len
                         else "decode")
                self._drop_lc_parked(r)
                self.allocator.free(i)
                self.page_table[i, :] = -1
                self.slots[i] = None
                self._draft_len[i] = 0
                break
        if stage is None:
            # reaped but not yet collected: drop the pending output
            for r in list(self.finished):
                if r.uid == uid:
                    self.finished.remove(r)
                    stage = "finished"
                    break
        if stage is None and uid in self._unclaimed:
            del self._unclaimed[uid]
            stage = "finished"
        if stage is None:
            return None
        self.cancels += 1
        self._stream_cursor.pop(uid, None)
        self.request_latency.on_cancel(uid)
        if trace.enabled:
            trace.event("request_cancel", cat="request", uid=int(uid),
                        stage=stage)
        return stage

    def _drop_lc_parked(self, r: Request) -> None:
        """Release a long-context request's parked middle page groups
        (the tier keys ``_reap`` would drop at finish)."""
        if self.tiering is not None:
            for g in range(r.lc_parked):
                self.tiering.drop(f"mid-{r.uid}-{g}")
        r.lc_parked = 0

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def sync(self) -> int:
        """Fold any pipelined in-flight device work back into host
        request state (deferred-harvest flush); returns the tokens
        folded.  No-op when the pipeline is off or idle.  Callers that
        read ``slots[*].generated`` / ``finished`` between ``step()``
        calls (benchmark boundaries, draining shutdown) call this
        first."""
        if self._dev is None:
            return 0
        return self._pipeline_harvest()

    def drain(self) -> Dict[int, np.ndarray]:
        """Run until every queued/resident request finishes; returns
        ``{uid: tokens}`` for everything reaped along the way (the
        replica-shutdown half of the engine handle — ``close()``
        releases resources after)."""
        outs: Dict[int, np.ndarray] = {}
        # nobody is coming to collect a pending handoff during a drain:
        # finish those sessions locally through the normal spilled /
        # continuation re-admission path
        for r in self._handoff_ready:
            r.handoff = False
            self.waiting.append(r)
        self._handoff_ready = []
        while self.has_work():
            self.step()
            outs.update(self.get_outputs())
        self.sync()
        outs.update(self.get_outputs())
        return outs

    # -- elastic shrink: parked-session handoff --------------------------

    def export_parked(self) -> List[Dict[str, Any]]:
        """Pop every PARKED session (the waiting queue: not yet
        admitted, spilled out of the pool, or queued as a re-prefill
        continuation) and return portable session blobs for
        :meth:`import_parked` on another replica.  A spilled session's
        private pages travel in SPILL FORMAT (packed bytes + the
        spill-time digests via ``TieredKVStore.export_spilled``), so
        the receiver's restore verifies them end-to-end.  Shared-prefix
        pages are rows in THIS engine's HBM and cannot travel — a
        session holding any folds to a re-prefill continuation
        (``ctx = prompt + generated``), which is output-identical under
        greedy decode, just re-paying its prefill."""
        sessions: List[Dict[str, Any]] = []
        while self.waiting:
            r = self.waiting.popleft()
            blob = self._session_blob(r)
            if trace.enabled:
                trace.event("request_export", cat="request", uid=r.uid,
                            spilled=blob["spill"] is not None)
            sessions.append(blob)
        # pending prefill->decode handoffs the router never pulled ride
        # the same retirement: they are parked sessions like any other
        for r in self._handoff_ready:
            blob = self._session_blob(r)
            if trace.enabled:
                trace.event("request_export", cat="request", uid=r.uid,
                            spilled=blob["spill"] is not None)
            sessions.append(blob)
        self._handoff_ready = []
        return sessions

    def _session_blob(self, r: Request) -> Dict[str, Any]:
        """Portable session blob for ``import_parked`` on another
        replica (shared by :meth:`export_parked` and
        :meth:`export_handoff`).  A spilled session's private pages
        travel in spill format via ``TieredKVStore.export_spilled``;
        one pinning shared-prefix pages (rows in THIS engine's HBM —
        they cannot travel) folds to a re-prefill continuation."""
        blob: Dict[str, Any] = {
            "uid": int(r.uid),
            "prompt": np.asarray(r.prompt, np.int32),
            "max_new_tokens": int(r.max_new_tokens),
            "eos_token_id": r.eos_token_id,
            "do_sample": bool(r.do_sample),
            "temperature": float(r.temperature),
            "top_k": int(r.top_k),
            "top_p": float(r.top_p),
            "generated": [int(t) for t in r.generated],
            "ctx": (None if r.ctx is None
                    else np.asarray(r.ctx, np.int32)),
            "prefill_done": int(r.prefill_done),
            "spill": None}
        if r.spilled is not None:
            shared = [int(p) for p in r.spilled.get("shared_pages",
                                                    ())]
            n_priv = int(r.spilled.get("n_pages", 0))
            holds = (self.tiering is not None
                     and self.tiering.holds(r.uid))
            if shared or (n_priv > 0 and not holds):
                # fold to a re-prefill continuation; release the
                # spill-holds and the orphaned payload
                for p in shared:
                    self.allocator.decref(p)
                if self.tiering is not None:
                    self.tiering.drop(r.uid)
                blob["ctx"] = np.concatenate(
                    [r.prompt, np.asarray(r.generated, np.int32)])
                blob["prefill_done"] = 0
            else:
                blob["spill"] = {
                    "last_tok": int(r.spilled["last_tok"]),
                    "live_tokens": int(r.spilled["live_tokens"]),
                    "payload": (self.tiering.export_spilled(r.uid)
                                if n_priv > 0 else None)}
        return blob

    def import_parked(self, sessions: List[Dict[str, Any]]) -> List[int]:
        """Receiving half of the handoff: install each exported session
        as a local waiting :class:`Request` under a FRESH uid (uids are
        per-engine) and park its spill payload in the local tier store
        with the donor's digests.  Returns the new uids in input order
        — the router re-keys its ledger with them.  A payload the local
        tiers can't hold folds to a re-prefill continuation instead of
        being dropped."""
        new_uids: List[int] = []
        for s in sessions:
            req = Request(uid=next(self._uid),
                          prompt=np.asarray(s["prompt"], np.int32),
                          max_new_tokens=int(s.get("max_new_tokens", 64)),
                          eos_token_id=s.get("eos_token_id"),
                          do_sample=bool(s.get("do_sample", False)),
                          temperature=float(s.get("temperature", 1.0)),
                          top_k=int(s.get("top_k", 0)),
                          top_p=float(s.get("top_p", 1.0)))
            req.generated = [int(t) for t in s.get("generated", ())]
            ctx = s.get("ctx")
            req.ctx = None if ctx is None else np.asarray(ctx, np.int32)
            req.prefill_done = int(s.get("prefill_done", 0))
            sp = s.get("spill")
            if sp is not None:
                payload = sp.get("payload")
                installed = payload is None
                if payload is not None and self.tiering is not None:
                    try:
                        self.tiering.import_spilled(req.uid, payload)
                        installed = True
                    except (ValueError, RuntimeError):
                        installed = False
                if installed:
                    req.spilled = {
                        "last_tok": int(sp["last_tok"]),
                        "n_pages": (int(payload["n_pages"])
                                    if payload is not None else 0),
                        "live_tokens": int(sp["live_tokens"]),
                        "shared_pages": []}
                else:
                    req.ctx = np.concatenate(
                        [req.prompt,
                         np.asarray(req.generated, np.int32)])
                    req.prefill_done = 0
            self.waiting.append(req)
            self.request_latency.on_submit(req.uid)
            if trace.enabled:
                trace.event("request_import", cat="request", uid=req.uid,
                            donor_uid=int(s.get("uid", -1)),
                            spilled=req.spilled is not None)
            new_uids.append(req.uid)
        return new_uids

    # -- disaggregated serving: prefill -> decode handoff ----------------

    def export_handoff(self) -> List[Dict[str, Any]]:
        """Pop every session parked by :meth:`_handoff_sweep` (prefill
        + first token done, KV spilled to the tiers or folded to a
        re-prefill continuation) as portable blobs for
        :meth:`import_handoff` on a decode-role replica.  The wire
        format is exactly :meth:`export_parked`'s — the receiver admits
        the session through the normal spilled-request re-admission
        path, so greedy outputs stay bit-identical to a fused tick."""
        sessions: List[Dict[str, Any]] = []
        for r in self._handoff_ready:
            blob = self._session_blob(r)
            self.handoffs += 1
            self._stream_cursor.pop(r.uid, None)
            self.request_latency.on_handoff_out(r.uid)
            if trace.enabled:
                trace.event("request_handoff", cat="request", uid=r.uid,
                            spilled=blob["spill"] is not None,
                            generated=len(blob["generated"]))
            sessions.append(blob)
        self._handoff_ready = []
        return sessions

    def import_handoff(self, sessions: List[Dict[str, Any]],
                       export_t: Optional[float] = None) -> List[int]:
        """Decode-role half of the handoff: install the exported
        sessions via :meth:`import_parked` (fresh uids, payloads parked
        in the local tiers with the DONOR's digests — the restore
        verifies end-to-end) and stamp the export->import stall onto
        each request's latency record.  The ``handoff.import`` fault
        site fires per session before installation; a ``bitflip``
        directive corrupts the wire payload, which the digest-verified
        restore must catch (re-read, then quarantine + re-prefill)."""
        for s in sessions:
            sp = s.get("spill")
            payload = None if sp is None else sp.get("payload")
            d = faults.hook("handoff.import", uid=int(s.get("uid", -1)))
            if (d is not None and d[0] == "bitflip"
                    and payload is not None):
                buf = np.frombuffer(bytearray(payload["payload"]),
                                    np.uint8)
                faults.apply_bitflip(buf, d[1])
                payload["payload"] = buf.tobytes()
        new_uids = self.import_parked(sessions)
        if export_t is not None:
            stall = max(time.perf_counter() - float(export_t), 0.0)
            for uid in new_uids:
                self.request_latency.on_handoff_stall(uid, stall)
        return new_uids

    def knob_registry(self):
        """The engine's typed knob surface for the control plane
        (:class:`~deepspeed_tpu.control.knobs.KnobRegistry`).

        Online-safe knobs: ``harvest_interval`` is read fresh each
        pipelined step; ``async_depth`` re-sizes the live decode window
        in place (the substrate back-pressures on the next submit).
        ``decode_block_size`` and ``spec_k`` are baked into compiled
        block shapes, so they carry ``recompiles=True`` — reachable only
        by the offline sweep / profile seeding, never the online policy
        (the zero-new-compilations contract).  With tiering on, the
        tier store's prefetch toggle and IO-window depths ride along
        under ``kv.*``."""
        from deepspeed_tpu.control.knobs import Knob, KnobRegistry

        reg = KnobRegistry()

        def _set_harvest(v):
            self.harvest_interval = max(int(v), 1)

        def _set_depth(v):
            self.async_depth = max(int(v), 1)
            if self._dev is not None:
                self._dev["window"].depth = self.async_depth

        def _set_block(v):
            self.decode_block_size = max(int(v), 1)
            self._decode_block_cache.clear()

        reg.register(Knob(
            "engine.harvest_interval",
            lambda: self.harvest_interval, _set_harvest,
            lo=1, hi=16, step=1, kind="int",
            doc="pipelined decode blocks between token harvests"))
        reg.register(Knob(
            "engine.async_depth",
            lambda: self.async_depth, _set_depth,
            lo=1, hi=8, step=1, kind="int",
            doc="in-flight decode blocks in the pipeline window"))
        reg.register(Knob(
            "engine.decode_block_size",
            lambda: self.decode_block_size, _set_block,
            lo=1, hi=16, step=1, kind="int", recompiles=True,
            doc="device ticks per decode block (compiled shape)"))
        if self.spec_mode != "off":
            def _set_spec_k(v):
                self.spec_k = max(int(v), 1)
                self._spec_block_cache.clear()

            reg.register(Knob(
                "engine.spec_k", lambda: self.spec_k, _set_spec_k,
                lo=1, hi=8, step=1, kind="int", recompiles=True,
                doc="speculative draft length (compiled shape)"))
        if self.tiering is not None:
            t = self.tiering

            def _set_prefetch(v):
                t.prefetch_enabled = bool(v) and t.nvme_budget > 0

            def _set_wdepth(v):
                t._writes.depth = max(int(v), 1)

            def _set_rdepth(v):
                t._reads.depth = max(int(v), 1)

            reg.register(Knob(
                "kv.prefetch", lambda: t.prefetch_enabled,
                _set_prefetch, kind="bool",
                doc="NVMe read-ahead on tier restore"))
            reg.register(Knob(
                "kv.write_depth", lambda: t._writes.depth, _set_wdepth,
                lo=1, hi=8, step=1, kind="int",
                doc="bounded spill write-back window depth"))
            reg.register(Knob(
                "kv.read_depth", lambda: t._reads.depth, _set_rdepth,
                lo=1, hi=8, step=1, kind="int",
                doc="bounded restore read-ahead window depth"))

            def _set_lookahead(v):
                self.prefetch_lookahead = max(int(v), 1)

            def _set_window(v):
                self._tier_cfg.window_pages = max(int(v), 1)

            reg.register(Knob(
                "kv.prefetch_lookahead",
                lambda: self.prefetch_lookahead, _set_lookahead,
                lo=1, hi=64, step=1, kind="int",
                doc="queued spilled sequences prefetched ahead of reap"))
            reg.register(Knob(
                "kv.window_pages",
                lambda: int(self._tier_cfg.window_pages), _set_window,
                lo=1, hi=64, step=1, kind="int",
                doc="recent HBM-resident pages per partially-resident "
                    "sequence (long-context residency window)"))
        return reg

    def serving_stages(self) -> Dict[str, Any]:
        """Per-dispatch host-path breakdown + ``host_bound_fraction``
        (see :class:`~deepspeed_tpu.inference.common.HostStageStats`);
        with tiering on, the tier store's flat stats ride along as a
        ``kv_tiering`` sub-dict (``MonitorMaster`` flattens it to
        ``Serving/kv_tiering/<name>`` series)."""
        out = self.host_stats.serving_stages()
        if self.tiering is not None:
            out["kv_tiering"] = self.tiering.stats()
        if self._pfx is not None:
            st = self.host_stats
            pc = self._pfx.stats()
            pc.update(hit_requests=st.prefix_hits,
                      miss_requests=st.prefix_misses,
                      hit_tokens=st.prefix_hit_tokens,
                      cow_copies=st.prefix_cow_copies)
            out["prefix_cache"] = pc
        if self.kv_cache_dtype != "none":
            from deepspeed_tpu.inference.common import kv_quant_block
            from deepspeed_tpu.inference.paged import kv_dequant_path

            out["kv_quant"] = kv_quant_block(
                self.cache, self.kv_cache_dtype,
                kv_dequant_path(int(getattr(self.cfg, "head_dim", 0))),
                self.num_pages)
        # pool pressure: the scale-out router's least-pressure policy
        # reads this (waiting queue + page occupancy, both plain host
        # ints — no device sync)
        usable = max(self.num_pages - 1, 1)
        in_use = usable - self.allocator.free_pages
        out["pool"] = {
            "num_pages": self.num_pages,
            "pages_in_use": int(in_use),
            "waiting_requests": len(self.waiting),
            "pressure": round(in_use / usable
                              + len(self.waiting), 4)}
        if self.handoffs or self.handoff_folds or self._handoff_ready:
            out["handoff"] = {"exported": int(self.handoffs),
                              "folds": int(self.handoff_folds),
                              "pending": len(self._handoff_ready)}
        if self._pipe_timers.seconds or self._pipe_timers.counters:
            # the pipelined decode window's substrate counters
            # (submitted/completed blocks, submit_wait back-pressure)
            out["pipeline_window"] = self._pipe_timers.snapshot()
        if self._controller is not None:
            out["control"] = self._controller.stats()
        out["requests"] = self.request_latency.summary()
        if self.slo is not None:
            out["slo"] = self.slo.flat_summary()
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics
        if _metrics.enabled:
            # flat registry view (histogram p50/p99 + counters) — one
            # scalar level, the MonitorMaster flattening contract
            out["metrics"] = _metrics.scalar_summary()
            if self._tail_sampler is not None:
                out["trace_sampling"] = dict(self._tail_sampler.counters())
        return out

    def close(self) -> None:
        """Release tier-store resources (AIO handle, staging buffers,
        digest pool, spill files) and prefix-cache holds.  Idempotent;
        a no-op with tiering and the prefix cache off."""
        for r in itertools.chain(self.waiting, self._handoff_ready):
            if r.spilled is not None:
                for p in r.spilled.get("shared_pages", ()):
                    self.allocator.decref(int(p))
                r.spilled["shared_pages"] = []
        if self._pfx is not None:
            self._pfx.clear()
            self._pfx = None
        if self.tiering is not None:
            self.tiering.close()
            self.tiering = None

    # -- host<->device funnels (every transfer is counted/timed) ---------

    def _upload(self, x):
        """Host -> device metadata transfer.  The pipelined decode loop
        must NOT call this in steady state (metadata is device-resident;
        ``host_stats.meta_uploads`` asserts it in tests)."""
        with self.host_stats.stage("upload"):
            self.host_stats.meta_uploads += 1
            return jnp.asarray(x)

    def _fetch(self, tree):
        """Blocking device -> host fetch — the serving loop's only sync
        point (``host_stats.blocking_gets`` counts them)."""
        with self.host_stats.stage("device"):
            self.host_stats.blocking_gets += 1
            return jax.device_get(tree)

    def _block_ready(self, block):
        """The pipeline window's waiter: joining a decode block means
        waiting for its device tokens (run-ahead bound, NOT a fetch —
        harvest later folds all ready blocks in one blocking get)."""
        with self.host_stats.stage("device"):
            jax.block_until_ready(block[0])
        return block

    # -- compiled fused step ---------------------------------------------

    def _init_cache(self, model=None):
        """Zeroed page buffers for every layer (eval_shape, no params);
        ``model`` defaults to the target (the draft model gets its own,
        smaller, pool tree)."""
        model = model if model is not None else self.model
        dummy_meta = self._device_meta(
            np.zeros((self.max_seqs,), np.int32),
            np.full((self.max_seqs, self.pages_per_seq), -1, np.int32),
            np.zeros((self.max_seqs + 1,), np.int32),
            np.zeros((1,), np.int32),
            np.zeros((self.T,), np.int32))
        ids = jnp.zeros((1, self.T), jnp.int32)
        pos = jnp.zeros((1, self.T), jnp.int32)

        def _init():
            return model.init(jax.random.PRNGKey(0), ids,
                              positions=pos, ragged_meta=dummy_meta)

        shapes = jax.eval_shape(_init)
        assert "cache" in shapes

        def make(s):
            z = jnp.zeros(s.shape, s.dtype)
            sh = self._cache_sharding(s.shape)
            return jax.device_put(z, sh) if sh is not None else z

        return jax.tree_util.tree_map(make, shapes["cache"])

    @staticmethod
    def _device_meta(kv_lens, page_indices, cu_q_lens, num_seqs,
                     new_kv_dest):
        return {"kv_lens": jnp.asarray(kv_lens),
                "page_indices": jnp.asarray(page_indices),
                "cu_q_lens": jnp.asarray(cu_q_lens),
                "num_seqs": jnp.asarray(num_seqs),
                "new_kv_dest": jnp.asarray(new_kv_dest)}

    def _fused_step_fn(self):
        """ONE jitted program for every tick: fused decode + prefill
        chunk(s) forward, paged-KV update, and logits row selection."""
        if self._step_fn is not None:
            return self._step_fn
        from deepspeed_tpu.inference.common import (logits_of,
                                                    unroll_scan_params)

        model = self.model
        unroll = self._unroll_params
        wq = self._wq
        native = self._wq_native

        def run(params, cache, token_ids, positions, kv_lens, page_indices,
                cu_q_lens, num_seqs, new_kv_dest, sample_rows):
            if wq:
                from deepspeed_tpu.inference.quantization import \
                    dequantize_param_tree

                params = dequantize_param_tree(params, native_w8a8=native)
            if unroll:
                params = unroll_scan_params(params)
            meta = {"kv_lens": kv_lens, "page_indices": page_indices,
                    "cu_q_lens": cu_q_lens, "num_seqs": num_seqs,
                    "new_kv_dest": new_kv_dest}
            out, vars_ = model.apply(
                {"params": params, "cache": cache}, token_ids,
                positions=positions, mutable=["cache"], ragged_meta=meta)
            logits = logits_of(out)[0]                      # [T, V]
            sel = jnp.take(logits, sample_rows, axis=0)     # [max_seqs, V]
            return sel, vars_["cache"]

        # distinguishable XLA program name ("jit_ragged_fused_step") so
        # the profiler bridge can attribute device time per program
        run.__name__ = run.__qualname__ = "ragged_fused_step"
        self._step_fn = jax.jit(run, donate_argnums=(1,))
        return self._step_fn

    # -- the on-device decode block --------------------------------------

    def _decode_block_fn(self, sampled: bool):
        """K decode ticks per dispatch: ``lax.scan`` over fused
        [1, max_seqs] decode forwards with on-device sampling.  The host
        round trip the reference pays per generated token
        (``engine_v2.py:107`` put -> schedule -> logits) amortizes to 1/K.
        Two variants compile: pure-greedy (no sort) and per-seq sampled."""
        if sampled in self._decode_block_cache:
            return self._decode_block_cache[sampled]
        from deepspeed_tpu.inference.common import (logits_of,
                                                    unroll_scan_params)

        model = self.model
        unroll = self._unroll_params
        S = self.max_seqs
        K = self.decode_block_size
        page = self.page_size
        max_len = self.max_seq_len

        wq = self._wq
        native = self._wq_native
        sample_base = self._sample_base

        def run(params, cache, last_tok, pos, active, remaining,
                page_table, eos_ids, do_sample, temperature, top_k, top_p,
                seeds, rng):
            if wq:
                from deepspeed_tpu.inference.quantization import \
                    dequantize_param_tree

                params = dequantize_param_tree(params, native_w8a8=native)
            if unroll:
                params = unroll_scan_params(params)

            def tick(carry, _):
                cache, last_tok, pos, active, remaining, rng = carry
                dest_page = jnp.take_along_axis(
                    jnp.maximum(page_table, 0),
                    (pos // page)[:, None], axis=1)[:, 0]
                dest = jnp.where(active, dest_page * page + pos % page, 0)
                kv_lens = jnp.where(active, pos + 1, 1)
                meta = {"kv_lens": kv_lens,
                        "page_indices": page_table,
                        "cu_q_lens": jnp.arange(S + 1, dtype=jnp.int32),
                        "num_seqs": jnp.asarray([S], jnp.int32),
                        "new_kv_dest": dest}
                out, vars_ = model.apply(
                    {"params": params, "cache": cache}, last_tok[None],
                    positions=jnp.where(active, pos, 0)[None],
                    mutable=["cache"], ragged_meta=meta)
                logits = logits_of(out)[0]              # [S, V]
                rng, _ = jax.random.split(rng)
                # position-keyed per-row keys: the draw at cache position
                # `pos` is the same bits the fused tick's host sampler
                # would use, no matter how this block was scheduled
                sub = (position_keys(sample_base, seeds, pos)
                       if sampled else None)
                nxt = sample_logits_batched(
                    logits, sub, do_sample,
                    temperature, top_k, top_p)
                produced = active
                nxt = jnp.where(active, nxt, last_tok)
                hit_eos = active & (nxt == eos_ids)
                remaining = remaining - produced.astype(jnp.int32)
                pos = jnp.where(active, pos + 1, pos)
                active = (active & ~hit_eos & (remaining > 0) &
                          (pos + 1 < max_len))
                return (vars_["cache"], nxt, pos, active, remaining,
                        rng), (nxt, produced)

            carry, (toks, mask) = jax.lax.scan(
                tick, (cache, last_tok, pos, active, remaining, rng),
                length=K)
            cache, last_tok, pos, active, remaining, rng = carry
            # the full carry returns so the pipelined host path can keep
            # it device-resident across dispatches (no re-upload)
            return cache, last_tok, pos, active, remaining, toks, mask

        run.__name__ = run.__qualname__ = "ragged_decode_block"
        fn = jax.jit(run, donate_argnums=(1,))
        self._decode_block_cache[sampled] = fn
        return fn

    def _block_arrays(self, reqs: List[Request]):
        """Host numpy decode-block state for ``reqs`` (shared by the
        unpipelined per-block rebuild and the pipelined loop's one-time
        entry upload)."""
        S = self.max_seqs
        last_tok = np.asarray(self._last_tokens, np.int32)
        pos = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        remaining = np.zeros((S,), np.int32)
        eos_ids = np.full((S,), -1, np.int32)
        do_sample = np.zeros((S,), bool)
        temperature = np.ones((S,), np.float32)
        top_k = np.zeros((S,), np.int32)
        top_p = np.ones((S,), np.float32)
        seeds = np.zeros((S,), np.int32)   # per-row sampling seed (uid)
        for r in reqs:
            s = r.slot
            self._last_sched[s] = self._sched_seq
            pos[s] = min(r.length - 1, self.max_seq_len - 1)
            active[s] = True
            remaining[s] = r.max_new_tokens - len(r.generated)
            if r.eos_token_id is not None:
                eos_ids[s] = r.eos_token_id
            do_sample[s] = r.do_sample
            temperature[s] = r.temperature
            top_k[s] = r.top_k
            top_p[s] = r.top_p
            seeds[s] = r.uid
        return (last_tok, pos, active, remaining, eos_ids, do_sample,
                temperature, top_k, top_p, seeds)

    def _fold_block(self, reqs: List[Request], toks: np.ndarray,
                    mask: np.ndarray) -> int:
        """Fold one harvested [K, S] block into request state."""
        produced = 0
        for r in reqs:
            new = toks[mask[:, r.slot], r.slot]
            r.generated.extend(int(t) for t in new)
            produced += int(new.size)
            if new.size:
                # harvest-time token visibility: the honest host-side
                # TTFT/TPOT timestamp under the deferred-harvest pipeline
                self.request_latency.on_tokens(r.uid, len(r.generated))
        return produced

    # -- the speculative decode block (round-6 tentpole) ------------------

    def _spec_grow_want(self, plen: int, rem: int) -> int:
        """Token coverage one speculative block needs for a slot at
        cache length ``plen`` with ``rem`` budget left: each of the
        block's ticks WRITES k+1 KV rows ahead of the cursor regardless
        of how many tokens are accepted, so pages must cover the
        worst-case span (writes past ``max_seq_len`` route to the trash
        page and need no backing)."""
        K1 = self.spec_k + 1
        ticks = min(self.decode_block_size, max(int(rem), 1))
        return int(min(plen + ticks * K1, self.max_seq_len))

    def _hist_array(self, reqs: List[Request]) -> np.ndarray:
        """Host build of the device token-history buffer [S, max_len]:
        ``hist[s, i]`` = the sequence's token at cache position ``i``
        (prompt + generated — exact in the decode phase).  The n-gram
        drafter matches/continues against it; rebuilt from host state at
        every pipeline entry/harvest re-anchor."""
        hist = np.zeros((self.max_seqs, self.max_seq_len), np.int32)
        if self.spec_mode == "ngram":
            for r in reqs:
                seq = np.concatenate(
                    [r.prompt, np.asarray(r.generated, np.int32)])
                L = min(r.length, self.max_seq_len)
                hist[r.slot, :L] = seq[:L]
        return hist

    def _draft_prefill_fn(self):
        """ONE compiled chunked prefill program for the draft model's
        paged KV catch-up (fixed [1, prefill_chunk] shape, one sequence
        per call — entry-time cost, not steady-state)."""
        if self._draft_prefill is not None:
            return self._draft_prefill
        from deepspeed_tpu.inference.common import unroll_scan_params

        draft = self._draft
        dunroll = self._draft_unroll

        def run(dparams, dcache, ids, positions, kv_lens, page_row, cu,
                dest):
            if dunroll:
                dparams = unroll_scan_params(dparams)
            meta = {"kv_lens": kv_lens, "page_indices": page_row,
                    "cu_q_lens": cu,
                    "num_seqs": jnp.asarray([1], jnp.int32),
                    "new_kv_dest": dest}
            _, vars_ = draft.apply(
                {"params": dparams, "cache": dcache}, ids,
                positions=positions, mutable=["cache"], ragged_meta=meta)
            return vars_["cache"]

        run.__name__ = run.__qualname__ = "draft_prefill"
        self._draft_prefill = jax.jit(run, donate_argnums=(1,))
        return self._draft_prefill

    def _draft_catchup(self, reqs: List[Request]) -> None:
        """Bring the draft model's paged KV up to each slot's cursor
        (positions ``< length - 1``; the drafter itself processes the
        cursor token).  No-op for slots already covered — inside a
        decode phase the speculative block keeps draft KV in sync by
        construction, so this only runs at admission/re-admission."""
        if self.spec_mode != "draft":
            return
        st = self.host_stats
        C = self.prefill_chunk
        page = self.page_size
        with st.stage("draft"):
            fn = self._draft_prefill_fn()
            for r in reqs:
                target = r.length - 1
                lo = int(self._draft_len[r.slot])
                if lo >= target:
                    continue
                seq = np.concatenate(
                    [r.prompt, np.asarray(r.generated, np.int32)])
                while lo < target:
                    take = min(C, target - lo)
                    ids = np.zeros((C,), np.int32)
                    ids[:take] = seq[lo:lo + take]
                    pos = np.arange(lo, lo + C, dtype=np.int32)
                    posc = np.minimum(pos, self.max_seq_len - 1)
                    pg = self.page_table[
                        r.slot, np.minimum(pos // page,
                                           self.pages_per_seq - 1)]
                    dest = np.where(
                        np.arange(C) < take,
                        np.maximum(pg, 0) * page + pos % page,
                        0).astype(np.int32)
                    st.meta_uploads += 6
                    self._draft_cache = fn(
                        self._draft_params, self._draft_cache,
                        jnp.asarray(ids[None]), jnp.asarray(posc[None]),
                        jnp.asarray([lo + take], jnp.int32),
                        jnp.asarray(self.page_table[r.slot][None]),
                        jnp.asarray([0, take], jnp.int32),
                        jnp.asarray(dest))
                    lo += take
                self._draft_len[r.slot] = target

    def _spec_block_fn(self, sampled: bool):
        """The fused draft+verify+accept block: ``decode_block_size``
        speculative ticks per dispatch in a ``lax.scan``.  Each tick
        drafts k tokens (n-gram lookup over the history buffer, or a
        k-step draft-model sub-scan sharing the target's page table),
        scores all k+1 positions with the TARGET in one ragged chunk
        per slot, and accepts/rolls back device-side
        (:func:`~deepspeed_tpu.inference.sampling.speculative_verify`).
        Rollback is position rollback: rows written for rejected
        positions sit between the new cursor and the old write frontier,
        a span the NEXT tick's scatter fully overwrites before attention
        can reach it."""
        if sampled in self._spec_block_cache:
            return self._spec_block_cache[sampled]
        from deepspeed_tpu.inference.common import (logits_of,
                                                    unroll_scan_params)

        model = self.model
        unroll = self._unroll_params
        wq = self._wq
        native = self._wq_native
        mode = self.spec_mode
        draft = self._draft
        dunroll = self._draft_unroll
        S = self.max_seqs
        Tt = self.decode_block_size
        k = self.spec_k
        K1 = k + 1
        n = self.spec_ngram
        page = self.page_size
        pp = self.pages_per_seq
        max_len = self.max_seq_len

        def ngram_propose(hist, pos, last_tok):
            """Prompt-lookup drafting: most recent earlier occurrence of
            the trailing n-gram proposes the k tokens that followed it;
            no match proposes the last token repeated (any proposal is
            distribution-safe — bad drafts are simply rejected)."""
            L = max_len
            ar = jnp.arange(L, dtype=jnp.int32)
            histp = jnp.pad(hist, ((0, 0), (0, n + k)))
            tpos = jnp.clip(pos[:, None] - (n - 1) +
                            jnp.arange(n, dtype=jnp.int32)[None, :],
                            0, L - 1)
            tail = jnp.take_along_axis(hist, tpos, axis=1)      # [S, n]
            match = jnp.ones((S, L), bool)
            for t in range(n):
                match = match & (histp[:, t:t + L] == tail[:, t:t + 1])
            valid = ar[None, :] <= (pos[:, None] - n)
            score = jnp.where(match & valid, ar[None, :] + 1, 0)
            best = jnp.argmax(score, axis=1)       # most recent match
            found = jnp.any(match & valid, axis=1)
            cidx = best[:, None] + n + jnp.arange(k,
                                                  dtype=jnp.int32)[None, :]
            cand = jnp.take_along_axis(histp, cidx, axis=1)     # [S, k]
            fb = jnp.broadcast_to(last_tok[:, None], (S, k))
            return jnp.where(found[:, None], cand, fb).astype(jnp.int32)

        def run(params, dparams, cache, dcache, hist, last_tok, pos,
                active, remaining, page_table, eos_ids, do_sample,
                temperature, top_k, top_p, rng):
            if wq:
                from deepspeed_tpu.inference.quantization import \
                    dequantize_param_tree

                params = dequantize_param_tree(params, native_w8a8=native)
            if unroll:
                params = unroll_scan_params(params)
            if mode == "draft" and dunroll:
                dparams = unroll_scan_params(dparams)
            rows = jnp.arange(S)

            def draft_propose(dcache, last_tok, pos, active, key):
                def dstep(carry, key_j):
                    dcache, cur, dpos = carry
                    dvalid = active & (dpos < max_len)
                    dp = jnp.take_along_axis(
                        jnp.maximum(page_table, 0),
                        jnp.minimum(dpos // page, pp - 1)[:, None],
                        axis=1)[:, 0]
                    ddest = jnp.where(dvalid, dp * page + dpos % page, 0)
                    dmeta = {"kv_lens": jnp.where(active, dpos + 1, 1),
                             "page_indices": page_table,
                             "cu_q_lens": jnp.arange(S + 1,
                                                     dtype=jnp.int32),
                             "num_seqs": jnp.asarray([S], jnp.int32),
                             "new_kv_dest": ddest}
                    dout, dvars = draft.apply(
                        {"params": dparams, "cache": dcache}, cur[None],
                        positions=jnp.where(dvalid, dpos, 0)[None],
                        mutable=["cache"], ragged_meta=dmeta)
                    dlg = logits_of(dout)[0].astype(jnp.float32)
                    dgreedy = jnp.argmax(dlg, axis=-1).astype(jnp.int32)
                    if sampled:
                        flg = filter_logits_batched(dlg, temperature,
                                                    top_k, top_p)
                        qj = jax.nn.softmax(flg, axis=-1)
                        samp = jax.random.categorical(
                            key_j, flg, axis=-1).astype(jnp.int32)
                        nxt = jnp.where(do_sample, samp, dgreedy)
                        out = (nxt, qj)
                    else:
                        nxt = dgreedy
                        out = (nxt,)
                    return (dvars["cache"], nxt, dpos + 1), out

                keys = jax.random.split(key, k)
                (dcache, _, _), outs = jax.lax.scan(
                    dstep, (dcache, last_tok, pos), keys)
                d_toks = outs[0].T                              # [S, k]
                q_probs = (outs[1].transpose(1, 0, 2) if sampled
                           else None)
                return dcache, d_toks, q_probs

            def tick(carry, _):
                (cache, dcache, hist, last_tok, pos, active, remaining,
                 rng, prop, accd) = carry
                rng, key_d, key_v = jax.random.split(rng, 3)
                # ---- draft k proposals per slot ----
                if mode == "ngram":
                    # the cursor token joins the history before matching
                    hist = hist.at[rows, jnp.where(active, pos,
                                                   max_len)].set(
                        last_tok, mode="drop")
                    d_toks = ngram_propose(hist, pos, last_tok)
                    q_probs = None
                else:
                    dcache, d_toks, q_probs = draft_propose(
                        dcache, last_tok, pos, active, key_d)
                # ---- verify: one ragged chunk of k+1 rows per slot ----
                chunk = jnp.concatenate([last_tok[:, None], d_toks],
                                        axis=1)                 # [S, K1]
                cpos = (pos[:, None] +
                        jnp.arange(K1, dtype=jnp.int32)[None, :])
                valid = active[:, None] & (cpos < max_len)
                dest_page = jnp.take_along_axis(
                    jnp.maximum(page_table, 0),
                    jnp.minimum(cpos // page, pp - 1), axis=1)
                dest = jnp.where(valid,
                                 dest_page * page + cpos % page, 0)
                meta = {"kv_lens": jnp.where(active, pos + K1, 1),
                        "page_indices": page_table,
                        "cu_q_lens": jnp.arange(
                            S + 1, dtype=jnp.int32) * K1,
                        "num_seqs": jnp.asarray([S], jnp.int32),
                        "new_kv_dest": dest.reshape(-1)}
                out, vars_ = model.apply(
                    {"params": params, "cache": cache},
                    chunk.reshape(1, -1),
                    positions=jnp.where(valid, cpos, 0).reshape(1, -1),
                    mutable=["cache"], ragged_meta=meta)
                cache = vars_["cache"]
                logits = logits_of(out)[0].reshape(S, K1, -1)
                out_toks, acc = speculative_verify(
                    logits, d_toks, q_probs, key_v if sampled else None,
                    do_sample, temperature, top_k, top_p)
                # ---- emission clamp: budget, max_len, first eos ----
                emit = jnp.minimum(acc + 1, remaining)
                emit = jnp.minimum(emit, max_len - 1 - pos)
                eos_hit = out_toks == eos_ids[:, None]
                first_eos = jnp.argmax(eos_hit, axis=1)
                emit = jnp.where(jnp.any(eos_hit, axis=1),
                                 jnp.minimum(emit, first_eos + 1), emit)
                emit = jnp.where(active, jnp.clip(emit, 1, K1), 0)
                j = jnp.arange(K1, dtype=jnp.int32)[None, :]
                mask_out = active[:, None] & (j < emit[:, None])
                # ---- advance / roll back the carry ----
                new_last = jnp.take_along_axis(
                    out_toks, jnp.maximum(emit - 1, 0)[:, None],
                    axis=1)[:, 0]
                ended_eos = jnp.any(eos_hit & (j < emit[:, None]),
                                    axis=1)
                if mode == "ngram":
                    hidx = jnp.where(mask_out, pos[:, None] + 1 + j,
                                     max_len)
                    hist = hist.at[rows[:, None], hidx].set(
                        out_toks, mode="drop")
                last_tok = jnp.where(active, new_last, last_tok)
                pos = jnp.where(active, pos + emit, pos)
                remaining = remaining - emit
                prop = prop + jnp.sum(jnp.where(active, k, 0))
                accd = accd + jnp.sum(jnp.where(active, emit - 1, 0))
                active = (active & ~ended_eos & (remaining > 0) &
                          (pos + 1 < max_len))
                return (cache, dcache, hist, last_tok, pos, active,
                        remaining, rng, prop, accd), (out_toks, mask_out)

            carry0 = (cache, dcache, hist, last_tok, pos, active,
                      remaining, rng, jnp.int32(0), jnp.int32(0))
            carry, (toks, mask) = jax.lax.scan(tick, carry0, length=Tt)
            (cache, dcache, hist, last_tok, pos, active, remaining, _,
             prop, accd) = carry
            # tick-major emission order, [Tt*K1, S] — _fold_block's shape
            toks = toks.transpose(0, 2, 1).reshape(Tt * K1, S)
            mask = mask.transpose(0, 2, 1).reshape(Tt * K1, S)
            return (cache, dcache, hist, last_tok, pos, active,
                    remaining, toks, mask, prop, accd)

        run.__name__ = run.__qualname__ = "spec_verify_block"
        fn = jax.jit(run, donate_argnums=(2, 3, 4))
        self._spec_block_cache[sampled] = fn
        return fn

    def _step_spec_block(self, reqs: List[Request]) -> int:
        """One speculative block, unpipelined (fresh uploads + one
        blocking harvest per dispatch) — the ``pipeline=False`` spec
        reference path."""
        st = self.host_stats
        with st.stage("plan"):
            # the spec block keeps the global rng stream (its rejection-
            # sampling keys are not position-keyed), so seeds is unused
            (last_tok, pos, active, remaining, eos_ids, do_sample,
             temperature, top_k, top_p, _seeds) = self._block_arrays(reqs)
            sampled = bool(do_sample.any())
            hist = self._hist_array(reqs)
        self._draft_catchup(reqs)
        self.rng, sub = jax.random.split(self.rng)
        args = [self._upload(a) for a in
                (hist, last_tok, pos, active, remaining, self.page_table,
                 eos_ids, do_sample, temperature, top_k, top_p)]
        if trace.enabled:
            trace.event("decode_block", cat="request",
                        uids=[r.uid for r in reqs],
                        ticks=self.decode_block_size, spec=True)
        with st.stage("verify"):
            st.dispatches += 1
            st.spec_dispatches += 1
            (self.cache, self._draft_cache, _h, new_last, _p, _a, _r,
             toks, mask, prop, accd) = self._spec_block_fn(sampled)(
                self.params, self._draft_params, self.cache,
                self._draft_cache, *args, sub)
        st.ticks += self.decode_block_size
        toks, mask, new_last, prop, accd = self._fetch(
            (toks, mask, new_last, prop, accd))
        st.harvests += 1
        with st.stage("harvest"):
            self._last_tokens = np.array(new_last)
            produced = self._fold_block(reqs, np.asarray(toks),
                                        np.asarray(mask))
            st.spec_proposed += int(prop)
            st.spec_accepted += int(accd)
            st.spec_tokens += produced
            for r in reqs:
                self._maybe_finish(r)
                if not r.done:
                    self._draft_len[r.slot] = r.length - 1
            self._reap()
        return produced

    def _step_decode_block(self, reqs: List[Request]) -> int:
        """Run one on-device decode block and fold results back into the
        host request state (the ``pipeline=False`` path: fresh metadata
        upload + one blocking harvest per block)."""
        st = self.host_stats
        with st.stage("plan"):
            (last_tok, pos, active, remaining, eos_ids, do_sample,
             temperature, top_k, top_p, seeds) = self._block_arrays(reqs)
            sampled = bool(do_sample.any())
        self.rng, sub = jax.random.split(self.rng)
        args = [self._upload(a) for a in
                (last_tok, pos, active, remaining, self.page_table,
                 eos_ids, do_sample, temperature, top_k, top_p, seeds)]
        if trace.enabled:
            trace.event("decode_block", cat="request",
                        uids=[r.uid for r in reqs],
                        ticks=self.decode_block_size)
        with st.stage("dispatch"):
            st.dispatches += 1
            (cache, new_last, _pos, _active, _remaining, toks,
             mask) = self._decode_block_fn(sampled)(
                self.params, self.cache, *args, sub)
        self.cache = cache
        st.ticks += self.decode_block_size
        toks, mask, new_last = self._fetch((toks, mask, new_last))
        st.harvests += 1
        with st.stage("harvest"):
            toks = np.asarray(toks)                     # [K, S]
            mask = np.asarray(mask)                     # [K, S]
            # np.array: device_get returns a READ-ONLY view; the
            # SplitFuse tick assigns into _last_tokens per sampled token
            self._last_tokens = np.array(new_last)
            produced = self._fold_block(reqs, toks, mask)
            for r in reqs:
                self._maybe_finish(r)
            self._reap()
        return produced

    # -- the pipelined decode loop (serving host-path tentpole) ----------

    def _admittable(self) -> bool:
        """Would the unpipelined engine admit the queue head right now?
        Evaluated from EXACT global state (allocator + slots), so the
        pipelined loop reconciles at precisely the steps where
        ``pipeline=False`` would have admitted."""
        if not self.waiting or not any(s is None for s in self.slots):
            return False
        req = self.waiting[0]
        need = self._admit_need(req)
        fresh, entries = self._fresh_pages_needed(req, need, touch=False)
        avail = self.allocator.free_pages
        if self._pfx is not None:
            avail += self._pfx.reclaimable(
                exclude={e.key for e in entries})
        return fresh <= avail

    def _lc_resident_pages(self) -> int:
        """HBM pages a partially-resident sequence needs at steady
        state: sinks + the recent window + one not-yet-parked group in
        flight + the growth frontier."""
        t = self._tier_cfg
        return (int(t.sink_pages) + int(t.window_pages)
                + int(t.chunk_pages) + 1)

    def _admit_need(self, req: Request) -> int:
        """Token coverage ``_admit`` reserves for ``req`` — ONE formula
        shared with ``_admittable`` so the pipelined loop reconciles at
        precisely the steps where ``pipeline=False`` would admit."""
        ctx_len = req.ctx_len
        rem = max(req.max_new_tokens - len(req.generated), 1)
        if req.lc:
            # partial residency: reserve the resident WINDOW, not the
            # context — the parked middle lives in the spill tiers
            return min(ctx_len + rem,
                       self._lc_resident_pages() * self.page_size)
        if self.kv_reserve == "worst_case":
            # worst case INCLUDING re-prefilled output for evicted
            # continuations (their ctx carries earlier tokens)
            return ctx_len + req.max_new_tokens - len(req.generated)
        if req.spilled is not None and req.prefill_done >= ctx_len:
            # spilled decode-phase continuation: _admit allocates for
            # its full restored length, not just the prompt
            return req.length + min(self.decode_block_size, rem)
        # on-demand (reference can_schedule): context + the first
        # decode block; growth happens per block
        return ctx_len + min(self.decode_block_size, rem)

    def _fresh_pages_needed(self, req: Request, need: int,
                            touch: bool = False):
        """Free pages an admission of ``req`` would consume, after
        prefix-cache attaches (resident matched pages cost nothing; a
        FULL match costs one extra page for the COW re-prefill) and
        spill-hold re-attaches.  Returns ``(fresh, matched_entries)``.
        ``touch=False`` for probes — LRU order must not move until the
        admission actually happens."""
        total = self.allocator.pages_for(need)
        if req.lc:
            # long-context admissions skip the prefix cache (parked
            # columns would punch holes in a shared prefix run)
            return total, []
        if req.spilled is not None:
            return total - len(req.spilled.get("shared_pages", ())), []
        if self._pfx is None:
            return total, []
        ctx = req.ctx if req.ctx is not None else req.prompt
        entries = self._pfx.match(ctx, touch=touch)
        resident = sum(1 for e in entries if e.state == "resident")
        full = bool(entries) and (
            len(entries) * self.page_size == ctx.size)
        return total - resident + (1 if full else 0), entries

    def _pipeline_start(self, reqs: List[Request],
                        spec: bool = False) -> None:
        """Enter the pipelined decode loop: upload the decode-block
        carry and sampler metadata ONCE; subsequent blocks chain
        device-resident state (zero steady-state uploads).  With
        ``spec`` the speculative block runs instead of the plain one and
        the projection becomes per-slot BOUNDS (advance is 1..k+1 per
        tick, data-dependent): ``plen``/``rem`` hold the slow bound
        (1 token per tick — the guaranteed floor), ``plen_hi``/
        ``rem_lo`` the fast bound (k+1 per tick); growth covers the
        fast bound's write span and a harvest is forced as soon as the
        fast bound says a finish is POSSIBLE."""
        if spec:
            self._draft_catchup(reqs)
        with self.host_stats.stage("plan"):
            (last_tok, pos, active, remaining, eos_ids, do_sample,
             temperature, top_k, top_p, seeds) = self._block_arrays(reqs)
            S = self.max_seqs
            # exact host projection of per-slot cache length and token
            # budget — for eos-free sequences the device's active/
            # remaining carry is a deterministic function of these, so
            # the host can plan ahead without syncing; eos-bearing
            # sequences force a harvest every block (finish_possible)
            plen = np.zeros((S,), np.int64)
            rem = np.zeros((S,), np.int64)
            has_eos = np.zeros((S,), bool)
            for r in reqs:
                plen[r.slot] = r.length
                rem[r.slot] = remaining[r.slot]
                has_eos[r.slot] = r.eos_token_id is not None
        self._dev = {
            "reqs": list(reqs),
            "sampled": bool(do_sample.any()),
            "last_tok": self._upload(last_tok),
            "pos": self._upload(pos),
            "active": self._upload(active),
            "remaining": self._upload(remaining),
            "page_table": self._upload(self.page_table),
            "eos_ids": self._upload(eos_ids),
            "do_sample": self._upload(do_sample),
            "temperature": self._upload(temperature),
            "top_k": self._upload(top_k),
            "top_p": self._upload(top_p),
            "plen": plen, "rem": rem, "has_eos": has_eos,
            "spec": spec,
            # un-harvested decode blocks ride the shared bounded-window
            # substrate: the window bounds device run-ahead at
            # async_depth (joining = block_until_ready on the block's
            # tokens, bracketed as device wait), joined blocks park in
            # "ready" until the next harvest folds them.  Same substrate
            # instance shape as the NVMe moment stream and the router's
            # per-replica feed loop.
            "window": BoundedAsyncStage(
                waiter=self._block_ready, depth=self.async_depth,
                timers=self._pipe_timers, name="serving_pipeline"),
            "ready": [],                  # joined, un-harvested blocks
            "block_seq": 0,
        }
        if spec:
            self._dev["hist"] = self._upload(self._hist_array(reqs))
            self._dev["plen_hi"] = plen.copy()
            self._dev["rem_lo"] = rem.copy()
        else:
            self._dev["seeds"] = self._upload(seeds)

    def _pipeline_step(self) -> int:
        """One pipelined iteration: plan + dispatch block k+1 while the
        device still runs block k; harvest only when forced."""
        dv = self._dev
        st = self.host_stats
        K = self.decode_block_size
        spec = dv.get("spec", False)
        K1 = self.spec_k + 1
        # a queued request became admittable (put_request arrived, or a
        # reap freed capacity): reconcile so the normal path admits it
        # exactly when the unpipelined engine would
        if self._admittable():
            return self._pipeline_harvest(teardown=True)
        with st.stage("plan"):
            # grow pages to cover the next block — exact for the plain
            # block (the projection is exact for every sequence that can
            # reach this point un-harvested, see _pipeline_start); for a
            # speculative block the projection is the FAST bound, so
            # growth covers the worst-case k+1-wide write span
            slots_active = [r.slot for r in dv["reqs"]
                            if dv["rem"][r.slot] > 0 and
                            dv["plen"][r.slot] < self.max_seq_len]
            grow_ok = bool(slots_active)
            table_dirty = False
            for s in slots_active:
                self._last_sched[s] = self._sched_seq
                if spec:
                    want = self._spec_grow_want(int(dv["plen_hi"][s]),
                                                int(dv["rem"][s]))
                else:
                    want = int(min(dv["plen"][s] + min(K, dv["rem"][s]),
                                   self.max_seq_len))
                before = self.allocator.owned(s)
                if not self._ensure_pages(s, want):
                    grow_ok = False
                    break
                table_dirty |= self.allocator.owned(s) != before
        if not grow_ok:
            # out of pages (or nothing left to run): reconcile and hand
            # control back to the normal path (stall/evict semantics)
            return self._pipeline_harvest(teardown=True)
        if table_dirty:
            dv["page_table"] = self._upload(self.page_table)
        self.rng, sub = jax.random.split(self.rng)
        if trace.enabled:
            trace.event("decode_block", cat="request",
                        uids=[r.uid for r in dv["reqs"]],
                        ticks=self.decode_block_size, pipelined=True,
                        spec=bool(spec))
        if spec:
            with st.stage("verify"):
                st.dispatches += 1
                st.spec_dispatches += 1
                (self.cache, self._draft_cache, dv["hist"],
                 dv["last_tok"], dv["pos"], dv["active"],
                 dv["remaining"], toks, mask, prop,
                 accd) = self._spec_block_fn(dv["sampled"])(
                    self.params, self._draft_params, self.cache,
                    self._draft_cache, dv["hist"], dv["last_tok"],
                    dv["pos"], dv["active"], dv["remaining"],
                    dv["page_table"], dv["eos_ids"], dv["do_sample"],
                    dv["temperature"], dv["top_k"], dv["top_p"], sub)
            block = (toks, mask, prop, accd)
        else:
            with st.stage("dispatch"):
                st.dispatches += 1
                (self.cache, dv["last_tok"], dv["pos"], dv["active"],
                 dv["remaining"], toks, mask) = self._decode_block_fn(
                    dv["sampled"])(
                    self.params, self.cache, dv["last_tok"], dv["pos"],
                    dv["active"], dv["remaining"], dv["page_table"],
                    dv["eos_ids"], dv["do_sample"], dv["temperature"],
                    dv["top_k"], dv["top_p"], dv["seeds"], sub)
            block = (toks, mask)
        # track the block in the bounded window: past async_depth the
        # submit first joins the oldest un-joined block (waiting for
        # its tokens under the "device" bracket), bounding device
        # run-ahead exactly as the hand-rolled carry did
        dv["window"].submit(dv["block_seq"], block,
                            on_done=dv["ready"].append)
        dv["block_seq"] += 1
        st.ticks += K
        with st.stage("plan"):
            # advance the projection past this block and decide whether
            # the unpipelined engine could have reaped after it
            finish_possible = False
            for s in slots_active:
                if spec:
                    # bounds: per tick a slot advances 1..k+1 tokens
                    slow = int(max(0, min(K, dv["rem_lo"][s])))
                    fast = int(min(K * K1, max(dv["rem"][s], 0)))
                    dv["plen"][s] = min(dv["plen"][s] + slow,
                                        self.max_seq_len)
                    dv["plen_hi"][s] = min(dv["plen_hi"][s] + fast,
                                           self.max_seq_len)
                    dv["rem"][s] -= slow
                    dv["rem_lo"][s] -= K * K1
                    if (dv["has_eos"][s] or dv["rem_lo"][s] <= 0 or
                            dv["plen_hi"][s] >= self.max_seq_len):
                        finish_possible = True
                else:
                    prod = int(min(K, dv["rem"][s],
                                   self.max_seq_len - dv["plen"][s]))
                    dv["rem"][s] -= prod
                    dv["plen"][s] += prod
                    if (dv["has_eos"][s] or dv["rem"][s] <= 0 or
                            dv["plen"][s] >= self.max_seq_len):
                        finish_possible = True
            if self.tiering is not None and finish_possible:
                # the projection says a slot may free at the next
                # harvest: start NVMe->host reads for the spilled
                # sequences the FIFO queue would re-admit first, under
                # the decode block the device is still running
                self.tiering.prefetch(
                    [q.uid for q in
                     itertools.islice(self.waiting,
                                      self.prefetch_lookahead)
                     if q.spilled is not None])
        pending = dv["window"].in_flight + len(dv["ready"])
        if finish_possible or pending >= self.harvest_interval:
            return self._pipeline_harvest()
        return 0

    def _pipeline_harvest(self, teardown: bool = False) -> int:
        """Fold every pending block back into host request state (ONE
        blocking fetch), reap finishes, and either keep the
        device-resident carry (nothing changed) or tear down so the
        normal path re-plans."""
        dv = self._dev
        st = self.host_stats
        st.harvests += 1
        spec = dv.get("spec", False)
        # join every block still tracked by the bounded window (on_done
        # appends them to dv["ready"] in submit order), then fold the
        # whole run with ONE blocking fetch
        dv["window"].drain()
        blocks = dv["ready"]
        toks_l, mask_l, last_tok, extra = self._fetch((
            [p[0] for p in blocks],
            [p[1] for p in blocks], dv["last_tok"],
            [p[2:] for p in blocks] if spec else []))
        with st.stage("harvest"):
            # np.array: device_get returns READ-ONLY views
            self._last_tokens = np.array(last_tok)
            produced = 0
            for toks, mask in zip(toks_l, mask_l):
                produced += self._fold_block(
                    dv["reqs"], np.asarray(toks), np.asarray(mask))
            if spec:
                st.spec_proposed += sum(int(p) for p, _ in extra)
                st.spec_accepted += sum(int(a) for _, a in extra)
                st.spec_tokens += produced
            for r in dv["reqs"]:
                self._maybe_finish(r)
                if spec and not r.done:
                    self._draft_len[r.slot] = max(r.length - 1, 0)
            changed = any(r.done for r in dv["reqs"])
            self._reap()
            dv["ready"] = []
            if teardown or changed:
                self._dev = None
            else:
                # device carry stays authoritative; re-anchor the host
                # projection on the now-exact lengths (and the
                # speculative fast/slow bounds collapse to exact)
                for r in dv["reqs"]:
                    dv["plen"][r.slot] = r.length
                    dv["rem"][r.slot] = (r.max_new_tokens -
                                         len(r.generated))
                if spec:
                    np.copyto(dv["plen_hi"], dv["plen"])
                    np.copyto(dv["rem_lo"], dv["rem"])
        return produced

    # -- the scheduler tick ----------------------------------------------

    def step(self) -> int:
        """One engine iteration; returns the number of tokens produced
        (0 for pipelined iterations whose harvest is still deferred —
        the tokens are counted at the harvest step).

        All-decoding batches take the multi-tick on-device block (K
        tokens per sequence per host dispatch) — pipelined across
        dispatches when ``pipeline=True``; any prefilling sequence
        falls back to the fused SplitFuse tick."""
        self._sched_seq += 1
        if self._controller is not None:
            self._control_steps += 1
            if self._control_steps >= self._control_cfg.interval:
                self._control_steps = 0
                with self.host_stats.stage("plan"):
                    self._controller.tick()
        if self._dev is not None:
            return self._pipeline_step()
        st = self.host_stats
        with st.stage("plan"):
            # park finished handoff prefills BEFORE admission: a
            # handoff request never reaches the decode block / spec /
            # pipeline paths — it leaves its slot the step after its
            # first token lands
            self._handoff_sweep()
            self._admit()
            lc_live = [r for r in self.slots
                       if r is not None and not r.done and r.lc]
            live = [r for r in self.slots
                    if r is not None and not r.done and not r.lc]
            decoding_ready = (not lc_live and bool(live) and all(
                r.prefill_done >= r.ctx_len for r in live))
            # speculation first: its block writes a k+1-wide span per
            # tick, so it needs more page coverage than a plain block —
            # when the pool can't back it, degrade to the plain decode
            # block (greedy outputs are unchanged either way; the
            # decision is taken from EXACT state, so pipelined and
            # unpipelined runs degrade at the same steps)
            spec_block = (decoding_ready and self.spec_mode != "off" and
                          all(self._ensure_pages(
                              r.slot,
                              self._spec_grow_want(
                                  r.length, r.max_new_tokens -
                                  len(r.generated)))
                              for r in live))
            all_decoding = (
                not spec_block and decoding_ready and
                self.decode_block_size > 1 and
                all(self._ensure_pages(
                    r.slot,
                    r.length + min(self.decode_block_size,
                                   r.max_new_tokens - len(r.generated)))
                    for r in live))
        if spec_block:
            if self.pipeline:
                self._pipeline_start(live, spec=True)
                return self._pipeline_step()
            return self._step_spec_block(live)
        if all_decoding:
            if self.pipeline:
                self._pipeline_start(live)
                return self._pipeline_step()
            return self._step_decode_block(live)
        # partially-resident sequences tick through the chunked-scan
        # driver — never the fused batch, the decode block, or the
        # pipeline (decoding_ready is gated off while any is live, so
        # the pipeline cannot start and orphan them)
        lc_produced = 0
        if lc_live:
            if self._lc is None:
                from deepspeed_tpu.inference.v2.long_context import \
                    LongContextDriver
                self._lc = LongContextDriver(self)
            for r in lc_live:
                lc_produced += self._lc.tick(r)
        with st.stage("plan"):
            plan = self._plan_tick()
        if plan is None:
            self._reap()
            # every live sequence is page-stalled: evict the youngest as
            # a continuation so the rest (and the queue) can progress
            # (reference scheduler backpressure, engine_v2.py:184)
            stalled = getattr(self, "_stalled", [])
            if stalled and live:
                if len(live) == 1 and not self.waiting:
                    raise RuntimeError(
                        ("HBM KV tier" if self.tiering is not None
                         else "KV pool") +
                        " too small for the only live sequence "
                        f"(uid={live[0].uid}, needs "
                        f"{pages_for(live[0].length + 1, self.page_size)}"
                        f" pages of {self.allocator.num_pages - 1}) — "
                        "raise num_pages or lower max_new_tokens" +
                        (" (spill tiers hold parked sessions, not the "
                         "live working set)" if self.tiering is not None
                         else ""))
                if self.tiering is not None:
                    # park the coldest stalled sequence in the spill
                    # tiers (restore = page upload); destructive evict
                    # only when the tiers are full
                    victim = self._pick_victim(stalled)
                    if not self._spill(victim):
                        self._evict(victim)
                else:
                    self._evict(max(stalled, key=lambda r: r.uid))
            return lc_produced
        (token_ids, positions, kv_lens, page_indices, cu_q_lens, num_seqs,
         new_kv_dest, sample_rows, samplers) = plan
        args = [self._upload(a) for a in
                (token_ids[None], positions[None], kv_lens, page_indices,
                 cu_q_lens, num_seqs, new_kv_dest, sample_rows)]
        with st.stage("dispatch"):
            st.dispatches += 1
            sel_logits, self.cache = self._fused_step_fn()(
                self.params, self.cache, *args)
        st.ticks += 1
        produced = self._sample(sel_logits, samplers)
        self._reap()
        # a handoff prefill that just sampled its first token parks NOW
        # — same step — so the router's next export pulls it without an
        # extra tick of decode-side latency
        self._handoff_sweep()
        return produced + lc_produced

    def _admit(self) -> None:
        for i in range(self.max_seqs):
            if not self.waiting:
                break
            if self.slots[i] is not None:
                continue
            req = self.waiting[0]
            if req.ctx is None:
                req.ctx = req.prompt
            need = self._admit_need(req)
            if self.allocator.pages_for(need) > self.num_pages - 1:
                # defense in depth behind put_request's submit-time
                # check: an unschedulable head would deadlock the FIFO
                # queue forever — drop it and fail loudly.  The HBM
                # bound stays hard with tiering on: a sequence's WORKING
                # SET must be device-resident to decode; the tiers only
                # hold whole parked sessions.
                self.waiting.popleft()
                if self.tiering is not None:
                    self.tiering.drop(req.uid)
                    raise ValueError(
                        f"request uid={req.uid} needs "
                        f"{self.allocator.pages_for(need)} KV pages to "
                        f"admit ({need} tokens) but the HBM tier owns "
                        f"{self.num_pages - 1} usable pages — a working "
                        "set can only decode device-resident; raise "
                        "num_pages (spill tiers hold parked sessions, "
                        "not live ones)")
                raise ValueError(
                    f"request uid={req.uid} needs "
                    f"{self.allocator.pages_for(need)} KV pages to admit "
                    f"({need} tokens) but the engine owns "
                    f"{self.num_pages - 1} usable pages — it can never "
                    "be scheduled, even after full eviction")
            fresh, probe = self._fresh_pages_needed(req, need,
                                                    touch=False)
            avail = self.allocator.free_pages
            if self._pfx is not None:
                avail += self._pfx.reclaimable(
                    exclude={e.key for e in probe})
            if fresh > avail:
                break                      # FIFO: wait for pages to free
            self.waiting.popleft()
            req.slot = i
            if req.spilled is None:
                req.prefill_done = 0       # spilled reqs keep their rows
                req.pc_parent, req.pc_pages, req.pc_cached = ROOT_HASH, \
                    0, 0
            self.slots[i] = req
            self._draft_len[i] = 0
            self.page_table[i, :] = -1
            if not self._attach_and_allocate(req, need):
                # a tombstone revival failed mid-attach and the shrunken
                # match needs more fresh pages than the pool holds —
                # undo and retry this head next step (rare: the probe
                # assumed the revivals would land)
                self.allocator.free(i)
                self.page_table[i, :] = -1
                self.slots[i] = None
                req.slot = -1
                self.waiting.appendleft(req)
                break
            self.request_latency.on_admit(req.uid)
            if trace.enabled:
                trace.event("request_admit", cat="request", uid=req.uid,
                            slot=i, pages=self.allocator.owned(i),
                            cached_pages=req.pc_pages
                            if req.spilled is None else 0,
                            spilled=req.spilled is not None)
            if req.spilled is not None:
                self._restore(req)

    def _attach_and_allocate(self, req: Request, need: int) -> bool:
        """Build slot ``req.slot``'s page run for an admission covering
        ``need`` tokens: prefix-cache attaches (and tombstone revivals)
        first, spill-hold re-attaches for a restoring request, COW of
        the last page on a FULL prefix match, then fresh pages for the
        remainder.  False when revival failures shrank the match below
        what the pool can cover (caller undoes the admission)."""
        i = req.slot
        st = self.host_stats
        attached = 0
        full = False
        if req.spilled is not None:
            shared = [int(p) for p in req.spilled.get("shared_pages", ())]
            if shared:
                self.allocator.attach(i, shared)
                self.page_table[i, :len(shared)] = shared
                attached = len(shared)
        elif self._pfx is not None and not req.lc:
            with st.stage("prefix"):
                entries = self._pfx.match(req.ctx, touch=True)
                pages_att: List[int] = []
                parent = ROOT_HASH
                for e in entries:
                    if e.state == "spilled" and not self._pfx_revive(e):
                        break
                    pages_att.append(int(e.page))
                    parent = e.key
                attached = len(pages_att)
                if attached:
                    self.allocator.attach(i, pages_att)
                    self.page_table[i, :attached] = pages_att
                    full = attached * self.page_size == req.ctx_len
                    # the matched pages are already prefilled: schedule
                    # only the suffix.  A FULL match still re-prefills
                    # its LAST token through the fused program (after a
                    # COW below) so the first sampled token's logits
                    # come from the same compiled program as a cache-off
                    # run — the bit-parity contract
                    req.prefill_done = attached * self.page_size - (
                        1 if full else 0)
                    req.pc_parent = parent
                    req.pc_pages = attached
                    req.pc_cached = req.prefill_done
                    st.prefix_hits += 1
                    st.prefix_hit_pages += attached
                    st.prefix_hit_tokens += req.prefill_done
                else:
                    st.prefix_misses += 1
        grow_n = self.allocator.pages_for(need) - attached
        want_free = grow_n + (1 if full else 0)
        self._reclaim_for(want_free)
        if want_free > self.allocator.free_pages:
            return False
        if full:
            # the write frontier (position ctx_len - 1) lands in the
            # last matched page: make it private before the re-prefill
            old, new = self.allocator.cow(i, attached - 1)
            if new != old:
                self._cow_copy(old, new)
                self.page_table[i, attached - 1] = new
        if grow_n > 0:
            pages = self.allocator.grow(i, grow_n)
            self.page_table[i, attached:attached + grow_n] = pages
        return True

    def _ensure_pages(self, slot: int, upto_tokens: int) -> bool:
        """Grow ``slot``'s page run to cover ``upto_tokens`` cache
        positions; False when the pool can't (scheduler backpressure —
        the sequence sits this tick out, or gets evicted)."""
        upto_tokens = min(upto_tokens, self.max_seq_len)
        need = pages_for(upto_tokens, self.page_size)
        have = self.allocator.owned(slot)
        if need <= have:
            return True
        self._reclaim_for(need - have)
        if need - have > self.allocator.free_pages:
            return False
        pages = self.allocator.grow(slot, need - have)
        self.page_table[slot, have:have + len(pages)] = pages
        return True

    def _reclaim_for(self, n_pages: int) -> None:
        """Ask the prefix index to give back cold single-reference pages
        when the free list can't cover ``n_pages``.  Safe against any
        page a live slot uses: those hold a slot reference on top of the
        index's, so the index never reclaims them."""
        if self._pfx is None:
            return
        short = n_pages - self.allocator.free_pages
        if short > 0:
            self._pfx.reclaim(short)

    def _handoff_sweep(self) -> None:
        """Park every handoff-marked sequence whose prefill AND first
        token are done: spill its KV to the tiers (the decode replica's
        restore is then bit-identical to never having left) or — when
        the pages can't travel (tiering off, tiers full) — fold it to a
        re-prefill continuation, the degraded leg.  Either way the
        sequence leaves its slot and waits in ``_handoff_ready`` for
        the router's ``export_handoff`` pull."""
        for r in list(self.slots):
            if (r is None or not r.handoff or r.done or r.lc
                    or r.prefill_done < r.ctx_len or not r.generated):
                continue
            if self._spill(r):
                self.waiting.remove(r)     # _spill parked it there
            else:
                # planned handoff, not pool exhaustion — fold inline
                # instead of via _evict (no eviction counter/log)
                self.allocator.free(r.slot)
                self.page_table[r.slot, :] = -1
                self.slots[r.slot] = None
                self._draft_len[r.slot] = 0
                r.ctx = np.concatenate(
                    [r.prompt, np.asarray(r.generated, np.int32)])
                r.prefill_done = 0
                r.pc_parent, r.pc_pages, r.pc_cached = ROOT_HASH, 0, 0
                r.slot = -1
                self.handoff_folds += 1
            self._handoff_ready.append(r)
            if trace.enabled:
                trace.event("request_handoff_ready", cat="request",
                            uid=r.uid, spilled=r.spilled is not None)

    def _evict(self, r) -> None:
        """Requeue ``r`` as a CONTINUATION: its pages return to the
        pool, and on re-admission it re-prefills prompt + its own
        generated tokens (greedy continuations are exact; sampled ones
        resume from the same sampled prefix)."""
        from deepspeed_tpu.utils.logging import logger

        self.allocator.free(r.slot)
        self.page_table[r.slot, :] = -1
        self.slots[r.slot] = None
        self._draft_len[r.slot] = 0
        r.ctx = np.concatenate(
            [r.prompt, np.asarray(r.generated, np.int32)])
        r.prefill_done = 0
        r.pc_parent, r.pc_pages, r.pc_cached = ROOT_HASH, 0, 0
        r.slot = -1
        self.waiting.append(r)             # back of the queue: the freed
        self.evictions += 1                # pages go to older work first
        if trace.enabled:
            trace.event("request_evict", cat="request", uid=r.uid,
                        ctx_tokens=int(r.ctx.size))
        logger.info(f"ragged engine: evicted uid={r.uid} "
                    f"({r.ctx.size} ctx tokens) — KV pool exhausted; "
                    "requeued as continuation")

    # -- tiered KV spill/restore (HBM <-> host RAM <-> NVMe) -------------

    def _tier_jits(self):
        """The two fixed-shape page-movement programs (compiled once,
        first spill/restore — the zero-new-compilation guard covers the
        steady state after that):

        - gather: ``[pages_per_seq]`` page rows out of every cache leaf
          (indices padded with the trash page 0 — always allocated).
        - scatter: the same rows back in, donating the cache buffers;
          pad indices point one past the pool and ``mode='drop'``
          discards them, so a partial restore writes exactly its live
          rows."""
        if self._tier_gather is None:
            def gather(cache, idx):
                return jax.tree_util.tree_map(
                    lambda l: jnp.take(l, idx, axis=0), cache)

            def scatter(cache, idx, rows):
                return jax.tree_util.tree_map(
                    lambda l, r: l.at[idx].set(r, mode="drop"),
                    cache, rows)

            self._tier_gather = jax.jit(gather)
            self._tier_scatter = jax.jit(scatter, donate_argnums=(0,))
        return self._tier_gather, self._tier_scatter

    def _live_tokens(self, r) -> int:
        """Cache rows that hold real KV for ``r`` RIGHT NOW.  Decode
        phase: the last sampled token's row is written by the NEXT tick
        (at position length-1), so ``length - 1`` rows are live.
        Prefill phase: exactly the prefilled prefix."""
        if r.prefill_done >= r.ctx_len:
            return r.length - 1
        return r.prefill_done

    def _spill(self, r) -> bool:
        """Park ``r`` in the spill tiers instead of destroying its KV:
        gather its live page rows, hand them (device_get) to the tier
        store, and requeue it as a RESTORABLE continuation.  Returns
        False when the tiers can't take it (caller falls back to
        ``_evict``'s re-prefill).  Restore is bit-identical to never
        having spilled: the exact cache rows come back, ``prefill_done``
        and the pending last token are preserved."""
        live = self._live_tokens(r)
        n_live = pages_for(live, self.page_size) if live > 0 else 0
        if n_live == 0 or self.tiering is None:
            return False
        # shared-prefix pages (refcount > 1: the prefix index or another
        # sequence also holds them) are maximally hot — they never leave
        # HBM.  Take a spill-hold (+1 ref) on the maximal shared PREFIX
        # of the live run and spill only the private suffix; no owner
        # ever writes a shared page (COW precedes any write), so the
        # rows stay valid for re-attach at restore
        j = 0
        while j < n_live and self.allocator.refcount(
                int(self.page_table[r.slot, j])) > 1:
            j += 1
        n_priv = n_live - j
        if n_priv > 0 and not self.tiering.can_spill(n_priv):
            return False
        shared = [int(p) for p in self.page_table[r.slot, :j]]
        st = self.host_stats
        with st.stage("spill"):
            if n_priv > 0:
                gather, _ = self._tier_jits()
                idx = np.zeros((self.pages_per_seq,),
                               np.int32)               # pad: trash
                idx[:n_priv] = self.page_table[r.slot, j:n_live]
                rows = jax.device_get(gather(self.cache,
                                             jnp.asarray(idx)))
                try:
                    self.tiering.spill(
                        r.uid,
                        [np.asarray(leaf[:n_priv]) for leaf in
                         jax.tree_util.tree_leaves(rows)],
                        n_priv)
                except RuntimeError:
                    return False           # tiers full: caller evicts
            r.spilled = {"last_tok": int(self._last_tokens[r.slot]),
                         "n_pages": n_priv, "live_tokens": live,
                         "shared_pages": shared}
            for p in shared:
                self.allocator.incref(p)   # spill-hold survives free()
        from deepspeed_tpu.utils.logging import logger

        self.allocator.free(r.slot)
        self.page_table[r.slot, :] = -1
        self.slots[r.slot] = None
        self._draft_len[r.slot] = 0
        r.slot = -1
        self.waiting.append(r)             # back of the queue, like evict
        self.spills += 1
        self.request_latency.on_spill(r.uid)
        if trace.enabled:
            trace.event("request_spill", cat="request", uid=r.uid,
                        pages=int(n_live), live_tokens=int(live))
        logger.info(f"ragged engine: spilled uid={r.uid} ({n_live} pages,"
                    f" {live} live tokens) to the KV tiers — restore is "
                    "a page upload, not a re-prefill")
        return True

    def _restore(self, req) -> None:
        """Upload ``req``'s spilled page rows into its freshly allocated
        pages (slot already assigned by ``_admit``).  On unrecoverable
        corruption (:class:`KVRestoreError` — the store already
        quarantined the payload) the request falls back to a plain
        re-prefill continuation at the FRONT of the queue, loudly."""
        from deepspeed_tpu.inference.kv_tiering import KVRestoreError
        from deepspeed_tpu.utils.logging import logger

        st = self.host_stats
        info = req.spilled
        n = info["n_pages"]                 # private pages in the tiers
        shared = info.get("shared_pages", [])
        jn = len(shared)                    # shared prefix re-attached by
        t_restore0 = time.perf_counter()    # _attach_and_allocate
        try:
            if n > 0:
                with st.stage("restore"):
                    arrs = self.tiering.restore(req.uid)
                    _, scatter = self._tier_jits()
                    # pad indices past the pool: mode='drop' drops them
                    idx = np.full((self.pages_per_seq,), self.num_pages,
                                  np.int32)
                    idx[:n] = self.page_table[req.slot, jn:jn + n]
                    leaves = []
                    for a in arrs:
                        full = np.zeros(
                            (self.pages_per_seq,) + a.shape[1:], a.dtype)
                        full[:n] = a
                        leaves.append(jnp.asarray(full))
                    rows = jax.tree_util.tree_unflatten(
                        self._cache_treedef, leaves)
                    self.cache = scatter(self.cache, jnp.asarray(idx),
                                         rows)
            self._last_tokens[req.slot] = info["last_tok"]
            req.spilled = None
            # release the spill-holds: the slot attach owns its refs now
            for p in shared:
                self.allocator.decref(p)
            self.restores += 1
            self.request_latency.on_restore_stall(
                req.uid, time.perf_counter() - t_restore0)
            if trace.enabled:
                trace.event("request_restore", cat="request",
                            uid=req.uid, pages=int(n),
                            shared_pages=int(jn))
        except KVRestoreError as e:
            self.allocator.free(req.slot)
            self.page_table[req.slot, :] = -1
            self.slots[req.slot] = None
            self._draft_len[req.slot] = 0
            for p in shared:
                self.allocator.decref(p)
            req.ctx = np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)])
            req.prefill_done = 0
            req.pc_parent, req.pc_pages, req.pc_cached = ROOT_HASH, 0, 0
            req.spilled = None
            req.slot = -1
            self.waiting.appendleft(req)   # front: it already waited
            self.request_latency.on_restore_stall(
                req.uid, time.perf_counter() - t_restore0)
            self.request_latency.on_error(req.uid)
            if trace.enabled:
                trace.event("request_restore_failed", cat="request",
                            uid=req.uid, page=int(e.page))
            logger.error(
                f"ragged engine: restore of uid={req.uid} failed "
                f"verification (page {e.page}; payload quarantined) — "
                "re-prefilling the session from its own tokens")

    def _pfx_demote(self, e) -> bool:
        """Index-LRU hook: move a single-reference prefix page's KV into
        the tiered store under the entry's PREFIX-HASH key (not a uid —
        one restore serves every future waiter).  Returns False when the
        tiers can't take it, in which case the index drops the entry."""
        if self.tiering is None or not self.tiering.can_spill(1):
            return False
        st = self.host_stats
        with st.stage("spill"):
            gather, _ = self._tier_jits()
            idx = np.zeros((self.pages_per_seq,), np.int32)  # pad: trash
            idx[0] = e.page
            rows = jax.device_get(gather(self.cache, jnp.asarray(idx)))
            try:
                self.tiering.spill(
                    PrefixCacheIndex.tier_key(e.key),
                    [np.asarray(leaf[:1]) for leaf in
                     jax.tree_util.tree_leaves(rows)],
                    1)
            except RuntimeError:
                return False
        if trace.enabled:
            trace.event("prefix_demote", cat="request",
                        key=PrefixCacheIndex.tier_key(e.key))
        return True

    def _pfx_revive(self, e) -> bool:
        """Bring a demoted prefix page back into a fresh pool page so an
        admission can attach it.  On failure the tombstone is dropped —
        the requester falls back to computing that page itself."""
        from deepspeed_tpu.inference.kv_tiering import KVRestoreError

        if self.tiering is None:
            self._pfx._drop(e)
            return False
        if self.allocator.free_pages < 1:
            return False
        st = self.host_stats
        page = self.allocator.take_page()
        try:
            with st.stage("restore"):
                arrs = self.tiering.restore(
                    PrefixCacheIndex.tier_key(e.key))
                _, scatter = self._tier_jits()
                idx = np.full((self.pages_per_seq,), self.num_pages,
                              np.int32)
                idx[0] = page
                leaves = []
                for a in arrs:
                    full = np.zeros((self.pages_per_seq,) + a.shape[1:],
                                    a.dtype)
                    full[:1] = a
                    leaves.append(jnp.asarray(full))
                rows = jax.tree_util.tree_unflatten(self._cache_treedef,
                                                    leaves)
                self.cache = scatter(self.cache, jnp.asarray(idx), rows)
        except KVRestoreError:
            self.allocator.decref(page)
            self._pfx._drop(e)             # payload quarantined: forget
            return False
        self._pfx.revive(e, page)
        if trace.enabled:
            trace.event("prefix_revive", cat="request",
                        key=PrefixCacheIndex.tier_key(e.key),
                        page=int(page))
        return True

    def _cow_copy(self, src: int, dst: int) -> None:
        """Fixed-shape device copy of one page row across every cache
        leaf — the copy half of copy-on-write.  ``src``/``dst`` are
        traced int32 operands, so every COW reuses one compiled
        program."""
        st = self.host_stats
        if self._cow_jit is None:
            self._cow_jit = jax.jit(
                lambda cache, s, d: jax.tree_util.tree_map(
                    lambda l: l.at[d].set(l[s]), cache),
                donate_argnums=(0,))
        with st.stage("prefix"):
            self.cache = self._cow_jit(self.cache, jnp.int32(src),
                                       jnp.int32(dst))
        st.prefix_cow_copies += 1
        if trace.enabled:
            trace.event("prefix_cow", cat="request", src=int(src),
                        dst=int(dst))

    def audit_kv_sharing(self) -> Dict[str, int]:
        """Refcount-conservation audit: every physical page's refcount
        must equal the number of holders that can reach it — slot
        page-table rows, resident prefix-index entries, and spill-holds
        on parked requests' shared prefixes.  Spilled payloads (tiers)
        hold no pool pages by construction.  Delegates the per-page
        equality to :meth:`PageAllocator.audit`."""
        external: Dict[int, int] = {}
        if self._pfx is not None:
            for e in self._pfx._entries.values():
                if e.state == "resident":
                    external[e.page] = external.get(e.page, 0) + 1
        for r in itertools.chain(self.waiting, self._handoff_ready):
            if r.spilled is not None:
                for p in r.spilled.get("shared_pages", ()):
                    external[p] = external.get(p, 0) + 1
        for s, r in enumerate(self.slots):
            if r is None:
                continue
            row = [int(p) for p in self.page_table[s] if p >= 0]
            owned = self.allocator.owned_pages(s)
            assert row == owned, (
                f"slot {s}: page-table row {row} != allocator "
                f"ownership {owned}")
        return self.allocator.audit(external=external)

    def _pick_victim(self, stalled):
        """Coldest page-stalled sequence: least-recently scheduled
        (tie: youngest) — it has waited longest for pages and will wait
        longest for them, so parking it frees the most useful HBM."""
        return min(stalled,
                   key=lambda r: (self._last_sched[r.slot], -r.uid))

    def _flat_dest(self, slot: int, pos: int) -> int:
        page = self.page_table[slot, pos // self.page_size]
        assert page > 0, "write into unallocated page"
        return int(page) * self.page_size + pos % self.page_size

    def _plan_tick(self):
        """Host-side SplitFuse plan: one decode token per ready sequence
        plus prompt chunks for prefilling sequences, all in ONE batch."""
        self._stalled = []
        decode_rs = []
        for r in self.slots:
            if r is None or r.done or r.lc or r.prefill_done < r.ctx_len:
                continue
            # the tick writes the last generated token at position
            # length-1, so pages must cover `length` tokens
            if self._ensure_pages(r.slot, r.length):
                decode_rs.append(r)
            else:
                self._stalled.append(r)    # out of pages: sit this tick out
        prefill_rs = sorted(
            (r for r in self.slots
             if r is not None and not r.lc
             and r.prefill_done < r.ctx_len),
            key=lambda r: r.uid)
        if not decode_rs and not prefill_rs:
            return None

        token_ids = np.zeros((self.T,), np.int32)
        positions = np.zeros((self.T,), np.int32)
        new_kv_dest = np.full((self.T,), 0, np.int32)   # trash page row 0
        kv_lens = np.zeros((self.max_seqs,), np.int32)
        # metadata rows are indexed by PACKED sequence number j, not slot:
        # pack each active slot's page-table row as it is assigned a j
        page_indices = np.full((self.max_seqs, self.pages_per_seq), -1,
                               np.int32)
        cu_q_lens = np.zeros((self.max_seqs + 1,), np.int32)
        sample_rows = np.zeros((self.max_seqs,), np.int32)
        samplers: List[Tuple[Request, int, bool]] = []  # (req, seq_j, sample?)

        budget = self.T - len(decode_rs)
        takes: Dict[int, int] = {}
        for r in prefill_rs:
            take = min(budget, r.ctx_len - r.prefill_done)
            if take <= 0:
                continue                   # batch-budget-limited, not stalled
            if not self._ensure_pages(r.slot, r.prefill_done + take):
                # partial growth: cover what the pool allows this tick
                # (cold prefix-index pages count — _ensure_pages
                # reclaims them on demand)
                coverable = (self.allocator.owned(r.slot) +
                             self.allocator.free_pages +
                             (self._pfx.reclaimable()
                              if self._pfx is not None else 0)
                             ) * self.page_size
                take = min(take, coverable - r.prefill_done)
                if take <= 0:
                    self._stalled.append(r)     # page-limited
                    continue
                self._ensure_pages(r.slot, r.prefill_done + take)
            takes[r.uid] = take
            budget -= take

        # pack sequences in slot order (any fixed order works; the kernel
        # sees sequences via cu_q_lens row j)
        stalled_uids = {r.uid for r in self._stalled}
        t = 0
        j = 0
        for r in [s for s in self.slots if s is not None]:
            if r.done or r.lc or r.uid in stalled_uids:
                continue
            self._last_sched[r.slot] = self._sched_seq
            if r.prefill_done >= r.ctx_len:                 # decode: 1 tok
                p = min(r.length - 1, self.max_seq_len - 1)
                token_ids[t] = self._last_tokens[r.slot]
                positions[t] = p
                new_kv_dest[t] = self._flat_dest(r.slot, p)
                page_indices[j] = self.page_table[r.slot]
                kv_lens[j] = p + 1
                cu_q_lens[j + 1] = cu_q_lens[j] + 1
                sample_rows[j] = t
                samplers.append((r, j, True))
                t += 1
                j += 1
            else:                                           # prefill chunk
                take = takes.get(r.uid, 0)
                if take <= 0:
                    continue
                lo = r.prefill_done
                token_ids[t:t + take] = r.ctx[lo:lo + take]
                pos = np.arange(lo, lo + take)
                positions[t:t + take] = pos
                pg = self.page_table[r.slot, pos // self.page_size]
                assert (pg > 0).all(), "write into unallocated page"
                new_kv_dest[t:t + take] = (pg * self.page_size +
                                           pos % self.page_size)
                r.prefill_done += take
                if self._pfx is not None:
                    # publish every freshly completed full page to the
                    # prefix index (chain-hash it onto the request's
                    # registered prefix); identical in both pipeline
                    # modes — registration keys off prefill progress,
                    # not dispatch timing
                    page = self.page_size
                    while (r.pc_pages + 1) * page <= r.prefill_done:
                        k = r.pc_pages
                        r.pc_parent = self._pfx.register(
                            r.pc_parent, r.ctx[k * page:(k + 1) * page],
                            int(self.page_table[r.slot, k]))
                        r.pc_pages += 1
                if trace.enabled:
                    trace.event("prefill_chunk", cat="request",
                                uid=r.uid, take=int(take),
                                prefill_done=int(r.prefill_done),
                                ctx_len=int(r.ctx_len))
                page_indices[j] = self.page_table[r.slot]
                kv_lens[j] = r.prefill_done
                cu_q_lens[j + 1] = cu_q_lens[j] + take
                finishes = r.prefill_done >= r.ctx_len
                if finishes:
                    self.request_latency.on_prefill_done(
                        r.uid, r.ctx_len - r.pc_cached, r.pc_cached)
                sample_rows[j] = t + take - 1
                samplers.append((r, j, finishes))
                t += take
                j += 1
        cu_q_lens[j + 1:] = cu_q_lens[j]
        if j == 0:
            return None
        return (token_ids, positions, kv_lens, page_indices, cu_q_lens,
                np.asarray([j], np.int32), new_kv_dest, sample_rows,
                samplers)

    def _sample(self, sel_logits, samplers) -> int:
        """One host sync per tick; one sampling call per distinct config."""
        produced = 0
        groups: Dict[Tuple, List[Tuple[Request, int]]] = {}
        for r, seq_j, wants in samplers:
            if not wants:
                continue
            key = (r.do_sample, r.temperature, r.top_k, r.top_p)
            groups.setdefault(key, []).append((r, seq_j))
        for (do_sample, temp, top_k, top_p), pairs in groups.items():
            rows = np.asarray([j for _, j in pairs])
            sub = None
            if do_sample:
                # (uid, position)-keyed streams: the draw for token n of
                # request u is the same whatever else is co-batched, so
                # seeded sampling is reproducible under prefix-cache
                # admission reordering (same convention as the decode
                # block's per-tick keys)
                sub = position_keys(
                    self._sample_base,
                    jnp.asarray([r.uid for r, _ in pairs], jnp.int32),
                    jnp.asarray([r.length - 1 for r, _ in pairs],
                                jnp.int32))
            dev_toks = sample_logits(
                sel_logits[rows], sub, do_sample=do_sample,
                temperature=temp, top_k=top_k, top_p=top_p)
            toks = np.asarray(self._fetch(dev_toks))
            with self.host_stats.stage("harvest"):
                for (r, _), tok in zip(pairs, toks):
                    r.generated.append(int(tok))
                    self._last_tokens[r.slot] = int(tok)
                    produced += 1
                    self.request_latency.on_tokens(r.uid,
                                                   len(r.generated))
                    self._maybe_finish(r)
        return produced

    def _maybe_finish(self, req: Request) -> None:
        if (len(req.generated) >= req.max_new_tokens or
                (req.eos_token_id is not None and req.generated and
                 req.generated[-1] == req.eos_token_id) or
                req.length >= self.max_seq_len):
            req.done = True

    def _reap(self) -> None:
        for i, r in enumerate(self.slots):
            if r is not None and r.done:
                if r.lc and self.tiering is not None:
                    # drop the parked middle groups with the session
                    for g in range(r.lc_parked):
                        self.tiering.drop(f"mid-{r.uid}-{g}")
                if (self._pfx is not None and not r.lc
                        and self._pfx_cfg.include_generated):
                    # opt-in: publish full pages of generated tokens
                    # before the refs drop.  Decode pages come from a
                    # different compiled program than fused prefill, so
                    # the bit-parity contract is waived for hits on them
                    # (documented on the config knob).
                    seq = np.concatenate(
                        [r.ctx, np.asarray(r.generated, np.int32)[
                            r.ctx_len - r.prompt.size:]])
                    written = r.length - 1   # last token never written
                    page = self.page_size
                    while (r.pc_pages + 1) * page <= written:
                        k = r.pc_pages
                        r.pc_parent = self._pfx.register(
                            r.pc_parent, seq[k * page:(k + 1) * page],
                            int(self.page_table[i, k]))
                        r.pc_pages += 1
                self.finished.append(r)
                self.slots[i] = None
                self.allocator.free(i)
                self.page_table[i, :] = -1
                self._draft_len[i] = 0
                rec = self.request_latency.on_finish(r.uid)
                if trace.enabled:
                    trace.event("request_reap", cat="request", uid=r.uid,
                                tokens=len(r.generated))
                if rec is not None:
                    breaches = (self.slo.record_request(rec)
                                if self.slo is not None else [])
                    if (trace.sampling and trace.enabled
                            and self._tail_sampler is not None):
                        keep, why = self._tail_sampler.should_promote(
                            breached=bool(breaches),
                            errored=rec["errors"] > 0)
                        if keep:
                            if breaches:
                                why = f"{why}:{','.join(breaches)}"
                            trace.promote(r.uid, rec["submit_t"],
                                          rec["finish_t"], reason=why)

    # -- introspection ----------------------------------------------------

    def cache_bytes(self) -> int:
        """Device bytes held by the paged KV cache (scales with
        ``num_pages``, the blocked-KV contract the reference's allocator
        provides — NOT with ``max_seqs * max_seq_len``)."""
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(self.cache))

    # -- convenience ------------------------------------------------------

    def generate_all(self, prompts: List[np.ndarray], **kw
                     ) -> Dict[int, np.ndarray]:
        """Submit everything, run until drained (batch convenience API —
        the serving loop calls ``step`` itself)."""
        uids = set(self.put_request(p, **kw) for p in prompts)
        outs: Dict[int, np.ndarray] = {}
        while self.has_work():
            self.step()
            for uid, toks in self.get_outputs():
                if uid in uids:
                    outs[uid] = toks
                else:
                    # foreign request (submitted outside this call): keep
                    # it claimable by the caller's own get_outputs()
                    self._unclaimed[uid] = toks
        return outs
