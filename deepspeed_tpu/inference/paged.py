"""Blocked (paged) KV cache for ragged continuous batching.

TPU-native re-design of the reference's FastGen blocked-KV machinery
(``inference/v2/ragged/blocked_allocator.py:1`` ``BlockedAllocator``,
``ragged/kv_cache.py`` ``BlockedKVCache``, and the ragged attention
kernels under ``inference/v2/kernels/ragged_ops/``): KV lives in
fixed-size pages addressed through a per-sequence page table, so device
memory scales with tokens in flight — not ``max_seqs x max_seq_len`` —
and one fused token batch mixes decode tokens with prefill chunks
(Dynamic SplitFuse, ``engine_v2.py:107``).

Device side, attention over the paged cache is JAX's built-in vLLM-TPU
Pallas kernel (``jax.experimental.pallas.ops.tpu.ragged_paged_attention``)
on TPU, and :func:`ref_paged_attention` — an XLA-compilable, mask-based
equivalent of the kernel's reference math — everywhere else (CPU tests).
The page allocator is host-side Python, like the reference's scheduler
tier.

Layout contract (the kernel's): pages are
``[num_pages, page_size, 2 * Hkv, Dh]`` with K at even combined-head
indices and V at odd; a tick's new K/V rows are scattered into the flat
page buffer BEFORE attention, and ``kv_lens`` includes this tick's
tokens.  Page 0 is reserved as the trash page: padding tokens write
there, no sequence is ever allocated it.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat
import numpy as np

TRASH_PAGE = 0

# same mask-value family as ops/flash_attention.py and the Pallas
# quantized kernel: vanishes under softmax, (mask - mask) stays exact 0
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


# ---------------------------------------------------------------------------
# Host-side page allocator (reference blocked_allocator.py)
# ---------------------------------------------------------------------------

def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` (ceil-div, min 1) — the single
    rounding rule shared by the allocator and the engine's page-table
    sizing."""
    return -(-max(n_tokens, 1) // page_size)


class PageAllocator:
    """Free-list page allocator over ``num_pages`` fixed-size pages.

    Page 0 is reserved (trash page for padding-token writes).  Sequences
    either reserve their worst case (``prompt + max_new_tokens``) at
    admission or — the reference's on-demand model
    (``ragged/blocked_allocator.py:1`` + ``engine_v2.py:184``
    ``can_schedule``) — take pages as they grow via :meth:`grow`, with
    the engine's scheduler providing admission backpressure and
    eviction when the pool runs dry mid-flight.

    Pages are refcounted so the prefix cache can share one physical
    page across many sequences (vLLM/SGLang copy-on-write model):

    - :meth:`allocate` / :meth:`grow` hand out pages at refcount 1 —
      never a page whose refcount is still > 0;
    - :meth:`attach` maps an already-resident page into another slot
      read-only (incref);
    - :meth:`free` is a per-page decref — the page returns to the free
      list only when the last reference drops;
    - :meth:`incref` / :meth:`decref` track references held outside any
      slot (the prefix index, spill-holds);
    - :meth:`cow` resolves a write to a shared page: a page at
      refcount 1 is already private, otherwise a fresh private page is
      granted and the old reference dropped (the device copy is the
      caller's job).
    """

    def __init__(self, num_pages: int, page_size: int):
        assert num_pages >= 2, "need at least one non-trash page"
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: List[int] = list(range(num_pages - 1, TRASH_PAGE, -1))
        self._owned: Dict[int, List[int]] = {}     # slot -> page ids
        self._ref = np.zeros(num_pages, dtype=np.int64)  # per-page refcount

    def pages_for(self, n_tokens: int) -> int:
        return pages_for(n_tokens, self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.pages_for(n_tokens) <= len(self._free)

    def _pop_fresh(self) -> int:
        page = self._free.pop()
        assert self._ref[page] == 0, (
            f"free list held page {page} with refcount {self._ref[page]}")
        self._ref[page] = 1
        return page

    def allocate(self, slot: int, n_tokens: int) -> List[int]:
        need = self.pages_for(n_tokens)
        assert slot not in self._owned, f"slot {slot} already allocated"
        assert need <= len(self._free), "out of KV pages"
        pages = [self._pop_fresh() for _ in range(need)]
        self._owned[slot] = pages
        return pages

    def owned(self, slot: int) -> int:
        return len(self._owned.get(slot, ()))

    def owned_pages(self, slot: int) -> List[int]:
        return list(self._owned.get(slot, ()))

    def grow(self, slot: int, n_pages: int) -> List[int]:
        """Extend ``slot`` by ``n_pages`` (on-demand growth; caller
        checks ``free_pages`` first — running dry here is a scheduler
        bug, not backpressure).  Granted pages are exclusively owned
        (refcount 1): a page never leaves the free list while any other
        reference to it is live."""
        assert n_pages <= len(self._free), "out of KV pages (grow)"
        pages = [self._pop_fresh() for _ in range(n_pages)]
        self._owned.setdefault(slot, []).extend(pages)
        return pages

    def attach(self, slot: int, pages: List[int]) -> None:
        """Map already-resident ``pages`` into ``slot`` read-only
        (prefix-cache hit).  Must precede any :meth:`grow` for the slot
        so the slot's page list stays in logical-position order."""
        for p in pages:
            assert p != TRASH_PAGE and self._ref[p] >= 1, (
                f"attach of non-resident page {p} (ref={self._ref[p]})")
            self._ref[p] += 1
        self._owned.setdefault(slot, []).extend(pages)

    def take_page(self) -> int:
        """Grant one fresh page (refcount 1) to an external holder —
        the prefix index reviving a demoted entry owns its page through
        :meth:`incref`/:meth:`decref`, not through a slot."""
        assert self._free, "out of KV pages (take_page)"
        return self._pop_fresh()

    def incref(self, page: int) -> None:
        """Add an external (non-slot) reference — prefix-index entry or
        spill-hold keeping a shared page resident."""
        assert page != TRASH_PAGE and self._ref[page] >= 1, (
            f"incref of non-resident page {page}")
        self._ref[page] += 1

    def decref(self, page: int) -> None:
        assert self._ref[page] >= 1, f"decref of free page {page}"
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def cow(self, slot: int, k: int):
        """Resolve a write to ``slot``'s ``k``-th page.  Returns
        ``(old, new)``: ``old is new`` when the page was already private
        (refcount 1 — nothing to do), otherwise ``new`` is a fresh
        private page already remapped in the slot's page list and the
        caller must device-copy ``old -> new`` and update its page
        table."""
        old = self._owned[slot][k]
        if self._ref[old] == 1:
            return old, old
        assert len(self._free) >= 1, "out of KV pages (cow)"
        new = self._pop_fresh()
        self._owned[slot][k] = new
        self.decref(old)
        return old, new

    def release_pages(self, slot: int, pages: List[int]) -> None:
        """Release a specific subset of ``slot``'s pages while the slot
        stays live (partial-residency parking: the parked middle leaves
        the page list, sinks and the recent window remain).  The
        remaining pages keep their relative order, so page-table rows
        rebuilt from :meth:`owned_pages` stay position-consistent."""
        owned = self._owned[slot]
        for p in pages:
            owned.remove(p)
            self.decref(p)

    def free(self, slot: int) -> None:
        for p in self._owned.pop(slot, ()):
            self.decref(p)

    def audit(self, external: Optional[Dict[int, int]] = None
              ) -> Dict[str, int]:
        """Conservation check for the pool.  Free pages and referenced
        pages partition the non-trash pool; every page's refcount is
        covered by slot ownership plus ``external`` references (prefix
        index entries, spill-holds) when the caller supplies that map —
        i.e. each physical page's refcount equals the number of
        page-table rows referencing it plus held non-slot refs.  Raises
        ``AssertionError`` on a leak or double-grant; returns the
        counts.  The speculative-decoding rollback path keeps pages it
        over-allocated for rejected draft positions (they cover the very
        next block's writes), so accounting exactness — not
        owned==pages_for(length) minimality — is the invariant."""
        owned = [p for pages in self._owned.values() for p in pages]
        counts: Dict[int, int] = {}
        for p in owned:
            counts[p] = counts.get(p, 0) + 1
        if external:
            for p, n in external.items():
                counts[p] = counts.get(p, 0) + n
        free_set = set(self._free)
        assert len(free_set) == len(self._free), (
            f"free list duplicate: {sorted(p for p in free_set if self._free.count(p) > 1)}")
        assert TRASH_PAGE not in free_set and TRASH_PAGE not in counts, (
            "trash page entered circulation")
        ref_pages = {p for p in range(self.num_pages)
                     if self._ref[p] > 0}
        assert not (free_set & ref_pages), (
            f"page both free and referenced: {sorted(free_set & ref_pages)}")
        for p in range(1, self.num_pages):
            r = int(self._ref[p])
            c = counts.get(p, 0)
            if external is not None:
                assert r == c, (
                    f"page {p}: refcount {r} != {c} references "
                    "(page-table rows + external holds)")
            else:
                assert r >= c, (
                    f"page {p}: refcount {r} < {c} slot references")
            if r == 0:
                assert p in free_set, f"page leak: page {p} ref 0 not free"
        assert len(free_set) + len(ref_pages) == self.num_pages - 1, (
            f"page leak: {self.num_pages - 1 - len(free_set) - len(ref_pages)} "
            "pages neither free nor referenced")
        shared = sum(1 for p in ref_pages if self._ref[p] > 1)
        return {"free": len(self._free), "owned": len(owned),
                "total": self.num_pages - 1, "shared": shared,
                "referenced": len(ref_pages)}


# ---------------------------------------------------------------------------
# XLA-compilable reference attention (CPU path / parity oracle)
# ---------------------------------------------------------------------------

def _masked_stats(att: jax.Array, mask: jax.Array, v_r: jax.Array):
    """Streaming-softmax statistics of masked logits.

    att: ``[H, T, R]`` scaled logits already filled with ``_MASK_VALUE``
    where masked; mask: ``[T, R]``; v_r: ``[R, H, D]``.  Returns
    ``(m [T,H], l [T,H], acc [T,H,D])`` — the flash-attention carry
    triple; a query row with no valid key keeps the neutral carry
    ``(m=_MASK_VALUE, l=0, acc=0)``.
    """
    m_cur = jnp.max(att, axis=-1).T                        # [T, H]
    m_safe = jnp.where(m_cur > jnp.float32(_MASK_VALUE * 0.5), m_cur,
                       jnp.float32(_MASK_VALUE))
    p = jnp.exp(att - m_safe.T[:, :, None])                # [H, T, R]
    p = jnp.where(mask[None], p, 0.0)
    l_cur = jnp.sum(p, axis=-1).T                          # [T, H]
    acc_cur = jnp.einsum("htr,rhd->thd", p, v_r)           # [T, H, D]
    return m_safe, l_cur, acc_cur


def fold_stats(carry, m_cur, l_cur, acc_cur):
    """Fold an incoming flash-attention carry ``(m, l, acc)`` with a
    fresh stat triple — the associative streaming-softmax combine the
    chunked partial-residency scan threads across dispatches.  The
    neutral carry ``(m=_MASK_VALUE, l=0, acc=0)`` folds exactly."""
    m0, l0, acc0 = (x.astype(jnp.float32) for x in carry)
    m_new = jnp.maximum(m0, m_cur)
    a0 = jnp.exp(m0 - m_new)
    a1 = jnp.exp(m_cur - m_new)
    l_new = a0 * l0 + a1 * l_cur
    acc_new = a0[..., None] * acc0 + a1[..., None] * acc_cur
    return m_new, l_new, acc_new


def neutral_carry(T: int, H: int, D: int):
    """The identity element for :func:`fold_stats` (all-masked stats)."""
    return (jnp.full((T, H), _MASK_VALUE, jnp.float32),
            jnp.zeros((T, H), jnp.float32),
            jnp.zeros((T, H, D), jnp.float32))


def ref_paged_attention(q: jax.Array, pages: jax.Array, kv_lens: jax.Array,
                        page_indices: jax.Array, cu_q_lens: jax.Array,
                        num_seqs: jax.Array, *, sm_scale: float,
                        sliding_window=None, carry=None) -> jax.Array:
    """Same math as the kernel's ``ref_ragged_paged_attention`` but with
    static control flow (where-masks over the flat page buffer), so it
    jits on any backend.  ``page_indices`` may pad unused entries with -1
    (never matches a real page — including interior holes, which is how
    a partially-resident sequence's parked columns drop out while the
    surviving columns keep their true positions).  O(T * P * page_size)
    — test scale.

    ``carry``: optional incoming flash-attention stats ``(m [T,H],
    l [T,H], acc [T,H,D])`` from earlier dispatches of a chunked scan;
    when given the output folds them in via streaming-softmax math
    (bit-identical shapes, ulp-level numeric difference vs the plain
    softmax path, which is preserved untouched when ``carry is None``).
    """
    T, H, D = q.shape
    P, page, combined, _ = pages.shape
    Hkv = combined // 2
    S, pp = page_indices.shape
    k_flat = pages[:, :, 0::2, :].reshape(P * page, Hkv, D)
    v_flat = pages[:, :, 1::2, :].reshape(P * page, Hkv, D)

    page_of_r = jnp.arange(P * page, dtype=jnp.int32) // page     # [R]
    pos_in_page = jnp.arange(P * page, dtype=jnp.int32) % page

    # token -> sequence (padding tokens map past num_seqs and mask out)
    t_idx = jnp.arange(T, dtype=jnp.int32)
    seq_of_t = jnp.sum((t_idx[:, None] >= cu_q_lens[None, 1:]).astype(
        jnp.int32), axis=1)                                       # [T]
    token_valid = t_idx < cu_q_lens[num_seqs[0]]
    seq_of_t = jnp.minimum(seq_of_t, S - 1)

    # per (seq, flat row): does the row belong to the seq, at which pos
    match = page_indices[:, :, None] == page_of_r[None, None, :]  # [S,pp,R]
    owned = jnp.any(match, axis=1)                                # [S, R]
    kvpos = (jnp.sum(jnp.where(
        match, jnp.arange(pp, dtype=jnp.int32)[None, :, None], 0),
        axis=1) * page + pos_in_page[None, :])                    # [S, R]

    q_len = cu_q_lens[1:] - cu_q_lens[:-1]                        # [S]
    # absolute position of token t within its sequence
    q_pos = (jnp.take(kv_lens - q_len, seq_of_t) +
             (t_idx - jnp.take(cu_q_lens[:-1], seq_of_t)))        # [T]

    mask = (jnp.take(owned, seq_of_t, axis=0) &
            (jnp.take(kvpos, seq_of_t, axis=0) <= q_pos[:, None]) &
            token_valid[:, None])                                 # [T, R]
    if sliding_window is not None:
        mask = mask & (jnp.take(kvpos, seq_of_t, axis=0) >
                       q_pos[:, None] - sliding_window)

    groups = H // Hkv
    k_r = jnp.repeat(k_flat, groups, axis=1)
    v_r = jnp.repeat(v_flat, groups, axis=1)
    att = jnp.einsum("thd,rhd->htr", q.astype(jnp.float32),
                     k_r.astype(jnp.float32)) * sm_scale
    att = jnp.where(mask[None], att, jnp.float32(_MASK_VALUE))
    if carry is None:
        p = jax.nn.softmax(att, axis=-1)
        y = jnp.einsum("htr,rhd->thd", p, v_r.astype(jnp.float32))
        return jnp.where(token_valid[:, None, None], y, 0.0).astype(
            q.dtype)
    m_c, l_c, acc_c = _masked_stats(att, mask, v_r.astype(jnp.float32))
    m_n, l_n, acc_n = fold_stats(carry, m_c, l_c, acc_c)
    y = acc_n / jnp.maximum(l_n, 1e-30)[..., None]
    return jnp.where(token_valid[:, None, None], y, 0.0).astype(q.dtype)


def ref_paged_attention_quant(q: jax.Array, pages: jax.Array,
                              scales: jax.Array, kv_lens: jax.Array,
                              page_indices: jax.Array, cu_q_lens: jax.Array,
                              num_seqs: jax.Array, *, sm_scale: float,
                              sliding_window=None, carry=None) -> jax.Array:
    """Dequant-free XLA read path for a QUANTIZED page pool: gather each
    sequence's attended pages (still 1-byte) through ``page_indices``,
    dequantize ONLY the gathered operand, then masked attention.  The
    dequantized intermediate is ``[S, pp*page, ...]`` — bounded by the
    pages sequences actually attend, never the ``[P, ...]`` pool
    (``test_paged_quant.py`` pins that on the traced jaxpr).  Rows
    gathered in page-table order sit at their kv position directly, so
    masking is ``row < kv_len`` + causal bound + per-column validity
    (a ``-1`` page-table entry — padding or a parked partial-residency
    hole — gathers the trash page, so its rows must mask out even when
    they sit below ``kv_len``).  ``carry`` as
    :func:`ref_paged_attention`.

    q: ``[T, H, D]``; pages: ``[P, page, 2*Hkv, D]`` int8/fp8_e4m3;
    scales: ``[P, page, 2*Hkv]`` fp32.  O(T * pp * page_size) — the
    same test-scale contract as :func:`ref_paged_attention`, but over
    per-sequence attended rows instead of the whole pool.
    """
    T, H, D = q.shape
    P, page, combined, _ = pages.shape
    Hkv = combined // 2
    S, pp = page_indices.shape
    R = pp * page                          # attended rows per sequence

    safe = jnp.maximum(page_indices, 0).reshape(-1)       # [S*pp]
    g_pages = jnp.take(pages, safe, axis=0)               # quantized
    g_scales = jnp.take(scales, safe, axis=0)
    kv = (g_pages.astype(jnp.float32) *
          g_scales[..., None]).reshape(S, R, combined, D)
    k_g = kv[:, :, 0::2, :]                               # [S, R, Hkv, D]
    v_g = kv[:, :, 1::2, :]

    t_idx = jnp.arange(T, dtype=jnp.int32)
    seq_of_t = jnp.sum((t_idx[:, None] >= cu_q_lens[None, 1:]).astype(
        jnp.int32), axis=1)                               # [T]
    token_valid = t_idx < cu_q_lens[num_seqs[0]]
    seq_of_t = jnp.minimum(seq_of_t, S - 1)

    q_len = cu_q_lens[1:] - cu_q_lens[:-1]                # [S]
    q_pos = (jnp.take(kv_lens - q_len, seq_of_t) +
             (t_idx - jnp.take(cu_q_lens[:-1], seq_of_t)))  # [T]
    r_idx = jnp.arange(R, dtype=jnp.int32)
    kv_len_t = jnp.take(kv_lens, seq_of_t)                # [T]
    # column validity: -1 entries (padding OR interior residency holes)
    # gathered the trash page above — their rows never attend
    col_valid = jnp.repeat(page_indices >= 0, page, axis=1)  # [S, R]
    mask = ((r_idx[None, :] <= q_pos[:, None]) &
            (r_idx[None, :] < kv_len_t[:, None]) &
            jnp.take(col_valid, seq_of_t, axis=0) &
            token_valid[:, None])                         # [T, R]
    if sliding_window is not None:
        mask = mask & (r_idx[None, :] > q_pos[:, None] - sliding_window)

    groups = H // Hkv
    k_t = jnp.repeat(jnp.take(k_g, seq_of_t, axis=0), groups, axis=2)
    v_t = jnp.repeat(jnp.take(v_g, seq_of_t, axis=0), groups, axis=2)
    att = jnp.einsum("thd,trhd->htr", q.astype(jnp.float32),
                     k_t) * sm_scale
    att = jnp.where(mask[None], att, jnp.float32(_MASK_VALUE))
    if carry is None:
        p = jax.nn.softmax(att, axis=-1)
        y = jnp.einsum("htr,trhd->thd", p, v_t)
        return jnp.where(token_valid[:, None, None], y, 0.0).astype(
            q.dtype)
    # carry path reuses the flat-row helper: v as [T, R, H, D] must be
    # indexed per token, so fold with einsum over the token-gathered v
    m_cur = jnp.max(att, axis=-1).T                       # [T, H]
    m_safe = jnp.where(m_cur > jnp.float32(_MASK_VALUE * 0.5), m_cur,
                       jnp.float32(_MASK_VALUE))
    p = jnp.exp(att - m_safe.T[:, :, None])
    p = jnp.where(mask[None], p, 0.0)
    l_cur = jnp.sum(p, axis=-1).T                         # [T, H]
    acc_cur = jnp.einsum("htr,trhd->thd", p, v_t)         # [T, H, D]
    m_n, l_n, acc_n = fold_stats(carry, m_safe, l_cur, acc_cur)
    y = acc_n / jnp.maximum(l_n, 1e-30)[..., None]
    return jnp.where(token_valid[:, None, None], y, 0.0).astype(q.dtype)


# ---------------------------------------------------------------------------
# Flax-side: write new KV into pages, attend
# ---------------------------------------------------------------------------

def _staged_attend_stats(mdl, q: jax.Array, ragged_meta, cfg) -> jax.Array:
    """Chunk-stats dispatch of the partial-residency scan: attend the
    query tokens against a STAGED dense KV block (parked pages uploaded
    through the staging buffer, never entering the pool) and sow the
    flash-attention stat triple into the ``"carry"`` collection instead
    of producing attention output.

    ``ragged_meta`` carries ``staged_kv [R, 2*Hkv, D]`` (store dtype —
    int8/fp8 pages stay 1-byte and dequantize here via
    ``staged_scales [R, 2*Hkv]``), ``staged_kpos [R]`` absolute key
    positions, ``staged_qpos [T]`` absolute query positions, and
    optionally an incoming carry (``carry_m``/``carry_l``/``carry_acc``)
    folded before sowing.  The pool is untouched: no cache variable is
    created, so chunk dispatches need no ``cache`` collection at all.
    Returns zeros shaped like the normal attention output — the driver
    reads the stats, not the module output.
    """
    _, H, T, D = q.shape
    staged = ragged_meta["staged_kv"]
    R, combined, _ = staged.shape
    Hkv = combined // 2
    sf = staged.astype(jnp.float32)
    if "staged_scales" in ragged_meta:
        sf = sf * ragged_meta["staged_scales"][..., None].astype(
            jnp.float32)
    k_s = sf[:, 0::2, :]                                   # [R, Hkv, D]
    v_s = sf[:, 1::2, :]
    groups = H // Hkv
    k_r = jnp.repeat(k_s, groups, axis=1)                  # [R, H, D]
    v_r = jnp.repeat(v_s, groups, axis=1)
    qt = q[0].transpose(1, 0, 2).astype(jnp.float32)       # [T, H, D]
    sm_scale = float(1.0 / np.sqrt(D))
    att = jnp.einsum("thd,rhd->htr", qt, k_r) * sm_scale
    kpos = ragged_meta["staged_kpos"]
    qpos = ragged_meta["staged_qpos"]
    # parked groups are full pages of live tokens strictly below the
    # query frontier, so the causal bound is usually all-true — kept
    # anyway (with the window bound) so a partially-covered group near
    # a sliding window stays exact
    mask = kpos[None, :] <= qpos[:, None]                  # [T, R]
    window = getattr(cfg, "sliding_window", None)
    if window is not None:
        mask = mask & (kpos[None, :] > qpos[:, None] - window)
    att = jnp.where(mask[None], att, jnp.float32(_MASK_VALUE))
    m_c, l_c, acc_c = _masked_stats(att, mask, v_r)
    if "carry_m" in ragged_meta:
        m_c, l_c, acc_c = fold_stats(
            (ragged_meta["carry_m"], ragged_meta["carry_l"],
             ragged_meta["carry_acc"]), m_c, l_c, acc_c)
    mdl.sow("carry", "stats", (m_c, l_c, acc_c))
    return jnp.zeros((1, H, T, D), q.dtype)


def paged_update_and_attend(mdl, q: jax.Array, k: jax.Array, v: jax.Array,
                            ragged_meta: Dict[str, jax.Array], cfg
                            ) -> jax.Array:
    """Inside an attention module: scatter this tick's K/V rows into the
    layer's page buffer, then ragged-paged attention for all T tokens.

    q: [1, H, T, D]; k, v: [1, Hkv, T, D] (rotary already applied).
    Returns [1, H, T, D].  Requires ``mutable=["cache"]`` on apply.

    Partial residency hooks (the chunked multi-dispatch scan): a
    ``staged_kv`` key in ``ragged_meta`` short-circuits to
    :func:`_staged_attend_stats` BEFORE any cache variable exists —
    chunk dispatches never touch the pool.  ``carry_m``/``carry_l``/
    ``carry_acc`` keys make the normal (finish) dispatch fold the
    accumulated chunk stats into its attention via the explicit-carry
    paths of the reference functions / quantized kernel.
    """
    _, H, T, D = q.shape
    if "staged_kv" in ragged_meta:
        return _staged_attend_stats(mdl, q, ragged_meta, cfg)
    carry = None
    if "carry_m" in ragged_meta:
        carry = (ragged_meta["carry_m"], ragged_meta["carry_l"],
                 ragged_meta["carry_acc"])
    Hkv = k.shape[1]
    P, page = cfg.kv_num_pages, cfg.kv_page_size
    assert P > 1, "paged_decode requires kv_num_pages (engine sets it)"

    # KV-cache quantization (reference csrc/fp_quantizer selective_dequant
    # + inference v2 KV configs): pages persist in fp8 e4m3 or int8 with a
    # per-(row, head) fp32 scale and are READ quantized — per-tile
    # register dequant in ops/ragged_paged_quant.py (TPU) or the
    # gathered-pages XLA reference below; never a full-width pool operand
    kv_quant = getattr(cfg, "kv_cache_dtype", "none") or "none"
    if kv_quant in ("fp8", "fp8_e4m3"):
        store_dtype, qmax = jnp.float8_e4m3fn, float(
            jnp.finfo(jnp.float8_e4m3fn).max)
    elif kv_quant == "int8":
        store_dtype, qmax = jnp.int8, 127.0
    else:
        assert kv_quant == "none", f"unknown kv_cache_dtype {kv_quant!r}"
        store_dtype, qmax = k.dtype, None

    pages_var = mdl.variable(
        "cache", "kv_pages", jnp.zeros, (P, page, 2 * Hkv, D), store_dtype)

    # interleave K/V onto combined heads: [T, 2Hkv, D], K even, V odd
    k_rows = k[0].transpose(1, 0, 2)                   # [T, Hkv, D]
    v_rows = v[0].transpose(1, 0, 2)
    combined = jnp.stack([k_rows, v_rows], axis=2).reshape(T, 2 * Hkv, D)

    flat = pages_var.value.reshape(P * page, 2 * Hkv, D)
    if qmax is None:
        flat = flat.at[ragged_meta["new_kv_dest"]].set(
            combined.astype(flat.dtype), mode="drop")
        pages = flat.reshape(P, page, 2 * Hkv, D)
        pages_var.value = pages
    else:
        scales_var = mdl.variable(
            "cache", "kv_scales", jnp.zeros, (P, page, 2 * Hkv),
            jnp.float32)
        cf = combined.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(cf), axis=-1)         # [T, 2Hkv]
        # floor the QUOTIENT at the smallest normal f32, not absmax at
        # an arbitrary 1e-12: fp8's qmax=448 can push absmax/qmax
        # subnormal, and a subnormal scale's reciprocal overflows
        # qv = cf/scale to inf before the store-dtype cast.  For any
        # absmax >= 1e-12 this is bit-identical to the old floor.
        scale = jnp.maximum(absmax / qmax,
                            jnp.float32(np.finfo(np.float32).tiny))
        qv = cf / scale[..., None]
        if store_dtype == jnp.int8:
            qv = jnp.clip(jnp.round(qv), -qmax, qmax)
        flat = flat.at[ragged_meta["new_kv_dest"]].set(
            qv.astype(store_dtype), mode="drop")
        flat_s = scales_var.value.reshape(P * page, 2 * Hkv)
        flat_s = flat_s.at[ragged_meta["new_kv_dest"]].set(scale,
                                                           mode="drop")
        scales_var.value = flat_s.reshape(P, page, 2 * Hkv)
        pages = flat.reshape(P, page, 2 * Hkv, D)
        pages_var.value = pages
        # NO transient dequant: the quantized pool is read directly by
        # the dequant-free attention variants below — per-tile register
        # dequant in the Pallas kernel on TPU, gathered-pages dequant
        # (O(attended rows), never O(pool)) in the XLA reference
        kv_scales = scales_var.value

    qt = q[0].transpose(1, 0, 2)                       # [T, H, D]
    sm_scale = float(1.0 / np.sqrt(D))
    kv_lens = ragged_meta["kv_lens"]
    cu_q_lens = ragged_meta["cu_q_lens"]
    num_seqs = ragged_meta["num_seqs"]
    page_indices = ragged_meta["page_indices"]
    window = getattr(cfg, "sliding_window", None)

    def attend(qt, pages, kv_lens, page_indices, cu_q_lens, num_seqs):
        # the vLLM-TPU kernel is built for head_dim 128 (its lane-width
        # row stats assert on smaller D); other dims take the XLA
        # reference — correct but O(T * total_page_rows), serving-shape
        # models should use 128-dim heads.  An incoming chunk-scan
        # carry always routes to the reference: the upstream kernel has
        # no carry operand (the quantized pool's own kernel does).
        if carry is None and jax.default_backend() == "tpu" and D == 128:
            from jax.experimental.pallas.ops.tpu.ragged_paged_attention \
                import kernel as rpa

            return rpa.ragged_paged_attention(
                qt, pages, kv_lens, jnp.maximum(page_indices, 0),
                cu_q_lens, num_seqs, sm_scale=sm_scale,
                sliding_window=window)
        if carry is None and jax.default_backend() == "tpu":
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                f"paged attention: head_dim={D} != 128 — the Pallas "
                "ragged kernel needs 128; using the dense XLA fallback")
        return ref_paged_attention(
            qt, pages, kv_lens, page_indices, cu_q_lens, num_seqs,
            sm_scale=sm_scale, sliding_window=window, carry=carry)

    def attend_quant(qt, pages, scales, kv_lens, page_indices, cu_q_lens,
                     num_seqs):
        # quantized pool: both routes read the 1-byte pages + scale rows
        # directly (see kv_dequant_path for the route the engine reports)
        if kv_dequant_path(D) == "pallas-quant":
            from deepspeed_tpu.ops.ragged_paged_quant import \
                ragged_paged_attention_quant

            return ragged_paged_attention_quant(
                qt, pages, scales, kv_lens, page_indices, cu_q_lens,
                num_seqs, sm_scale=sm_scale, sliding_window=window,
                carry=carry)
        return ref_paged_attention_quant(
            qt, pages, scales, kv_lens, page_indices, cu_q_lens, num_seqs,
            sm_scale=sm_scale, sliding_window=window, carry=carry)

    # TP serving (reference v2 sharding/attn.py: heads split over the TP
    # group): attention is embarrassingly parallel over heads, so under a
    # >1 `tensor` mesh axis run it shard_map-manual over `tensor` with q
    # and the KV pages head-sharded and the ragged metadata replicated —
    # required for the Pallas kernel, which composes with shard_map, not
    # with GSPMD auto-sharding
    tp = _serving_tp(cfg)
    if tp > 1:
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.sequence.layer import resolve_mesh

        assert carry is None, (
            "chunked partial-residency scan requires tensor_parallel=1 "
            "(the long-context driver gates admission on it)")
        assert H % tp == 0 and Hkv % tp == 0, (
            f"TP serving requires heads divisible by tp={tp} "
            f"(H={H}, Hkv={Hkv})")
        mesh = resolve_mesh(None, "tensor")
        if qmax is None:
            y = _shard_map_compat(
                attend, mesh=mesh,
                in_specs=(P(None, "tensor", None),
                          P(None, None, "tensor", None), P(), P(), P(),
                          P()),
                out_specs=P(None, "tensor", None),
                axis_names={"tensor"}, check_vma=False)(
                    qt, pages, kv_lens, page_indices, cu_q_lens, num_seqs)
        else:
            # the scale buffer shards its combined-head dim with the pool
            y = _shard_map_compat(
                attend_quant, mesh=mesh,
                in_specs=(P(None, "tensor", None),
                          P(None, None, "tensor", None),
                          P(None, None, "tensor"), P(), P(), P(), P()),
                out_specs=P(None, "tensor", None),
                axis_names={"tensor"}, check_vma=False)(
                    qt, pages, kv_scales, kv_lens, page_indices,
                    cu_q_lens, num_seqs)
    elif qmax is None:
        y = attend(qt, pages, kv_lens, page_indices, cu_q_lens, num_seqs)
    else:
        y = attend_quant(qt, pages, kv_scales, kv_lens, page_indices,
                         cu_q_lens, num_seqs)
    return y.transpose(1, 0, 2)[None]                  # [1, H, T, D]


def kv_dequant_path(head_dim: int) -> str:
    """Which dequant-free read path a quantized pool takes on this
    backend: the Pallas quantized-pages kernel
    (:mod:`deepspeed_tpu.ops.ragged_paged_quant`; TPU, head_dim 128) or
    the gathered-pages XLA reference
    (:func:`ref_paged_attention_quant`).  Neither materializes a
    full-width pool operand.  The engine reports this in its
    ``serving_stages()['kv_quant']`` block."""
    if jax.default_backend() == "tpu" and head_dim == 128:
        return "pallas-quant"
    return "xla-gather"


def _serving_tp(cfg) -> int:
    """Tensor-parallel degree for the paged path: the model must be
    TP-annotated AND a multi-device `tensor` mesh axis installed."""
    if not getattr(cfg, "tensor_parallel", False):
        return 1
    import deepspeed_tpu.comm as dist

    topo = dist.peek_topology()
    if topo is None:
        return 1
    return int(topo.mesh.shape.get("tensor", 1))
