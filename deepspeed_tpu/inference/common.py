"""Conventions shared by the v1 and v2 inference engines."""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def logits_of(out):
    """Models may return (logits, aux) tuples (e.g. Mixtral's router
    loss); serving wants the logits."""
    return out[0] if isinstance(out, tuple) else out


def normalize_params(model, params: Any,
                     rng: Optional[jax.Array] = None,
                     plain_model=None):
    """Default-init when absent (benchmarking) and strip the flax
    ``{"params": ...}`` wrapper."""
    if params is None:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        init_model = plain_model if plain_model is not None else model
        params = jax.jit(init_model.init)(rng, np.zeros((1, 8), np.int32))
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    return params


def unroll_scan_params(params):
    """Scan-stacked layer params -> unrolled-layer params.

    Decode models run with ``scan_layers=False``: flax's scan-over-layers
    restacks the mutable KV-cache collection every decode step (profiled
    at ~2.4ms/step of full-cache copies on a 302MB GPT-2 cache, v5e —
    3.8x decode throughput once removed), while unrolled layers keep one
    independently-aliased cache per layer.  Training params stay stacked;
    this converts a scan subtree ``{K: {"block": leaves[L, ...]}}`` into
    ``{K_0: leaves[...], ..., K_{L-1}: ...}`` (the models' unrolled
    naming).  Call INSIDE the jitted decode program so the slices fuse
    instead of materializing copies.
    """
    import jax.tree_util as jtu

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict) and set(v) == {"block"}:
                sub = walk(v["block"])
                L = jtu.tree_leaves(sub)[0].shape[0]
                for i in range(L):
                    out[f"{k}_{i}"] = jtu.tree_map(
                        lambda x, _i=i: x[_i], sub)
            else:
                out[k] = walk(v)
        return out

    return walk(params)
