"""Conventions shared by the v1 and v2 inference engines."""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics


class HostStageStats:
    """Per-dispatch host-path breakdown for the serving engines.

    The serving wall/device throughput gap lives entirely on the host
    (BENCH_MATRIX ragged: 23.3k device vs 295 wall tok/s), so both
    engines bracket every hot-loop stage:

    - ``plan``:     host-side numpy scheduling (admission, SplitFuse
                    packing, page growth, run-ahead projection)
    - ``upload``:   host->device metadata transfers (``jnp.asarray``)
    - ``dispatch``: handing the jitted program to the async runtime
    - ``device``:   host BLOCKED waiting on device results (the only
                    sync points: harvests and run-ahead depth waits)
    - ``harvest``:  folding fetched tokens back into request state

    ``serving_stages()`` reports per-dispatch milliseconds plus
    ``host_bound_fraction`` = host-stage time / (host + device-wait)
    — ~1.0 means the loop never waits on the device (host-bound),
    ~0.0 means the host keeps the device saturated (device-bound).
    Counters make the pipelining contract testable: ``meta_uploads``
    and ``blocking_gets`` must stay flat across steady-state decode
    blocks when the pipeline is on.

    Speculative decoding adds two host stages — ``draft`` (draft-model
    KV catch-up prefill + host-side draft planning) and ``verify`` (the
    fused draft+verify block's dispatch bracket; program handoff time,
    not device time) — and the ``spec_*`` counters.  When any
    speculative block ran, ``serving_stages()`` carries a
    ``speculation`` sub-dict with the acceptance breakdown.

    KV tiering adds ``spill`` (page gather + device_get + handoff to
    the tier store) and ``restore`` (tier fetch + verify + page upload
    + scatter); the v2 engine additionally merges the tier store's own
    flat stats as a ``kv_tiering`` sub-dict.

    The prefix cache adds ``prefix`` (index lookup + token
    verification + attach/COW bookkeeping at admission) and the
    ``prefix_*`` counters; when the index saw any lookup the v2 engine
    emits a ``prefix_cache`` sub-dict merging the index's own stats.
    """

    STAGES = ("plan", "upload", "dispatch", "device", "harvest", "draft",
              "verify", "spill", "restore", "prefix")

    def __init__(self, replica: str = ""):
        # scale-out serving runs several engines in one process; the
        # ``replica`` label keeps their registry children apart (the
        # solo-engine default is the empty label value, so a process
        # with one engine exports the same series it always did)
        self.replica = str(replica)
        self._hists: Dict[str, Any] = {}
        self._hist_fam = None
        self.reset()

    def set_replica(self, replica: str) -> None:
        """Re-label after construction (ReplicaSet assigns indices to
        engines built without one); drops cached children so the next
        bracket lands under the new label."""
        self.replica = str(replica)
        self._hists.clear()
        self._hist_fam = None

    def reset(self) -> None:
        self.seconds: Dict[str, float] = {s: 0.0 for s in self.STAGES}
        self.ticks = 0            # model ticks (a K-block counts K)
        self.dispatches = 0       # compiled-program launches
        self.meta_uploads = 0     # host->device metadata arrays sent
        self.blocking_gets = 0    # blocking device->host fetches
        self.harvests = 0         # deferred-harvest fold-backs
        self.spec_dispatches = 0  # speculative draft+verify blocks
        self.spec_proposed = 0    # draft tokens proposed (device count)
        self.spec_accepted = 0    # draft tokens accepted (device count)
        self.spec_tokens = 0      # tokens emitted by speculative blocks
        self.prefix_hits = 0      # admissions that attached >=1 cached page
        self.prefix_misses = 0    # admissions that attached nothing
        self.prefix_hit_pages = 0   # cached pages attached
        self.prefix_hit_tokens = 0  # prefill tokens skipped via the cache
        self.prefix_cow_copies = 0  # copy-on-write page copies

    @contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.seconds[name] += dt
            if trace.enabled:
                trace.add_complete(name, t0, dt, cat="serving")
            if _metrics.enabled:
                self._stage_hist(name).observe(dt)

    def _stage_hist(self, name: str):
        """Cached registry child for this stage (lookup once, then a
        plain attribute read per bracket)."""
        h = self._hists.get(name)
        if h is None or self._hist_fam is not _metrics.get(
                "dstpu_serving_stage_seconds"):
            self._hist_fam = _metrics.histogram(
                "dstpu_serving_stage_seconds",
                "Serving host-path stage bracket durations (s)",
                labels=("stage", "replica"))
            h = self._hist_fam.labels(stage=name, replica=self.replica)
            self._hists[name] = h
        return h

    def serving_stages(self) -> Dict[str, Any]:
        d = max(self.dispatches, 1)
        out: Dict[str, Any] = {
            f"{s}_ms": round(self.seconds[s] * 1e3 / d, 4)
            for s in self.STAGES}
        host = sum(self.seconds[s] for s in
                   ("plan", "upload", "dispatch", "harvest", "draft",
                    "verify", "spill", "restore", "prefix"))
        dev = self.seconds["device"]
        out["host_s"] = round(host, 4)
        out["device_wait_s"] = round(dev, 4)
        out["host_bound_fraction"] = (round(host / (host + dev), 4)
                                      if host + dev > 0 else None)
        out.update(ticks=self.ticks, dispatches=self.dispatches,
                   meta_uploads=self.meta_uploads,
                   blocking_gets=self.blocking_gets,
                   harvests=self.harvests)
        if self.spec_dispatches:
            sd = self.spec_dispatches
            out["speculation"] = {
                "spec_dispatches": sd,
                "draft_ms": round(self.seconds["draft"] * 1e3 / sd, 4),
                "verify_ms": round(self.seconds["verify"] * 1e3 / sd, 4),
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "acceptance_rate": round(
                    self.spec_accepted / max(self.spec_proposed, 1), 4),
                "mean_accepted_len": round(
                    self.spec_accepted / max(self.spec_tokens -
                                             self.spec_accepted, 1), 4),
                "tokens": self.spec_tokens,
                "effective_tokens_per_dispatch": round(
                    self.spec_tokens / sd, 2),
            }
        return out


def kv_quant_block(cache, fmt: str, dequant_path: str,
                   num_pages: int) -> Dict[str, Any]:
    """``serving_stages()['kv_quant']`` sub-dict for a quantized paged
    pool: exact byte accounting (1-byte payload pages vs fp32 scale
    rows), the dequant-free read route taken on this backend, and
    written-scale statistics.  Fetches the scale leaves — call at
    stats/report time, never in the serving hot loop."""
    leaves = jax.tree_util.tree_leaves(cache)
    payload = sum(leaf.size * leaf.dtype.itemsize for leaf in leaves
                  if np.dtype(leaf.dtype).itemsize == 1)
    scale_bytes = sum(leaf.size * leaf.dtype.itemsize for leaf in leaves
                      if np.dtype(leaf.dtype).itemsize != 1)
    scales = [np.asarray(jax.device_get(leaf)).ravel() for leaf in leaves
              if np.dtype(leaf.dtype).itemsize != 1]
    flat = (np.concatenate(scales) if scales
            else np.zeros((0,), np.float32))
    # the quant write path floors every written scale at the smallest
    # normal f32, so exact zeros are rows never written
    nz = flat[flat != 0.0]
    return {
        "format": fmt,
        "dequant_path": dequant_path,
        "pool_bytes": payload + scale_bytes,
        "payload_bytes": payload,
        "scale_bytes": scale_bytes,
        "num_pages": int(num_pages),
        "scale_rows_written": int(nz.size),
        "scale_min": float(nz.min()) if nz.size else 0.0,
        "scale_max": float(nz.max()) if nz.size else 0.0,
        "scale_mean": float(nz.mean()) if nz.size else 0.0,
    }


def logits_of(out):
    """Models may return (logits, aux) tuples (e.g. Mixtral's router
    loss); serving wants the logits."""
    return out[0] if isinstance(out, tuple) else out


def normalize_params(model, params: Any,
                     rng: Optional[jax.Array] = None,
                     plain_model=None):
    """Default-init when absent (benchmarking) and strip the flax
    ``{"params": ...}`` wrapper."""
    if params is None:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        init_model = plain_model if plain_model is not None else model
        params = jax.jit(init_model.init)(rng, np.zeros((1, 8), np.int32))
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    return params


def unroll_scan_params(params):
    """Scan-stacked layer params -> unrolled-layer params.

    Decode models run with ``scan_layers=False``: flax's scan-over-layers
    restacks the mutable KV-cache collection every decode step (profiled
    at ~2.4ms/step of full-cache copies on a 302MB GPT-2 cache, v5e —
    3.8x decode throughput once removed), while unrolled layers keep one
    independently-aliased cache per layer.  Training params stay stacked;
    this converts a scan subtree ``{K: {"block": leaves[L, ...]}}`` into
    ``{K_0: leaves[...], ..., K_{L-1}: ...}`` (the models' unrolled
    naming).  Call INSIDE the jitted decode program so the slices fuse
    instead of materializing copies.
    """
    import jax.tree_util as jtu

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict) and set(v) == {"block"}:
                sub = walk(v["block"])
                L = jtu.tree_leaves(sub)[0].shape[0]
                for i in range(L):
                    out[f"{k}_{i}"] = jtu.tree_map(
                        lambda x, _i=i: x[_i], sub)
            else:
                out[k] = walk(v)
        return out

    return walk(params)
