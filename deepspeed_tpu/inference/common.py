"""Conventions shared by the v1 and v2 inference engines."""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def logits_of(out):
    """Models may return (logits, aux) tuples (e.g. Mixtral's router
    loss); serving wants the logits."""
    return out[0] if isinstance(out, tuple) else out


def normalize_params(model, params: Any,
                     rng: Optional[jax.Array] = None,
                     plain_model=None):
    """Default-init when absent (benchmarking) and strip the flax
    ``{"params": ...}`` wrapper."""
    if params is None:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        init_model = plain_model if plain_model is not None else model
        params = jax.jit(init_model.init)(rng, np.zeros((1, 8), np.int32))
    if isinstance(params, dict) and "params" in params:
        params = params["params"]
    return params
