"""Token sampling for the generate loop.

The reference defers sampling to HuggingFace ``generate`` (v1,
``inference/engine.py:554``) or implements greedy/top-p in its ragged
logits-gather kernels (v2).  Here sampling is a pure jittable function over
the last-position logits so the whole generate loop stays inside one XLA
program.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def position_keys(base: jax.Array, seeds: jax.Array,
                  positions: jax.Array) -> jax.Array:
    """Per-row PRNG keys [B, 2] for scheduling-invariant sampling: the
    key for one drawn token is a pure function of (engine base key,
    request seed, cache position), so the SAME token of the SAME
    request samples identically no matter how requests were batched,
    chunked, evicted, or prefix-cache-skipped along the way.  This is
    what lets seeded sampling stay bit-identical between prefix-cache
    on and off runs, whose dispatch sequences differ."""
    def one(seed, pos):
        return jax.random.fold_in(jax.random.fold_in(base, seed), pos)

    return jax.vmap(one)(seeds, positions)


def sample_logits(logits: jax.Array, rng: Optional[jax.Array], *,
                  do_sample: bool = False, temperature: float = 1.0,
                  top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Next token ids [B] from logits [B, V].

    ``do_sample``/``top_k`` are static (change recompiles); temperature and
    top_p are folded in as constants of the compiled program too since they
    arrive as Python floats.

    ``rng`` may be one key (shared draw over the batch) or per-row keys
    ``[B, 2]`` from :func:`position_keys` (detected by ndim).
    """
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature != 1.0:
        logits = logits / jnp.float32(max(temperature, 1e-6))
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]      # [B, 1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # desc
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the mass BEFORE them is < top_p (always >=1 kept)
        keep_sorted = (cum - probs) < top_p
        kth_idx = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, kth_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    assert rng is not None, "sampling needs an rng"
    if rng.ndim == 2:                                    # per-row keys
        return jax.vmap(lambda k, l: jax.random.categorical(k, l))(
            rng, logits).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def filter_logits_batched(logits: jax.Array, temperature: jax.Array,
                          top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Per-row temperature/top-k/top-p filter over [S, V] float32 logits:
    kept entries scaled by temperature, filtered entries at ``-inf``.
    Softmax of the result is EXACTLY the distribution
    :func:`sample_logits_batched` draws from — the speculative verify
    path scores draft tokens against it so acceptance preserves the
    serving distribution bit-for-bit in expectation."""
    S, V = logits.shape
    lg = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: threshold at the k-th largest value (k<=0 -> keep all)
    k = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_lg, (k - 1)[:, None], axis=-1)
    lg = jnp.where(lg < kth, -jnp.inf, lg)
    # top-p on the top-k-filtered distribution (matches sample_logits'
    # sequential filter semantics) — re-sort so masked rows drop out
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_lg, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < jnp.clip(top_p, 0.0, 1.0)[:, None]
    kth_idx = jnp.maximum(jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1,
                          0)
    cutoff = jnp.take_along_axis(sorted_lg, kth_idx, axis=-1)
    return jnp.where(lg < cutoff, -jnp.inf, lg)


def sample_logits_batched(logits: jax.Array, rng: Optional[jax.Array],
                          do_sample: jax.Array, temperature: jax.Array,
                          top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Per-ROW sampling configs, fully on device: next token ids [S] from
    logits [S, V] with ``do_sample``/``temperature``/``top_k``/``top_p``
    as [S] arrays (so one compiled program serves a continuous batch of
    requests with heterogeneous sampling settings — the v2 engine's
    on-device multi-tick decode needs this; the reference samples host-side
    per request).

    ``rng=None`` compiles the pure-greedy program (no sort).  ``top_k <= 0``
    and ``top_p >= 1`` disable their filters per row.  ``rng`` may be one
    key or per-row keys ``[S, 2]`` (:func:`position_keys`).
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        return greedy
    lg = filter_logits_batched(logits, temperature, top_k, top_p)
    if rng.ndim == 2:                                    # per-row keys
        sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(
            rng, lg).astype(jnp.int32)
    else:
        sampled = jax.random.categorical(rng, lg, axis=-1).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)


def speculative_verify(logits: jax.Array, draft_toks: jax.Array,
                       draft_probs: Optional[jax.Array],
                       rng: Optional[jax.Array], do_sample: jax.Array,
                       temperature: jax.Array, top_k: jax.Array,
                       top_p: jax.Array):
    """Accept/rollback core of speculative decoding (jit-pure).

    ``logits`` [S, k+1, V]: the TARGET model's logits over a drafted
    chunk — row ``i`` conditions on the sequence plus drafted tokens
    ``< i``.  ``draft_toks`` [S, k]: the proposals.  ``draft_probs``
    [S, k, V] is the draft's (filtered) proposal distribution, or
    ``None`` for point-mass drafts (prompt-lookup / n-gram — the draft
    "distribution" is a delta at the proposed token).

    Returns ``(out_toks [S, k+1], accept_len [S])``: per slot the first
    ``accept_len`` entries of ``out_toks`` are accepted draft tokens and
    entry ``accept_len`` is the correction/bonus token, so a slot always
    emits ``accept_len + 1 in [1, k+1]`` tokens (callers clamp by
    budget/eos).

    - Greedy rows (``do_sample`` False): longest exact-match prefix
      against the target argmax; the emitted tokens are the target's
      greedy continuation REGARDLESS of draft quality, so greedy
      speculative output is bit-identical to non-speculative decode.
    - Sampled rows: standard rejection sampling — accept ``d_i`` with
      probability ``min(1, p_i(d_i) / q_i(d_i))``; on first rejection
      resample from the residual ``max(p_i - q_i, 0)`` (renormalized);
      if all accepted, sample the bonus from ``p_{k+1}``.  The output
      distribution provably equals sampling from ``p`` directly
      (Leviathan et al.; tested by Monte-Carlo in the suite).
      ``p`` is the same filtered distribution the non-speculative
      sampler draws from (:func:`filter_logits_batched`).

    ``rng=None`` compiles the pure-greedy program.
    """
    S, K1, V = logits.shape
    k = K1 - 1
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)            # [S, K1]
    g_match = draft_toks == greedy[:, :k]                         # [S, k]
    a_greedy = jnp.sum(jnp.cumprod(g_match.astype(jnp.int32),
                                   axis=1), axis=1)               # [S]
    if rng is None:
        return greedy, a_greedy

    rep = lambda x: jnp.repeat(x, K1, axis=0)
    lg = filter_logits_batched(lf.reshape(S * K1, V), rep(temperature),
                               rep(top_k), rep(top_p))
    p = jax.nn.softmax(lg, axis=-1).reshape(S, K1, V)             # [S,K1,V]
    p_d = jnp.take_along_axis(p[:, :k], draft_toks[..., None],
                              axis=-1)[..., 0]                    # [S, k]
    if draft_probs is None:
        # point-mass draft: q(d)=1 -> accept with prob p(d); residual is
        # p with the drafted token removed, renormalized
        ratio = p_d
        residual = p[:, :k] * (jnp.arange(V)[None, None, :] !=
                               draft_toks[..., None])
    else:
        q = draft_probs.astype(jnp.float32)
        q_d = jnp.take_along_axis(q, draft_toks[..., None],
                                  axis=-1)[..., 0]
        ratio = p_d / jnp.maximum(q_d, 1e-30)
        residual = jnp.maximum(p[:, :k] - q, 0.0)
    key_u, key_r, key_b = jax.random.split(rng, 3)
    u = jax.random.uniform(key_u, (S, k))
    accept = u < ratio                                            # [S, k]
    a_samp = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                     axis=1)                                      # [S]
    # residual resample per position (independent keys are fine: the
    # correction at i is only USED when i is the first rejection);
    # all-zero residual (p <= q everywhere) falls back to p itself
    res_sum = jnp.sum(residual, axis=-1, keepdims=True)
    res = jnp.where(res_sum > 1e-30, residual, p[:, :k])
    corr = jax.random.categorical(
        key_r, jnp.log(jnp.maximum(res, 1e-30)), axis=-1)         # [S, k]
    bonus = jax.random.categorical(
        key_b, jnp.log(jnp.maximum(p[:, k], 1e-30)), axis=-1)     # [S]
    fixes = jnp.concatenate([corr, bonus[:, None]],
                            axis=1).astype(jnp.int32)             # [S, K1]
    d_pad = jnp.concatenate(
        [draft_toks, jnp.zeros((S, 1), jnp.int32)], axis=1)
    j = jnp.arange(K1)[None, :]
    out_samp = jnp.where(j < a_samp[:, None], d_pad, fixes)
    out = jnp.where(do_sample[:, None], out_samp, greedy)
    return out.astype(jnp.int32), jnp.where(do_sample, a_samp, a_greedy)
