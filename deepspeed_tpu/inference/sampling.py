"""Token sampling for the generate loop.

The reference defers sampling to HuggingFace ``generate`` (v1,
``inference/engine.py:554``) or implements greedy/top-p in its ragged
logits-gather kernels (v2).  Here sampling is a pure jittable function over
the last-position logits so the whole generate loop stays inside one XLA
program.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logits: jax.Array, rng: Optional[jax.Array], *,
                  do_sample: bool = False, temperature: float = 1.0,
                  top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Next token ids [B] from logits [B, V].

    ``do_sample``/``top_k`` are static (change recompiles); temperature and
    top_p are folded in as constants of the compiled program too since they
    arrive as Python floats.
    """
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature != 1.0:
        logits = logits / jnp.float32(max(temperature, 1e-6))
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]      # [B, 1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # desc
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the mass BEFORE them is < top_p (always >=1 kept)
        keep_sorted = (cum - probs) < top_p
        kth_idx = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, kth_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    assert rng is not None, "sampling needs an rng"
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_logits_batched(logits: jax.Array, rng: Optional[jax.Array],
                          do_sample: jax.Array, temperature: jax.Array,
                          top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Per-ROW sampling configs, fully on device: next token ids [S] from
    logits [S, V] with ``do_sample``/``temperature``/``top_k``/``top_p``
    as [S] arrays (so one compiled program serves a continuous batch of
    requests with heterogeneous sampling settings — the v2 engine's
    on-device multi-tick decode needs this; the reference samples host-side
    per request).

    ``rng=None`` compiles the pure-greedy program (no sort).  ``top_k <= 0``
    and ``top_p >= 1`` disable their filters per row.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        return greedy
    S, V = logits.shape
    lg = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: threshold at the k-th largest value (k<=0 -> keep all)
    k = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_lg, (k - 1)[:, None], axis=-1)
    lg = jnp.where(lg < kth, -jnp.inf, lg)
    # top-p on the top-k-filtered distribution (matches sample_logits'
    # sequential filter semantics) — re-sort so masked rows drop out
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_lg, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < jnp.clip(top_p, 0.0, 1.0)[:, None]
    kth_idx = jnp.maximum(jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1,
                          0)
    cutoff = jnp.take_along_axis(sorted_lg, kth_idx, axis=-1)
    lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    sampled = jax.random.categorical(rng, lg, axis=-1).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)
