"""ZeRO-Inference NVMe tier: stream layer weights from SSD per forward.

TPU-native re-design of the reference's NVMe weight path
(``runtime/swap_tensor/partitioned_param_swapper.py:37``
``AsyncPartitionedParameterSwapper`` feeding stage-3 inference —
"ZeRO-Inference": models whose weights exceed HBM+RAM generate by
streaming each layer's parameters from NVMe through the device).

Design: the transformer's repeated block makes every layer an identical
compiled program, so the engine

1. writes each layer's param subtree to one NVMe file at init (native
   AIO engine, ``io/csrc/aio.cpp``);
2. keeps only the small resident tree (embeddings, final norm, LM head)
   in device memory;
3. drives ONE jitted block function layer-by-layer per forward, with the
   AIO pool prefetching layer ``i+1`` from NVMe while the device runs
   layer ``i`` — the same host-side double buffering the optimizer
   swapper uses.  Device residency: resident tree + two layers.

Throughput is bounded by SSD bandwidth x model size per token batch —
the reference's economics (their Llama-70B numbers run batch 96 to
amortize each weight sweep); amortize with large batches.

Llama-family models (Llama / Mistral / Qwen2; per-token positions and a
uniform block) are supported.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


class NvmeWeightStore:
    """Per-layer param subtrees on NVMe, async-read with prefetch."""

    def __init__(self, nvme_path: str, layers: List[Any],
                 aio_block_size: int = 1 << 20, aio_thread_count: int = 8,
                 aio_queue_depth: int = 64, aio_use_odirect: bool = False):
        from deepspeed_tpu.io.aio import aio_handle

        os.makedirs(nvme_path, exist_ok=True)
        self.dir = nvme_path
        self.handle = aio_handle(block_size=aio_block_size,
                                 thread_count=aio_thread_count,
                                 queue_depth=aio_queue_depth,
                                 use_odirect=aio_use_odirect)
        self._layout = None            # [(path_key, shape, dtype, offset)]
        self.n_layers = len(layers)
        total = 0
        for i, tree in enumerate(layers):
            total += self._write_layer(i, tree)
        log_dist(f"ZeRO-Inference weight store: {self.n_layers} layers, "
                 f"{total / 1e9:.2f} GB at {nvme_path}", ranks=[0])

    def _fname(self, i: int) -> str:
        return os.path.join(self.dir, f"layer_{i:04d}.bin")

    def _write_layer(self, i: int, tree) -> int:
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        layout, off = [], 0
        bufs = []
        for kp, leaf in flat:
            arr = np.asarray(jax.device_get(leaf))
            layout.append((jax.tree_util.keystr(kp), arr.shape,
                           arr.dtype, off))
            bufs.append(arr)
            off += arr.nbytes
        if self._layout is None:
            self._layout = layout
            self._treedef = jax.tree_util.tree_structure(tree)
        else:
            assert [(k, s, d) for k, s, d, _ in layout] == \
                [(k, s, d) for k, s, d, _ in self._layout], (
                    f"layer {i} param layout differs — streaming needs a "
                    "uniform block")
        from deepspeed_tpu.io.aio import _pretruncate

        fname = self._fname(i)
        _pretruncate(fname, off, exact=False)
        ops = [self.handle.async_pwrite(
            np.ascontiguousarray(b), fname, lay[3], _truncate=False)
            for b, lay in zip(bufs, layout)]
        for op in ops:
            self.handle.wait(op)
        return off

    def start_read(self, i: int):
        """Begin the async read of layer ``i``; returns (ops, buffers)."""
        bufs = [np.empty(shape, dt) for _, shape, dt, _ in self._layout]
        ops = [self.handle.async_pread(b, self._fname(i), off)
               for b, (_, _, _, off) in zip(bufs, self._layout)]
        return ops, bufs

    def finish_read(self, started) -> Any:
        ops, bufs = started
        for op in ops:
            self.handle.wait(op)
        return jax.tree_util.tree_unflatten(self._treedef, bufs)


class NvmeWeightStreamingEngine:
    """Generate with layer weights resident on NVMe, not in HBM.

    ``model``: a Llama-family ``*ForCausalLM`` module (unrolled twin is
    built internally); ``params``: the full tree (host or device) —
    consumed into the store at init.
    """

    def __init__(self, model, params: Any, nvme_path: str,
                 max_batch_size: int = 8, max_out_tokens: int = 256,
                 aio_block_size: int = 1 << 20, aio_thread_count: int = 8,
                 aio_queue_depth: int = 64, aio_use_odirect: bool = False):
        import dataclasses

        from deepspeed_tpu.inference.common import (normalize_params,
                                                    unroll_scan_params)

        mcfg = getattr(model, "config", None)
        assert mcfg is not None and hasattr(mcfg, "rope_theta"), (
            "NVMe weight streaming supports the Llama family")
        self.cfg = dataclasses.replace(
            mcfg, decode=True, scan_layers=False,
            max_cache_len=max_out_tokens)
        from deepspeed_tpu.models import llama as _llama

        self._block_cls = _llama.LlamaBlock
        self._norm_cls = _llama.RMSNorm
        params = normalize_params(model, params,
                                  plain_model=type(model)(mcfg))
        if getattr(mcfg, "scan_layers", False):
            params = unroll_scan_params(params)
        L = self.cfg.num_hidden_layers
        layers = [params["model"][f"layers_{i}"] for i in range(L)]
        # resident tree: embeddings + final norm + head (the persistent
        # small params — reference persistence-threshold analogue)
        self.resident = {
            "embed": jnp.asarray(params["model"]["embed_tokens"]
                                 ["embedding"]),
            "norm": jax.tree_util.tree_map(jnp.asarray,
                                           params["model"]["norm"]),
            "head": jnp.asarray(params["lm_head"]["kernel"]),
        }
        self.store = NvmeWeightStore(nvme_path, layers,
                                     aio_block_size=aio_block_size,
                                     aio_thread_count=aio_thread_count,
                                     aio_queue_depth=aio_queue_depth,
                                     aio_use_odirect=aio_use_odirect)
        self.max_batch_size = max_batch_size
        self.max_out_tokens = max_out_tokens
        self._block_fn = None
        self._cache_shapes = None
        log_dist(
            "ZeRO-Inference: resident "
            f"{sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(self.resident)) / 1e6:.1f}"
            " MB on device; block weights stream from NVMe", ranks=[0])

    # -- compiled pieces --------------------------------------------------

    def _block(self):
        if self._block_fn is not None:
            return self._block_fn
        block = self._block_cls(self.cfg)

        def run(layer_params, cache, x, positions):
            out, vars_ = block.apply(
                {"params": layer_params, "cache": cache}, x, positions,
                mutable=["cache"])
            return out, vars_["cache"]

        self._block_fn = jax.jit(run, donate_argnums=(1,))
        return self._block_fn

    def _init_layer_cache(self, batch: int):
        block = self._block_cls(self.cfg)
        x = jnp.zeros((batch, 1, self.cfg.hidden_size), self.cfg.dtype)
        shapes = jax.eval_shape(
            lambda: block.init(jax.random.PRNGKey(0), x,
                               jnp.zeros((batch, 1), jnp.int32)))
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])

    # -- forward over streamed layers ------------------------------------

    def _embed(self, ids):
        return jnp.take(self.resident["embed"], ids, axis=0).astype(
            self.cfg.dtype)

    def _head(self, x):
        norm = self._norm_cls(self.cfg.rms_norm_eps, self.cfg.dtype)
        x = norm.apply({"params": self.resident["norm"]}, x)
        return x @ self.resident["head"].astype(self.cfg.dtype)

    def _forward(self, ids, positions, caches) -> Tuple[jax.Array, list]:
        """One streamed pass: embed -> L x (prefetch next; run block) ->
        head.  ``caches``: per-layer KV trees, threaded through."""
        L = self.store.n_layers
        x = self._embed(ids)
        block_fn = self._block()
        started = self.store.start_read(0)
        new_caches = list(caches)
        for i in range(L):
            layer_host = self.store.finish_read(started)
            if i + 1 < L:
                started = self.store.start_read(i + 1)   # overlap
            layer_dev = jax.tree_util.tree_map(jnp.asarray, layer_host)
            x, new_caches[i] = block_fn(layer_dev, new_caches[i], x,
                                        positions)
        return self._head(x), new_caches

    # -- public API -------------------------------------------------------

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Greedy generation with per-step weight streaming (the
        reference ZeRO-Inference loop: every token batch pays one full
        weight sweep — batch wide to amortize)."""
        ids = np.asarray(input_ids, np.int32)
        B, P = ids.shape
        assert B <= self.max_batch_size
        assert P + max_new_tokens <= self.max_out_tokens
        caches = [self._init_layer_cache(B)
                  for _ in range(self.store.n_layers)]
        positions = jnp.broadcast_to(jnp.arange(P), (B, P))
        logits, caches = self._forward(jnp.asarray(ids), positions, caches)
        out = [ids]
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        for t in range(max_new_tokens - 1):
            out.append(np.asarray(tok)[:, None])
            pos = jnp.full((B, 1), P + t, jnp.int32)
            logits, caches = self._forward(tok[:, None], pos, caches)
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            if eos_token_id is not None and bool(
                    np.all(np.asarray(tok) == eos_token_id)):
                break
        out.append(np.asarray(tok)[:, None])
        return np.concatenate(out, axis=1)

    def resident_bytes(self) -> int:
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(self.resident))
