from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                            load_inference_config)
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference

__all__ = ["DeepSpeedInferenceConfig", "load_inference_config",
           "InferenceEngine", "init_inference"]
