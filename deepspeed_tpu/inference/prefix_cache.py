"""Cross-request prefix cache: a hash index over the paged-KV pool.

The page table already decouples logical position from physical pages
(the PagedAttention insight); this module adds the sharing layer on
top — SGLang's RadixAttention observation that identical token-id
prefixes produce identical KV pages, so N requests re-sending the same
system prompt can all attend over ONE physical copy:

- token ids are chain-hashed at page granularity (vLLM's prefix-hash
  scheme: ``h_i = H(h_{i-1}, chunk_i)``), so a chunk's hash commits to
  the entire prefix before it, and page i of two sequences may only be
  shared when tokens ``[0, (i+1)*page_size)`` match exactly;
- every index entry holds one allocator reference on its page, keeping
  the page resident after the request that prefilled it finishes;
- lookups VERIFY stored token ids against the query chunk before a
  page is attached — a hash collision degrades to a miss, never to
  cross-request KV corruption;
- under pool pressure, least-recently-used entries whose page nobody
  else references are demoted to the tiered KV store (keyed by prefix
  hash, not request uid — a spilled prefix restores once for all
  waiters) or dropped.

The index is host-side bookkeeping only; the engine owns device copies
(COW) and the tier store.  Refcount rules live in
:class:`~deepspeed_tpu.inference.paged.PageAllocator`.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import (AbstractSet, Callable, FrozenSet, List, Optional,
                    Sequence, Tuple)

_EMPTY: FrozenSet[int] = frozenset()

# chain seed: hash of the empty prefix
ROOT_HASH = 0


def _chunk_hash(parent_hash: int, tokens: Tuple[int, ...]) -> int:
    """64-bit chain hash of one page-sized token chunk.  Module-level so
    adversarial tests can monkeypatch a colliding hash and prove that
    token-id verification — not hash uniqueness — is the safety
    contract."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent_hash.to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return int.from_bytes(h.digest(), "little")


class PrefixEntry:
    """One fully-prefilled page of a hashed prefix chain."""

    __slots__ = ("key", "parent", "tokens", "page", "state", "hits")

    def __init__(self, key: int, parent: int, tokens: Tuple[int, ...],
                 page: Optional[int]):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.page = page                    # physical page id when resident
        self.state = "resident" if page is not None else "spilled"
        self.hits = 0


class PrefixCacheIndex:
    """LRU index from chain hash -> :class:`PrefixEntry`.

    Holds one ``allocator`` reference per resident entry.  ``demote``
    and ``drop_spilled`` are engine-provided hooks (set after
    construction): ``demote(entry) -> bool`` moves a resident page's
    contents into the tiered store under the entry's tier key;
    ``drop_spilled(tier_key)`` deletes a demoted payload when its
    tombstone leaves the index.
    """

    def __init__(self, allocator, page_size: int, *,
                 max_entries: int = 1024, min_match_pages: int = 1):
        self.allocator = allocator
        self.page_size = page_size
        self.max_entries = max_entries
        self.min_match_pages = min_match_pages
        self._entries: "OrderedDict[int, PrefixEntry]" = OrderedDict()
        self.demote: Optional[Callable[[PrefixEntry], bool]] = None
        self.drop_spilled: Optional[Callable[[str], None]] = None
        # counters (engine folds these into serving_stages)
        self.lookups = 0
        self.hits = 0
        self.hit_pages = 0
        self.collisions = 0
        self.demotions = 0
        self.revivals = 0
        self.drops = 0

    # -- keys ---------------------------------------------------------------

    @staticmethod
    def tier_key(key: int) -> str:
        """Tiered-store key for a demoted prefix page — the prefix hash,
        NOT a request uid, so one restore serves every waiter."""
        return f"pfx-{key & (2 ** 64 - 1):016x}"

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: int) -> bool:
        return key in self._entries

    def get(self, key: int) -> Optional[PrefixEntry]:
        return self._entries.get(key)

    # -- lookup -------------------------------------------------------------

    def match(self, tokens: Sequence[int], *, touch: bool = True
              ) -> List[PrefixEntry]:
        """Longest verified chain prefix of ``tokens`` present in the
        index, as a list of entries (page i of the prefix at position
        i).  Only FULL pages participate; entries may be resident or
        spilled tombstones (the caller revives the latter).  Each
        entry's stored token ids are compared to the query chunk — a
        colliding hash with different tokens terminates the walk.
        ``touch=False`` keeps admission probes from perturbing LRU
        order (pipelined and unpipelined schedules must see the same
        index state)."""
        if touch:
            self.lookups += 1
        out: List[PrefixEntry] = []
        parent = ROOT_HASH
        page = self.page_size
        for lo in range(0, len(tokens) - page + 1, page):
            chunk = tuple(int(t) for t in tokens[lo:lo + page])
            key = _chunk_hash(parent, chunk)
            e = self._entries.get(key)
            if e is None or e.parent != parent:
                break
            if e.tokens != chunk:
                if touch:
                    self.collisions += 1
                break
            out.append(e)
            parent = key
        if len(out) < self.min_match_pages:
            return []
        if touch:
            for e in out:
                e.hits += 1
                self._entries.move_to_end(e.key)
            self.hits += 1
            self.hit_pages += len(out)
        return out

    # -- registration -------------------------------------------------------

    def register(self, parent: int, tokens: Sequence[int], page: int
                 ) -> int:
        """Record that resident ``page`` holds the KV for ``tokens``
        whose chain parent is ``parent``.  Takes one allocator ref on
        the page for a NEW entry; an existing entry with the same
        tokens is left canonical (the caller's private copy stays
        private).  Returns the chunk's chain hash."""
        chunk = tuple(int(t) for t in tokens)
        assert len(chunk) == self.page_size, (
            f"register needs one full page ({len(chunk)} tokens)")
        key = _chunk_hash(parent, chunk)
        e = self._entries.get(key)
        if e is not None:
            if e.tokens == chunk and e.parent == parent:
                if e.state == "spilled":
                    # a fresh resident copy supersedes the demoted
                    # payload: adopt the page, drop the tier entry
                    if self.drop_spilled is not None:
                        self.drop_spilled(self.tier_key(key))
                    e.page = page
                    e.state = "resident"
                    self.allocator.incref(page)
                    self.revivals += 1
                self._entries.move_to_end(key)
                return key
            # collision: different prefix hashed to the same key —
            # evict the old entry, the new registration wins
            self.collisions += 1
            self._drop(e)
        self._entries[key] = PrefixEntry(key, parent, chunk, page)
        self.allocator.incref(page)
        self._evict_overflow()
        return key

    def mark_spilled(self, e: PrefixEntry) -> None:
        """Entry's page was demoted to the tier store: drop the
        allocator ref, keep a tombstone so future matches revive it."""
        assert e.state == "resident"
        self.allocator.decref(e.page)
        e.page = None
        e.state = "spilled"
        self.demotions += 1

    def revive(self, e: PrefixEntry, page: int) -> None:
        """A spilled entry's payload was restored into fresh ``page``
        (caller already owns one ref for the index)."""
        assert e.state == "spilled"
        e.page = page
        e.state = "resident"
        self.revivals += 1

    # -- reclamation --------------------------------------------------------

    def reclaimable(self, exclude: AbstractSet[int] = _EMPTY) -> int:
        """Pages the index could hand back under pressure: resident
        entries nobody but the index references.  ``exclude`` holds
        entry keys a prospective admission is about to attach — they
        must not be counted as reclaimable for that same admission."""
        return sum(1 for e in self._entries.values()
                   if e.state == "resident" and e.key not in exclude
                   and self.allocator.refcount(e.page) == 1)

    def reclaim(self, n_pages: int,
                exclude: AbstractSet[int] = _EMPTY) -> int:
        """Free up to ``n_pages`` pool pages by demoting (or dropping)
        LRU resident entries whose page only the index holds.  Returns
        pages actually freed."""
        freed = 0
        for key in list(self._entries):
            if freed >= n_pages:
                break
            e = self._entries[key]
            if (e.state != "resident" or e.key in exclude
                    or self.allocator.refcount(e.page) != 1):
                continue
            if self.demote is not None and self.demote(e):
                self.mark_spilled(e)       # decref -> page back to free
            else:
                self._drop(e)
            freed += 1
        return freed

    def _drop(self, e: PrefixEntry) -> None:
        if e.state == "resident":
            self.allocator.decref(e.page)
        elif self.drop_spilled is not None:
            self.drop_spilled(self.tier_key(e.key))
        del self._entries[e.key]
        self.drops += 1

    def _evict_overflow(self) -> None:
        while len(self._entries) > self.max_entries:
            key = next(iter(self._entries))
            self._drop(self._entries[key])

    def clear(self) -> None:
        for key in list(self._entries):
            self._drop(self._entries[key])

    def stats(self) -> dict:
        resident = sum(1 for e in self._entries.values()
                       if e.state == "resident")
        return {
            "entries": len(self._entries),
            "resident_entries": resident,
            "spilled_entries": len(self._entries) - resident,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_pages": self.hit_pages,
            "hit_rate": round(self.hits / max(self.lookups, 1), 4),
            "collisions": self.collisions,
            "demotions": self.demotions,
            "revivals": self.revivals,
            "drops": self.drops,
        }
