"""Serving-side quantization: weight-only formats for the inference
engines.

TPU-native counterpart of the reference's inference quantization stack:
``csrc/fp_quantizer/quantize.cu`` (fp8/fp6 ``selective_dequant``),
``inference/v2/kernels/core_ops/cuda_linear/`` (FP6-LLM GEMM), and the
int8 ``replace_with_quantized_linear`` path.  Weights live in HBM in the
quantized format (int8 group-wise, fp8 e4m3, or packed fp6 e3m2 —
``ops/quantization.py``) and dequantize IN-JIT at use, where XLA fuses
the elementwise expansion into the consuming matmul's operand read — the
TPU equivalent of the reference's dequant-in-GEMM-prologue kernels.

KV-cache quantization (fp8/int8 paged pools with per-row-per-head
scales) lives in ``inference/paged.py`` — it is a storage-layout concern
of the blocked KV pool.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.quantization import (FP6Tensor, FP8Tensor,
                                            QuantizedTensor, dequantize,
                                            dequantize_fp6, dequantize_fp8,
                                            quantize, quantize_fp6,
                                            quantize_fp8)

WEIGHT_FORMATS = ("int8", "fp8", "fp6", "w8a8")

# matmul-bearing leaf names — norms/biases/scales stay high precision
# (the reference's policies quantize Linear/Embedding weights only)
_QUANT_LEAVES = frozenset(
    {"kernel", "embedding", "w1", "w2", "w3", "wi", "wo"})


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """Pytree wrapper for a quantized parameter: the payload/scale arrays
    are children (device_put/jit/donation all work), the layout metadata
    (format, original shape/dtype, group size) is STATIC aux data — the
    ops-level NamedTuples carry shape/dtype as pytree children, which
    breaks abstraction the moment they sit inside a params tree."""

    def __init__(self, fmt: str, arrays: Tuple[jax.Array, ...],
                 shape: Tuple[int, ...], dtype, group_size: int = 0):
        self.fmt = fmt
        self.arrays = tuple(arrays)
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.group_size = int(group_size)

    def tree_flatten(self):
        return self.arrays, (self.fmt, self.shape, str(self.dtype),
                             self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        fmt, shape, dtype, group_size = aux
        return cls(fmt, tuple(children), shape, dtype, group_size)

    @property
    def nbytes(self) -> int:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in self.arrays)


def _is_q(leaf) -> bool:
    return isinstance(leaf, QuantizedWeight)


def quantize_param_tree(params: Any, fmt: str, min_size: int = 1024,
                        group_size: int = 2048) -> Tuple[Any, int, int]:
    """Quantize every matmul-bearing leaf of ``params`` to ``fmt``.

    Returns ``(qtree, bytes_before, bytes_after)``; small leaves (<
    ``min_size`` elements) and non-matmul leaves pass through unchanged.
    ``group_size`` is the int8/fp6 blockwise-scale granularity
    (reference ``QuantizationConfig.group_size``); fp8 scales per
    tensor.

    ``fmt="w8a8"``: 2-D ``kernel`` leaves get PER-OUTPUT-CHANNEL
    symmetric int8 (scale constant along the contraction axis, so it
    factors out of an int8 x int8 MXU dot — the models consume these
    leaves natively through :func:`w8a8_dot_general`, the reference's
    W8A8 quantized-inference GEMM, ``csrc/quantization``); non-kernel
    matmul leaves (embeddings, stacked MoE experts) fall back to
    group-wise int8 with in-jit dequant.
    """
    assert fmt in WEIGHT_FORMATS, \
        f"quantize_weights={fmt!r}: expected one of {WEIGHT_FORMATS}"
    before = after = 0

    def q(path, leaf):
        nonlocal before, after
        before += leaf.size * leaf.dtype.itemsize
        name = str(getattr(path[-1], "key", path[-1]))
        if (leaf.ndim < 2 or leaf.size < min_size or
                name not in _QUANT_LEAVES):
            after += leaf.size * leaf.dtype.itemsize
            return leaf
        if fmt == "w8a8" and name == "kernel" and leaf.ndim == 2:
            s = jnp.max(jnp.abs(leaf.astype(jnp.float32)), axis=0)
            s = jnp.maximum(s, 1e-12) / 127.0
            v = jnp.clip(jnp.round(leaf.astype(jnp.float32) / s),
                         -127, 127).astype(jnp.int8)
            out = QuantizedWeight("w8a8", (v, s), leaf.shape, leaf.dtype)
        elif fmt in ("int8", "w8a8"):
            t = quantize(leaf, num_bits=8, group_size=group_size)
            out = QuantizedWeight("int8", (t.values, t.scale, t.offset),
                                  t.shape, t.dtype)
        elif fmt == "fp8":
            t = quantize_fp8(leaf)
            out = QuantizedWeight("fp8", (t.values, t.scale), t.shape,
                                  t.dtype)
        else:
            t = quantize_fp6(leaf, group_size=group_size)
            out = QuantizedWeight("fp6", (t.values, t.scale), t.shape,
                                  t.dtype, t.group_size)
        after += out.nbytes
        return out

    return (jax.tree_util.tree_map_with_path(q, params), before, after)


def dequantize_param_tree(qtree: Any, native_w8a8: bool = False) -> Any:
    """In-jit inverse of :func:`quantize_param_tree` (XLA fuses the
    expansion into consumers; quantized leaves never persist in HBM at
    full precision).  ``native_w8a8=True`` leaves "w8a8" leaves in place
    for a model whose Dense layers consume them through
    :func:`w8a8_dot_general` — the int8 payload then never expands to
    full precision at all."""

    def dq(leaf):
        if not isinstance(leaf, QuantizedWeight):
            return leaf
        if leaf.fmt == "w8a8":
            if native_w8a8:
                return leaf
            v, s = leaf.arrays
            return (v.astype(jnp.float32) * s).astype(leaf.dtype)
        if leaf.fmt == "int8":
            v, s, o = leaf.arrays
            return dequantize(QuantizedTensor(v, s, o, leaf.shape,
                                              leaf.dtype))
        if leaf.fmt == "fp8":
            v, s = leaf.arrays
            return dequantize_fp8(FP8Tensor(v, s, leaf.shape, leaf.dtype))
        v, s = leaf.arrays
        return dequantize_fp6(FP6Tensor(v, s, leaf.shape, leaf.dtype,
                                        leaf.group_size))

    return jax.tree_util.tree_map(dq, qtree, is_leaf=_is_q)


# ---------------------------------------------------------------------------
# Native W8A8 consumption (the reference's dequant-in-GEMM-prologue /
# W8A8 inference GEMMs, ``csrc/quantization`` + ``cuda_linear``): the
# model's Dense layers read the int8 payload DIRECTLY — activations
# dynamically quantize per row, the dot runs on the MXU's int8 path
# (int32 accumulation), and the two scales rescale the output.  Decode
# is weight-bandwidth-bound, so halving the weight bytes halves the
# decode floor — unlike tree-level dequant, which pays an extra
# full-precision materialization per dispatch.
# ---------------------------------------------------------------------------

def quant_promote_dtype(*args, dtype=None, **kw):
    """``nn.Dense.promote_dtype`` replacement: QuantizedWeight leaves
    pass through untouched (flax's default would jnp.asarray them)."""
    from flax.linen.dtypes import promote_dtype

    qs = [a if isinstance(a, QuantizedWeight) else None for a in args]
    proms = promote_dtype(*(None if q is not None else a
                            for q, a in zip(qs, args)), dtype=dtype, **kw)
    return [q if q is not None else p for q, p in zip(qs, proms)]


def w8a8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """``nn.Dense.dot_general`` replacement: int8 x int8 dot against a
    "w8a8" :class:`QuantizedWeight` with dynamic per-row activation
    scales; plain arrays fall through to ``lax.dot_general``."""
    if not isinstance(rhs, QuantizedWeight):
        return jax.lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type)
    assert rhs.fmt == "w8a8", rhs.fmt
    (lc, rc), (lb, rb) = dimension_numbers
    assert tuple(rc) == (0,) and not lb and not rb, (
        "w8a8 kernels only support Dense-style contractions")
    v, s = rhs.arrays
    sx = jnp.max(jnp.abs(lhs.astype(jnp.float32)), axis=-1,
                 keepdims=True) / 127.0
    # clip before the int8 cast (matching the weight branch): a NaN/inf
    # activation row would otherwise cast to undefined int8 values
    xq = jnp.clip(jnp.round(lhs.astype(jnp.float32) /
                            jnp.maximum(sx, 1e-12)),
                  -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, v, dimension_numbers,
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * s).astype(
        lhs.dtype if jnp.issubdtype(lhs.dtype, jnp.floating)
        else rhs.dtype)


def _dense_supports_promote_dtype() -> bool:
    import inspect

    import flax.linen as nn

    return "promote_dtype" in inspect.signature(nn.Dense).parameters


def _patch_flax_promote_dtype() -> None:
    """flax < 0.10.2 compat: ``nn.Dense`` has no ``promote_dtype``
    attribute there, and its module-level ``promote_dtype`` would
    ``jnp.asarray`` a :class:`QuantizedWeight` kernel.  Wrap that one
    function (idempotently) to pass quantized leaves through — plain
    arrays take the original path unchanged."""
    from flax.linen import dtypes as _dtypes
    from flax.linen import linear as _linear

    if getattr(_linear.promote_dtype, "_dstpu_quant_aware", False):
        return
    orig = _dtypes.promote_dtype

    def promote(*args, dtype=None, **kw):
        qs = [a if isinstance(a, QuantizedWeight) else None for a in args]
        if not any(q is not None for q in qs):
            return orig(*args, dtype=dtype, **kw)
        proms = orig(*(None if q is not None else a
                       for q, a in zip(qs, args)), dtype=dtype, **kw)
        return [q if q is not None else p for q, p in zip(qs, proms)]

    promote._dstpu_quant_aware = True
    _linear.promote_dtype = promote


def weight_quant_dense_kwargs(weight_quant: str):
    """``nn.Dense`` kwargs wiring native quantized-weight consumption
    into a model (the model zoo's ``cfg.weight_quant`` knob)."""
    if weight_quant in (None, "none"):
        return {}
    assert weight_quant == "w8a8", weight_quant
    if not _dense_supports_promote_dtype():
        _patch_flax_promote_dtype()
        return {"dot_general": w8a8_dot_general}
    return {"promote_dtype": quant_promote_dtype,
            "dot_general": w8a8_dot_general}
