"""Serving-side quantization: weight-only formats for the inference
engines.

TPU-native counterpart of the reference's inference quantization stack:
``csrc/fp_quantizer/quantize.cu`` (fp8/fp6 ``selective_dequant``),
``inference/v2/kernels/core_ops/cuda_linear/`` (FP6-LLM GEMM), and the
int8 ``replace_with_quantized_linear`` path.  Weights live in HBM in the
quantized format (int8 group-wise, fp8 e4m3, or packed fp6 e3m2 —
``ops/quantization.py``) and dequantize IN-JIT at use, where XLA fuses
the elementwise expansion into the consuming matmul's operand read — the
TPU equivalent of the reference's dequant-in-GEMM-prologue kernels.

KV-cache quantization (fp8/int8 paged pools with per-row-per-head
scales) lives in ``inference/paged.py`` — it is a storage-layout concern
of the blocked KV pool.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.quantization import (FP6Tensor, FP8Tensor,
                                            QuantizedTensor, dequantize,
                                            dequantize_fp6, dequantize_fp8,
                                            quantize, quantize_fp6,
                                            quantize_fp8)

WEIGHT_FORMATS = ("int8", "fp8", "fp6")

# matmul-bearing leaf names — norms/biases/scales stay high precision
# (the reference's policies quantize Linear/Embedding weights only)
_QUANT_LEAVES = frozenset(
    {"kernel", "embedding", "w1", "w2", "w3", "wi", "wo"})


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """Pytree wrapper for a quantized parameter: the payload/scale arrays
    are children (device_put/jit/donation all work), the layout metadata
    (format, original shape/dtype, group size) is STATIC aux data — the
    ops-level NamedTuples carry shape/dtype as pytree children, which
    breaks abstraction the moment they sit inside a params tree."""

    def __init__(self, fmt: str, arrays: Tuple[jax.Array, ...],
                 shape: Tuple[int, ...], dtype, group_size: int = 0):
        self.fmt = fmt
        self.arrays = tuple(arrays)
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.group_size = int(group_size)

    def tree_flatten(self):
        return self.arrays, (self.fmt, self.shape, str(self.dtype),
                             self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        fmt, shape, dtype, group_size = aux
        return cls(fmt, tuple(children), shape, dtype, group_size)

    @property
    def nbytes(self) -> int:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in self.arrays)


def _is_q(leaf) -> bool:
    return isinstance(leaf, QuantizedWeight)


def quantize_param_tree(params: Any, fmt: str, min_size: int = 1024,
                        group_size: int = 2048) -> Tuple[Any, int, int]:
    """Quantize every matmul-bearing leaf of ``params`` to ``fmt``.

    Returns ``(qtree, bytes_before, bytes_after)``; small leaves (<
    ``min_size`` elements) and non-matmul leaves pass through unchanged.
    ``group_size`` is the int8/fp6 blockwise-scale granularity
    (reference ``QuantizationConfig.group_size``); fp8 scales per
    tensor.
    """
    assert fmt in WEIGHT_FORMATS, \
        f"quantize_weights={fmt!r}: expected one of {WEIGHT_FORMATS}"
    before = after = 0

    def q(path, leaf):
        nonlocal before, after
        before += leaf.size * leaf.dtype.itemsize
        name = str(getattr(path[-1], "key", path[-1]))
        if (leaf.ndim < 2 or leaf.size < min_size or
                name not in _QUANT_LEAVES):
            after += leaf.size * leaf.dtype.itemsize
            return leaf
        if fmt == "int8":
            t = quantize(leaf, num_bits=8, group_size=group_size)
            out = QuantizedWeight("int8", (t.values, t.scale, t.offset),
                                  t.shape, t.dtype)
        elif fmt == "fp8":
            t = quantize_fp8(leaf)
            out = QuantizedWeight("fp8", (t.values, t.scale), t.shape,
                                  t.dtype)
        else:
            t = quantize_fp6(leaf, group_size=group_size)
            out = QuantizedWeight("fp6", (t.values, t.scale), t.shape,
                                  t.dtype, t.group_size)
        after += out.nbytes
        return out

    return (jax.tree_util.tree_map_with_path(q, params), before, after)


def dequantize_param_tree(qtree: Any) -> Any:
    """In-jit inverse of :func:`quantize_param_tree` (XLA fuses the
    expansion into consumers; quantized leaves never persist in HBM at
    full precision)."""

    def dq(leaf):
        if not isinstance(leaf, QuantizedWeight):
            return leaf
        if leaf.fmt == "int8":
            v, s, o = leaf.arrays
            return dequantize(QuantizedTensor(v, s, o, leaf.shape,
                                              leaf.dtype))
        if leaf.fmt == "fp8":
            v, s = leaf.arrays
            return dequantize_fp8(FP8Tensor(v, s, leaf.shape, leaf.dtype))
        v, s = leaf.arrays
        return dequantize_fp6(FP6Tensor(v, s, leaf.shape, leaf.dtype,
                                        leaf.group_size))

    return jax.tree_util.tree_map(dq, qtree, is_leaf=_is_q)
