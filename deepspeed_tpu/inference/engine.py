"""Inference engine (v1-equivalent).

TPU-native re-design of the reference ``InferenceEngine``
(``deepspeed/inference/engine.py:40``, entry ``deepspeed.init_inference``,
``deepspeed/__init__.py:291``).  The reference wraps an HF torch module,
injects fused CUDA kernel containers or AutoTP-shards it, optionally
captures a CUDA graph, and defers generation to HF ``generate``.  Here:

- kernel injection collapses: the flax models already run the fused
  Pallas/XLA ops (``replace_with_kernel_inject`` warns and no-ops);
- TP sharding is the same GSPMD story as training: param PartitionSpecs
  from flax metadata or AutoTP name rules, over the ``tensor`` mesh axis;
- the CUDA graph is the jit: prefill, decode step, and the whole generate
  loop (a ``lax.scan`` over decode steps with the KV cache as carry)
  compile into single XLA programs per shape;
- generation is native: greedy/temperature/top-k/top-p sampling fused into
  the loop (``inference/sampling.py``), KV cache per layer
  (``inference/kv_cache.py``);
- the token harvest is deferrable (the serving host-path pipeline,
  ``config.v2``): :meth:`InferenceEngine.generate_async` dispatches the
  fused prefill+decode program and returns a :class:`PendingGeneration`
  handle WITHOUT blocking on ``device_get`` — back-to-back calls overlap
  the next dispatch's host work with the previous call's device work, and
  the caller harvests when it actually needs tokens.  ``generate()`` is
  ``generate_async(...).result()``.  ``host_stats`` breaks the host path
  into plan/upload/dispatch/device/harvest per dispatch.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu.comm as dist
from deepspeed_tpu.inference.common import HostStageStats
from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                            load_inference_config)
from deepspeed_tpu.inference.kv_cache import init_cache
from deepspeed_tpu.inference.sampling import sample_logits
from deepspeed_tpu.utils.logging import log_dist, logger

_DTYPES = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
           "float16": jnp.float16, "fp16": jnp.float16,
           "float32": jnp.float32, "fp32": jnp.float32}


def init_inference(model: Any, config: Any = None, params: Any = None,
                   topology=None, rng: Optional[jax.Array] = None,
                   checkpoint: Any = None,
                   **kwargs) -> "InferenceEngine":
    """Create an :class:`InferenceEngine` (reference
    ``deepspeed.init_inference``, ``deepspeed/__init__.py:291``).

    ``model``: a flax causal-LM module returning ``[B, S, V]`` logits (or a
    ``(logits, aux)`` tuple, e.g. Mixtral).  If its ``config`` dataclass has
    a ``decode`` field, a decode-mode twin is constructed automatically.
    ``params``: trained parameters; randomly initialized when omitted
    (benchmarking).
    """
    cfg = load_inference_config(config, **kwargs)
    if checkpoint is not None:
        assert params is None, "pass checkpoint= or params=, not both"
        from deepspeed_tpu.module_inject import load_hf_checkpoint

        params = load_hf_checkpoint(model, checkpoint)
    return InferenceEngine(model, cfg, params=params, topology=topology,
                           rng=rng)


class PendingGeneration:
    """Deferred-harvest handle from :meth:`InferenceEngine.generate_async`.

    The fused decode program is already dispatched (the device runs
    asynchronously); :meth:`result` blocks on the ONE ``device_get`` and
    caches the numpy tokens.  :meth:`device_array` exposes the device
    buffer for callers chaining further device work (the bench harness's
    overlap loop) without ever paying the host copy."""

    def __init__(self, engine: "InferenceEngine", arr):
        self._engine = engine
        self._arr = arr
        self._result: Optional[np.ndarray] = None

    def device_array(self):
        return self._arr

    def ready(self) -> bool:
        """True when the tokens can be read without blocking (already
        harvested, or the device reports the buffer ready)."""
        if self._result is not None:
            return True
        try:
            return bool(self._arr.is_ready())
        except AttributeError:      # pragma: no cover - old jax arrays
            return True

    def result(self) -> np.ndarray:
        if self._result is None:
            st = self._engine.host_stats
            with st.stage("device"):
                st.blocking_gets += 1
                out = jax.device_get(self._arr)
            st.harvests += 1
            with st.stage("harvest"):
                self._result = np.asarray(out)
        return self._result


class InferenceEngine:
    def __init__(self, model, config: DeepSpeedInferenceConfig, params=None,
                 topology=None, rng: Optional[jax.Array] = None,
                 param_source=None):
        """``param_source``: zero-copy live parameter callable (the hybrid
        engine's RLHF path) — when set, params are NOT staged here; every
        forward/generate reads ``param_source()`` and any dtype cast
        happens in-graph (flax computes in the serving dtype)."""
        self.config = config
        self.dtype = _DTYPES[config.dtype]
        self.module = model                      # API parity with reference
        self._param_source = param_source
        self.host_stats = HostStageStats()
        self.v2 = config.v2      # serving host-path knobs (pipeline, ...)

        tp_size = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        dist.init_distributed()
        if topology is None:
            topology = (dist.initialize_mesh(tp=tp_size) if tp_size > 1
                        else dist.get_topology())
        else:
            dist.set_topology(topology)
        self.topology = topology
        self.mesh = topology.mesh

        # decode-mode twin of the model (KV cache threaded through attention)
        mcfg = getattr(model, "config", None)
        if (dataclasses.is_dataclass(mcfg) and
                any(f.name == "decode" for f in dataclasses.fields(mcfg))):
            # decode twins unroll the layer scan: flax scan restacks the
            # mutable cache per step (full-cache copies); unrolled layers
            # alias each cache independently — 3.8x decode on v5e.
            # Scan-stacked params convert in-jit (common.unroll_scan_params)
            self._unroll_params = bool(getattr(mcfg, "scan_layers", False))
            self._plain_model = (model if mcfg.dtype == self.dtype
                                 else type(model)(
                                     dataclasses.replace(mcfg,
                                                         dtype=self.dtype)))
            is_encoder = bool(getattr(mcfg, "is_encoder", False))
            has_cache = any(f.name == "max_cache_len"
                            for f in dataclasses.fields(mcfg))
            if not is_encoder and not has_cache:
                raise TypeError(
                    f"{type(mcfg).__name__} has a 'decode' field but no "
                    "'max_cache_len' and is not marked is_encoder=True — "
                    "decoder configs need max_cache_len for the KV "
                    "cache; encoder configs must set is_encoder")
            if has_cache and not is_encoder:
                # learned/rotary position tables bound usable positions;
                # clamp the cache so generate() can't run past them into
                # silently clamped embedding gathers
                pos_bound = (getattr(mcfg, "n_positions", None) or
                             getattr(mcfg, "max_position_embeddings", None))
                cache_len = (getattr(mcfg, "max_cache_len", 0) or
                             config.max_out_tokens)
                if pos_bound is not None and cache_len > pos_bound:
                    logger.warning(
                        f"max_out_tokens={cache_len} exceeds the model's "
                        f"position bound {pos_bound}; clamping the KV cache")
                    cache_len = pos_bound
                dcfg = dataclasses.replace(
                    mcfg, decode=True, dtype=self.dtype,
                    max_cache_len=cache_len, scan_layers=False)
                self._decode_model = type(model)(dcfg)
                self.max_cache_len = dcfg.max_cache_len
            else:
                # encoder families (BERT): forward()-only serving, the
                # reference's BertLayer injection scope — no KV cache,
                # generate() refuses below
                self._decode_model = None
                self.max_cache_len = 0
        else:
            raise TypeError(
                "init_inference needs a model whose config dataclass has a "
                "'decode' field (models/gpt2.py, models/llama.py, "
                "models/mixtral.py do)")

        # -- params: init if absent, cast to serving dtype, TP-shard -------
        from deepspeed_tpu.parallel import tensor_parallel as tp_lib

        if param_source is not None:
            self.params = None                   # live view, never staged
            self._generate_cache: Dict[Tuple, Any] = {}
            self._forward_fn = None
            self._cache_shapes: Dict[int, Any] = {}
            log_dist(f"InferenceEngine: dtype={config.dtype} tp={tp_size} "
                     f"max_cache_len={self.max_cache_len} "
                     "(live shared params)", ranks=[0])
            return
        from deepspeed_tpu.inference.common import normalize_params

        if params is None:
            log_dist("init_inference: params randomly initialized "
                     "(none provided)", ranks=[0])
        params = normalize_params(model, params, rng=rng,
                                  plain_model=self._plain_model)

        specs = None
        if tp_lib.has_partitioning(params):
            specs = tp_lib.extract_partition_specs({"params": params},
                                                   self.mesh.axis_names)
            specs = specs["params"]
            params = tp_lib.unbox_params(params)
        elif topology.tensor_parallel_size > 1:
            specs = tp_lib.auto_tp_specs(params,
                                         topology.tensor_parallel_size)
            log_dist("init_inference AutoTP: inferred tensor-parallel "
                     "sharding from parameter names", ranks=[0])

        def cast(x):
            x = jnp.asarray(x)
            return x.astype(self.dtype) if jnp.issubdtype(
                x.dtype, jnp.floating) else x

        params = jax.tree_util.tree_map(cast, params)
        self._wq = None
        if config.quant.enabled:
            # weight-only serving quantization (reference QuantizationConfig
            # -> replace_with_quantized_linear / FP6-LLM cuda_linear):
            # weights persist quantized, dequantize in-jit at use
            assert tp_size <= 1, (
                "quant.enabled does not compose with tensor-parallel "
                "serving yet")
            from deepspeed_tpu.inference.quantization import \
                quantize_param_tree

            self._wq = config.quant.qtype
            params, b0, b1 = quantize_param_tree(
                params, self._wq, group_size=config.quant.group_size)
            # quantized leaves are QuantizedWeight subtrees — the per-leaf
            # spec tree no longer lines up, and tp<=1 means replication
            # was the only placement anyway
            specs = None
            log_dist(f"init_inference weights -> {self._wq}: "
                     f"{b0 / 2**20:.1f} MiB -> {b1 / 2**20:.1f} MiB",
                     ranks=[0])
        from jax.sharding import NamedSharding, PartitionSpec as P

        if specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.tree_util.tree_map(jax.device_put, params,
                                            shardings)
        else:
            params = jax.device_put(params,
                                    NamedSharding(self.mesh, P()))
        self.params = params

        self._generate_cache: Dict[Tuple, Any] = {}
        self._forward_fn = None
        self._cache_shapes: Dict[int, Any] = {}
        log_dist(f"InferenceEngine: dtype={config.dtype} tp={tp_size} "
                 f"max_cache_len={self.max_cache_len}", ranks=[0])

    # ------------------------------------------------------------------

    def _logits(self, out):
        from deepspeed_tpu.inference.common import logits_of

        return logits_of(out)

    def _zero_cache_shapes(self, B: int, S: int):
        if B not in self._cache_shapes:
            self._cache_shapes[B] = jax.tree_util.tree_map(
                lambda l: (l.shape, l.dtype),
                init_cache(self._decode_model, np.zeros((B, S), np.int32)))
        return self._cache_shapes[B]

    def forward(self, input_ids, attention_mask=None) -> jax.Array:
        """Full-sequence logits (reference ``InferenceEngine.forward``,
        ``engine.py:554``) — no KV cache, one fused program.

        ``attention_mask`` ([B, S], 1 = real token): padding mask for
        encoder families serving mixed-length padded batches (BERT —
        without it every query attends to pad keys); decoder models are
        causal and ignore it."""
        if self._forward_fn is None:
            model = self._plain_model
            wq = getattr(self, "_wq", None)
            takes_mask = "attention_mask" in inspect.signature(
                model.__call__).parameters

            def fwd(params, ids, mask):
                if wq:
                    from deepspeed_tpu.inference.quantization import \
                        dequantize_param_tree

                    params = dequantize_param_tree(params)
                kw = {"attention_mask": mask} if (takes_mask and
                                                  mask is not None) else {}
                return self._logits(model.apply({"params": params}, ids,
                                                **kw))

            self._forward_fn = jax.jit(fwd, static_argnames=())
            self._forward_takes_mask = takes_mask
        if attention_mask is not None and not self._forward_takes_mask:
            if not getattr(self, "_mask_warned", False):
                self._mask_warned = True
                logger.warning("forward(): this model takes no "
                               "attention_mask; ignoring it "
                               "(warning once)")
            attention_mask = None
        mask = (None if attention_mask is None
                else jnp.asarray(attention_mask))
        return self._forward_fn(self._live_params(),
                                jnp.asarray(input_ids), mask)

    def _live_params(self):
        if self._param_source is not None:
            p = self._param_source()
            return p["params"] if isinstance(p, dict) and "params" in p \
                else p
        return self.params

    __call__ = forward

    # ------------------------------------------------------------------

    def _build_generate(self, B: int, P: int, max_new: int, do_sample: bool,
                        temperature: float, top_k: int, top_p: float,
                        eos_id: Optional[int]):
        model = self._decode_model
        logits_of = self._logits
        cache_shapes = self._zero_cache_shapes(B, P)

        def sample(lg, rng):
            return sample_logits(lg, rng, do_sample=do_sample,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)

        unroll = self._unroll_params
        wq = getattr(self, "_wq", None)

        def gen(params, prompt, rng):
            if wq:
                from deepspeed_tpu.inference.quantization import \
                    dequantize_param_tree

                params = dequantize_param_tree(params)
            if unroll:
                from deepspeed_tpu.inference.common import \
                    unroll_scan_params

                params = unroll_scan_params(params)
            cache = jax.tree_util.tree_map(
                lambda sd: jnp.zeros(*sd), cache_shapes,
                is_leaf=lambda x: isinstance(x, tuple))
            out, vars_ = model.apply(
                {"params": params, "cache": cache}, prompt,
                positions=jnp.arange(P), mutable=["cache"])
            cache = vars_["cache"]
            rng, sub = jax.random.split(rng)
            tok = sample(logits_of(out)[:, -1], sub)
            done = (jnp.zeros((B,), bool) if eos_id is None
                    else tok == eos_id)

            def step(carry, _):
                cache, tok, pos, rng, done = carry
                out, vars_ = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    positions=pos[None, None], mutable=["cache"])
                rng, sub = jax.random.split(rng)
                nxt = sample(logits_of(out)[:, -1], sub)
                if eos_id is not None:
                    nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                    done = done | (nxt == eos_id)
                return (vars_["cache"], nxt, pos + 1, rng, done), nxt

            (_, _, _, _, done), toks = jax.lax.scan(
                step, (cache, tok, jnp.int32(P), rng, done),
                length=max_new - 1)
            new = jnp.concatenate([tok[:, None], toks.T], axis=1)
            return jnp.concatenate([prompt, new.astype(prompt.dtype)],
                                   axis=1)

        return jax.jit(gen)

    def generate_async(self, input_ids, max_new_tokens: int = 128,
                       do_sample: bool = False, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 1.0,
                       eos_token_id: Optional[int] = None,
                       rng: Optional[jax.Array] = None
                       ) -> PendingGeneration:
        """Dispatch the fused prefill+decode program and return WITHOUT
        waiting for the device — the deferred-harvest half of
        :meth:`generate`.  The returned :class:`PendingGeneration`
        blocks only when ``result()`` is called, so a serving loop can
        keep dispatching (the host path of call k+1 overlaps the device
        work of call k) and harvest tokens in bulk."""
        if self._decode_model is None:
            raise TypeError(
                "generate() needs a decoder model; encoder families "
                "(BERT) serve through forward() only")
        st = self.host_stats
        with st.stage("upload"):
            st.meta_uploads += 1
            prompt = jnp.asarray(np.asarray(input_ids), jnp.int32)
        assert prompt.ndim == 2, "input_ids must be [batch, prompt_len]"
        B, P = prompt.shape
        if self.config.max_batch_size and B > self.config.max_batch_size:
            raise ValueError(f"batch {B} exceeds max_batch_size "
                             f"{self.config.max_batch_size}")
        assert max_new_tokens >= self.config.min_out_tokens, (
            f"max_new_tokens {max_new_tokens} < min_out_tokens "
            f"{self.config.min_out_tokens}")
        assert P + max_new_tokens <= self.max_cache_len, (
            f"prompt {P} + max_new_tokens {max_new_tokens} exceeds "
            f"max_cache_len {self.max_cache_len} (raise max_out_tokens)")
        with st.stage("plan"):
            key = (B, P, max_new_tokens, do_sample, temperature, top_k,
                   top_p, eos_token_id)
            if key not in self._generate_cache:
                self._generate_cache[key] = self._build_generate(
                    B, P, max_new_tokens, do_sample, temperature, top_k,
                    top_p, eos_token_id)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        with st.stage("dispatch"):
            st.dispatches += 1
            arr = self._generate_cache[key](self._live_params(), prompt,
                                            rng)
        st.ticks += max_new_tokens
        return PendingGeneration(self, arr)

    def generate(self, input_ids, max_new_tokens: int = 128,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None) -> np.ndarray:
        """Autoregressive generation: prefill + ``max_new_tokens`` fused
        decode steps in one compiled program per (batch, prompt-len,
        max-new) shape.  Returns ``[B, P + max_new_tokens]`` token ids.
        (``generate_async`` is the non-blocking variant.)"""
        return self.generate_async(
            input_ids, max_new_tokens=max_new_tokens, do_sample=do_sample,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, rng=rng).result()

    def serving_stages(self) -> Dict[str, Any]:
        """Per-dispatch host-path breakdown + ``host_bound_fraction``
        (see :class:`~deepspeed_tpu.inference.common.HostStageStats`)."""
        return self.host_stats.serving_stages()
