"""Inference config.

Mirrors the reference ``DeepSpeedInferenceConfig``
(``deepspeed/inference/config.py``) with the same JSON key names where the
knob exists on TPU.  GPU-only knobs (kernel injection, CUDA graphs) are
accepted and warned about: under XLA every jitted function IS a captured
graph and the fused kernels are the Pallas/XLA ops the models already use,
so there is nothing to inject.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import ConfigModel
from deepspeed_tpu.utils.logging import logger


class InferenceTPConfig(ConfigModel):
    """``tensor_parallel`` subtree (reference ``DeepSpeedTPConfig``)."""

    enabled: bool = True
    tp_size: int = 1


class QuantConfig(ConfigModel):
    """Weight quantization for serving (reference ``QuantizationConfig``):
    int8 group-wise via ops/quantization.py; weights are stored quantized
    and dequantized on the fly in the matmul's prologue."""

    enabled: bool = False
    qtype: str = "int8"          # "int8" | "fp8" | "fp6"
    group_size: int = 128


class SpeculationConfig(ConfigModel):
    """``v2.speculation`` subtree: speculative decoding on the ragged
    engine's decode-block path.

    ``mode``: ``off`` | ``ngram`` (prompt-lookup drafting from the
    sequence's own emitted+prompt tokens — no second model) | ``draft``
    (a small same-vocab family member proposes; the engine needs the
    draft module+params passed programmatically, ``draft_model`` here
    names a model-zoo preset for CLIs/benches to construct).
    ``k``: drafted tokens per speculative tick — the target scores all
    ``k+1`` positions in ONE ragged dispatch, so one weight pass
    amortizes over up to ``k+1`` emitted tokens.
    ``ngram``: the lookup n-gram length for ``mode=ngram``."""

    mode: str = "off"
    k: int = 4
    ngram: int = 3
    draft_model: Optional[str] = None

    @model_validator(mode="after")
    def _check(self):
        if self.mode not in ("off", "ngram", "draft"):
            raise ValueError(
                f"speculation.mode must be off|ngram|draft, got "
                f"{self.mode!r}")
        if self.k < 1:
            raise ValueError("speculation.k must be >= 1")
        if self.ngram < 1:
            raise ValueError("speculation.ngram must be >= 1")
        return self


class KVTieringConfig(ConfigModel):
    """``v2.kv_tiering`` subtree: host-RAM + NVMe spill tiers for the
    paged-KV pool.

    When the pool can't grow a scheduled sequence, the engine spills
    the coldest non-scheduled sequence's pages to host RAM
    (device_get into page-aligned pinned buffers) instead of evicting
    it — restore is a page upload, not a re-prefill.  Host RAM
    overflows into NVMe through the hardened bucketed AIO path
    (qd-128, optional O_DIRECT, fallocate), every spilled page is
    digested (``resilience/sdc.py``) at spill and verified on restore,
    and NVMe->host prefetch for predicted next-scheduled sequences
    runs under the decode block.

    ``host_pages`` / ``nvme_pages``: per-tier budgets in KV pages
    (0 disables that tier).  ``nvme_dir``: spill directory (required
    when ``nvme_pages > 0``).  ``use_odirect``: O_DIRECT spill files
    (off by default — dev containers often spill to tmpfs, where
    O_DIRECT is unsupported).  ``prefetch``: overlap NVMe->host
    restores with decode blocks.  ``verify``: digest-check every
    restored page (re-read heals transient flips; persistent
    corruption quarantines the page and the session re-prefills
    loudly).  Tiering requires ``kv_reserve="on_demand"`` — spill
    tiers ARE the on-demand model's overflow story."""

    enabled: bool = False
    host_pages: int = 256
    nvme_pages: int = 0
    nvme_dir: Optional[str] = None
    use_odirect: bool = False
    prefetch: bool = True
    verify: bool = True
    checksum: str = "sum64"
    max_reread: int = 2
    # -- degraded mode: nvme_fail_threshold hard NVMe failures since
    # the last clean probe (EIO at write submit / cold read, or a
    # quarantine of an NVMe-backed payload) trip the tier offline —
    # spills fall back host-only, parked NVMe payloads fold to
    # re-prefill.  While offline, every probe_every blocked spills run
    # a write/read/verify revival probe; a clean probe re-arms the tier
    nvme_fail_threshold: int = 3
    probe_every: int = 8
    # -- partial residency (long context): a live sequence's page list
    # may split between HBM-resident pages and parked pages.  The first
    # ``sink_pages`` (attention sinks) and the most recent
    # ``window_pages`` stay resident; full middle groups of
    # ``chunk_pages`` demote through the host/NVMe tiers and stream
    # back through a fixed staging buffer during the chunked attention
    # scan.  ``prefetch_lookahead`` bounds how many waiting spilled
    # sessions the pipeline's restore-prefetch scans ahead (the old
    # hardcoded islice(waiting, 8)).  ``long_context`` arms the
    # partial-residency admission path (a request whose full KV exceeds
    # HBM is admitted as long as its resident window fits HBM and its
    # total fits the combined tiers).
    long_context: bool = False
    sink_pages: int = 1
    window_pages: int = 8
    chunk_pages: int = 4
    prefetch_lookahead: int = 8

    @model_validator(mode="after")
    def _check(self):
        if self.host_pages < 0 or self.nvme_pages < 0:
            raise ValueError("kv_tiering tier budgets must be >= 0")
        if self.enabled and self.host_pages == 0 and self.nvme_pages == 0:
            raise ValueError(
                "kv_tiering.enabled needs a nonzero host_pages or "
                "nvme_pages budget")
        if self.nvme_pages > 0 and not self.nvme_dir:
            raise ValueError(
                "kv_tiering.nvme_pages > 0 requires kv_tiering.nvme_dir")
        if self.max_reread < 0:
            raise ValueError("kv_tiering.max_reread must be >= 0")
        if self.nvme_fail_threshold < 1:
            raise ValueError(
                "kv_tiering.nvme_fail_threshold must be >= 1")
        if self.probe_every < 1:
            raise ValueError("kv_tiering.probe_every must be >= 1")
        if self.sink_pages < 1:
            raise ValueError("kv_tiering.sink_pages must be >= 1")
        if self.window_pages < 1:
            raise ValueError("kv_tiering.window_pages must be >= 1")
        if self.chunk_pages < 1:
            raise ValueError("kv_tiering.chunk_pages must be >= 1")
        if self.prefetch_lookahead < 1:
            raise ValueError("kv_tiering.prefetch_lookahead must be >= 1")
        if self.long_context and not self.enabled:
            raise ValueError(
                "kv_tiering.long_context requires kv_tiering.enabled — "
                "partial residency parks middle pages in the spill tiers")
        from deepspeed_tpu.resilience.sdc import CHECKSUM_ALGOS

        if self.checksum not in CHECKSUM_ALGOS:
            raise ValueError(
                f"kv_tiering.checksum must be one of {CHECKSUM_ALGOS}, "
                f"got {self.checksum!r}")
        return self


class PrefixCacheConfig(ConfigModel):
    """``v2.prefix_cache`` subtree: cross-request KV sharing over the
    paged pool.

    Token-id chunks are chain-hashed at page granularity; a new
    request's prefill attaches read-only to every fully-matched page
    already resident (refcounted, copy-on-write on first divergent
    write) and computes only the non-cached suffix.  Stored token ids
    are verified before attach, so a hash collision is a miss, never a
    wrong share.

    ``max_index_entries``: LRU bound on index entries (each holds one
    page reference while resident).  ``min_match_pages``: shortest
    prefix worth attaching (shorter matches prefill normally).
    ``include_generated``: also register pages completed during decode
    at request teardown — more reuse for multi-turn traffic, but those
    pages were written by the decode-block program, whose KV bits are
    not guaranteed identical to the fused prefill program's, so
    bit-parity vs cache-off is only contracted while this is off."""

    enabled: bool = False
    max_index_entries: int = 1024
    min_match_pages: int = 1
    include_generated: bool = False

    @model_validator(mode="after")
    def _check(self):
        if self.max_index_entries < 1:
            raise ValueError("prefix_cache.max_index_entries must be >= 1")
        if self.min_match_pages < 1:
            raise ValueError("prefix_cache.min_match_pages must be >= 1")
        return self


class ControlConfig(ConfigModel):
    """``v2.control`` subtree: the closed-loop autotuner.

    ``enabled`` arms the online controller on the engine's host loop
    (``DSTPU_CONTROL=0`` force-disarms regardless).  ``interval`` is
    engine steps per controller tick.  ``settle`` ticks pass between a
    hill-climb probe and its judgment; a relative objective change
    inside ``±hysteresis`` is noise (quiet revert), below it is a
    regression (revert + oscillation-guard bookkeeping: more than
    ``guard_reverts`` regressions on one knob within ``guard_window``
    ticks freezes that knob for ``freeze`` ticks).  ``cooldown`` ticks
    block re-probing a just-reverted knob.  ``objective`` names the
    signal to maximize (prefix ``-`` to minimize).  ``profile`` points
    at a per-host profile file or directory that seeds knob values at
    construction (fingerprint-checked; a foreign host's profile is
    ignored)."""

    enabled: bool = False
    interval: int = 8
    settle: int = 2
    hysteresis: float = 0.05
    cooldown: int = 4
    guard_window: int = 16
    guard_reverts: int = 2
    freeze: int = 32
    smooth: float = 1.0
    objective: str = "throughput"
    profile: Optional[str] = None

    @model_validator(mode="after")
    def _check(self):
        for name in ("interval", "settle", "guard_window",
                     "guard_reverts", "freeze"):
            if getattr(self, name) < 1:
                raise ValueError(f"control.{name} must be >= 1")
        if self.cooldown < 0:
            raise ValueError("control.cooldown must be >= 0")
        if self.hysteresis < 0:
            raise ValueError("control.hysteresis must be >= 0")
        if not 0.0 < self.smooth <= 1.0:
            raise ValueError("control.smooth must be in (0, 1]")
        if not self.objective.lstrip("-"):
            raise ValueError("control.objective must name a signal")
        return self


class InferenceV2Config(ConfigModel):
    """``v2`` subtree: the serving host-path pipeline knobs.

    ``pipeline`` (default ON) runs the ragged engine's decode steady
    state as a software pipeline — metadata pinned on device, host
    planning overlapped with device work, tokens harvested every
    ``harvest_interval`` decode blocks with at most ``async_depth``
    blocks in flight.  ``pipeline=False`` preserves the unpipelined
    host loop exactly (one blocking harvest + fresh metadata upload per
    dispatch) and is the bit-identical parity reference.  The v1 engine
    consumes the same subtree for its deferred-harvest
    ``generate_async`` path."""

    pipeline: bool = True
    async_depth: int = 2
    harvest_interval: int = 4
    # KV pool storage format: "none" keeps full-width pages; "int8" /
    # "fp8" (alias "fp8_e4m3") persist 1-byte pages with per-(row, head)
    # fp32 scales, read dequant-free by the quantized attention variants
    # (ops/ragged_paged_quant.py on TPU, the gathered-pages XLA
    # reference elsewhere) — the pool is never materialized full-width.
    kv_cache_dtype: str = "none"
    speculation: SpeculationConfig = Field(
        default_factory=SpeculationConfig)
    kv_tiering: KVTieringConfig = Field(default_factory=KVTieringConfig)
    prefix_cache: PrefixCacheConfig = Field(
        default_factory=PrefixCacheConfig)
    control: ControlConfig = Field(default_factory=ControlConfig)
    # SLO objectives ("ttft_ms_p99 <= 150"-style strings) fed at reap
    # time; serving_stages()["slo"] reports the rolling budget burn.
    # Empty = no objectives.
    slo: List[str] = Field(default_factory=list)
    # Tail-based trace sampling 1-in-N (0 = off unless the env var
    # DSTPU_TRACE_SAMPLE arms it); breaching/erroring requests always
    # promote when sampling is armed.
    trace_sample: int = 0

    @model_validator(mode="after")
    def _positive(self):
        if self.async_depth < 1:
            raise ValueError("async_depth must be >= 1")
        if self.harvest_interval < 1:
            raise ValueError("harvest_interval must be >= 1")
        if self.kv_cache_dtype not in ("none", "int8", "fp8", "fp8_e4m3"):
            raise ValueError(
                "kv_cache_dtype must be none|int8|fp8|fp8_e4m3, got "
                f"{self.kv_cache_dtype!r}")
        if self.trace_sample < 0:
            raise ValueError("trace_sample must be >= 0")
        from deepspeed_tpu.telemetry.slo import parse_objective
        for spec in self.slo:
            parse_objective(spec)      # raises ValueError on a bad spec
        return self


class DeepSpeedInferenceConfig(ConfigModel):
    """Top-level inference config (``deepspeed.init_inference`` arg)."""

    dtype: str = "bfloat16"                 # bfloat16 | float16 | float32
    tensor_parallel: InferenceTPConfig = Field(
        default_factory=InferenceTPConfig, alias="tp")
    max_out_tokens: int = 1024              # KV-cache length bound
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = False
    enable_cuda_graph: bool = False
    max_batch_size: int = 0                 # 0 = unbounded (shape-compiled)
    quant: QuantConfig = Field(default_factory=QuantConfig)
    v2: InferenceV2Config = Field(default_factory=InferenceV2Config)
    # reference knobs accepted for config compat, consumed elsewhere
    replace_method: str = "auto"
    checkpoint: Optional[str] = None

    @model_validator(mode="after")
    def _warn_gpu_only(self):
        if self.replace_with_kernel_inject:
            logger.warning(
                "replace_with_kernel_inject=True is a no-op on TPU: the "
                "models already run fused Pallas/XLA kernels; AutoTP-style "
                "sharding is applied regardless")
        if self.enable_cuda_graph:
            logger.warning(
                "enable_cuda_graph is a no-op on TPU: every jitted "
                "function is a captured XLA program")
        return self


def load_inference_config(
        config: Union[None, Dict[str, Any], DeepSpeedInferenceConfig],
        **kwargs) -> DeepSpeedInferenceConfig:
    if isinstance(config, DeepSpeedInferenceConfig):
        return config
    merged = dict(config or {})
    merged.update(kwargs)
    return DeepSpeedInferenceConfig(**merged)
