"""KV cache for autoregressive decode.

TPU-native re-design of the reference's inference KV-cache machinery
(v1: ``csrc/transformer/inference/includes/inference_context.h`` workspace
slabs + per-layer K/V pointers; v2: blocked ragged KV in
``inference/v2/ragged/``).  Here the cache is an explicit flax ``"cache"``
variable collection: one ``[B, Hkv, max_len, Dh]`` buffer pair per attention
layer (stacked ``[L, ...]`` under the model's ``nn.scan``), updated in place
with ``dynamic_update_slice`` and threaded functionally through the jitted
generate loop — no pointer arithmetic, no allocator; XLA double-buffers the
donated cache.

Dense rectangular batches only (every sequence shares one length); the
ragged/continuous-batching engine (FastGen equivalent) builds on top.

The cache layout is TIME-MAJOR (``[max_len, B, H, D]`` per layer): a
decode step's write is a whole leading-dim slice (full trailing tiles),
the alias-friendly orientation for the scan carry.

Decode models run with UNROLLED layers (the engines build their decode
twins with ``scan_layers=False`` and convert stacked params in-jit,
``inference/common.unroll_scan_params``): flax's scan-over-layers
restacks the mutable cache collection every decode step — profiled at
~2.4ms/step of full-cache copies on a 302MB GPT-2 cache (v5e) — while
unrolled layers keep one independently-aliased cache per layer.
Measured: 5.0k -> 19.3k decode tokens/s (GPT-2 125M, bs32, v5e).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def update_kv_cache(mdl, k: jax.Array, v: jax.Array, max_len: int,
                    write_positions: jax.Array = None):
    """Append this call's K/V ``[B, Hkv, S, Dh]`` to the layer's cache.

    Returns ``(k_full, v_full, start)`` where the full buffers are
    TIME-MAJOR ``[max_len, B, Hkv, Dh]`` and ``start`` is the write
    offset (number of tokens cached before this call).  Call inside an
    attention module with ``mutable=["cache"]`` applies; ``model.init``
    creates zeroed buffers.

    Time-major layout is load-bearing for decode throughput: a step's
    write is a WHOLE leading-dim slice (full trailing tiles), so the
    dynamic-update-slice aliases the scan carry in place — the
    seq-inner layout forced XLA into per-step full-cache copies
    (~2.4ms/step for a 302MB GPT-2 cache on v5e, profiled).

    ``write_positions``: optional [B] PER-SEQUENCE write offsets — the
    ragged/continuous-batching path (FastGen v2), where each slot sits at
    its own length.  The scalar ``cache_index`` then only tracks the max
    offset for bookkeeping; masking is the reader's job (positions-aware
    ``cached_attention``).
    """
    B, Hkv, S, Dh = k.shape
    assert S <= max_len, (
        f"chunk of {S} tokens exceeds the {max_len}-slot cache; "
        "dynamic_update_slice would clamp and silently corrupt it")
    ck = mdl.variable("cache", "cached_key", jnp.zeros,
                      (max_len, B, Hkv, Dh), k.dtype)
    cv = mdl.variable("cache", "cached_value", jnp.zeros,
                      (max_len, B, Hkv, Dh), v.dtype)
    ci = mdl.variable("cache", "cache_index",
                      lambda: jnp.zeros((), jnp.int32))
    k_tm = k.transpose(2, 0, 1, 3)                 # [S, B, Hkv, Dh]
    v_tm = v.transpose(2, 0, 1, 3)
    if write_positions is not None:
        wp = write_positions.astype(jnp.int32).reshape(B)

        def row_write(buf, kk, st):
            # per-sequence column: buf [max_len, Hkv, Dh], kk [S, Hkv, Dh]
            return jax.lax.dynamic_update_slice(buf, kk, (st, 0, 0))

        ck.value = jax.vmap(row_write, in_axes=(1, 1, 0),
                            out_axes=1)(ck.value, k_tm, wp)
        cv.value = jax.vmap(row_write, in_axes=(1, 1, 0),
                            out_axes=1)(cv.value, v_tm, wp)
        start = ci.value
        ci.value = jnp.maximum(ci.value, jnp.max(wp) + S)
        return ck.value, cv.value, start
    start = ci.value
    ck.value = jax.lax.dynamic_update_slice(ck.value, k_tm,
                                            (start, 0, 0, 0))
    cv.value = jax.lax.dynamic_update_slice(cv.value, v_tm,
                                            (start, 0, 0, 0))
    ci.value = start + S
    return ck.value, cv.value, start


def cached_attention(q: jax.Array, k_full: jax.Array, v_full: jax.Array,
                     q_positions: jax.Array, window=None,
                     k_bias: jax.Array = None,
                     scale: float = None) -> jax.Array:
    """Attention of ``q`` [B, H, S, Dh] against the TIME-MAJOR cache
    buffers [L, B, Hkv, Dh], masking key slots beyond each query's
    absolute position.  ``q_positions``: [S] or [B, S] absolute
    positions.  ``window``: Mistral-style sliding window — key slots
    more than ``window-1`` behind the query are masked too.  ``k_bias``:
    per-head additive score bias over key SLOTS, shape [H, L] — ALiBi
    (BLOOM) reduces to this because its per-query shift is constant
    along each softmax row.  Used for decode steps (S=1) and ragged
    chunked prefill; full prefill attends within its chunk via the
    normal causal kernels.  ``scale``: score multiplier (default
    1/sqrt(Dh); GPT-Neo passes 1.0 — that family trains UNscaled).
    """
    B, H, S, Dh = q.shape
    L, Hkv = k_full.shape[0], k_full.shape[2]
    if Hkv != H:                                   # GQA: expand KV heads
        rep = H // Hkv
        k_full = jnp.repeat(k_full, rep, axis=2)
        v_full = jnp.repeat(v_full, rep, axis=2)
    if scale is None:
        scale = 1.0 / np.sqrt(Dh)
    att = jnp.einsum("bhsd,lbhd->bhsl", q, k_full) * scale
    if k_bias is not None:
        att = att + k_bias[None, :, None, :].astype(att.dtype)
    qpos = q_positions if q_positions.ndim == 2 else q_positions[None]
    kpos = jnp.arange(L)[None, None, None, :]
    mask = kpos <= qpos[:, None, :, None]
    if window is not None:
        mask = mask & (kpos > qpos[:, None, :, None] - window)
    att = jnp.where(mask, att.astype(jnp.float32), jnp.float32(-1e30))
    p = jax.nn.softmax(att, axis=-1).astype(v_full.dtype)
    return jnp.einsum("bhsl,lbhd->bhsd", p, v_full)


def init_cache(model, example_ids: np.ndarray, positions=None):
    """Zeroed cache pytree for ``model`` (decode-mode config) shaped for
    ``example_ids`` [B, S], computed without materializing params."""
    import numpy as _np

    ids = jnp.asarray(_np.zeros(_np.asarray(example_ids).shape, _np.int32))

    def _init():
        kw = {} if positions is None else {"positions": positions}
        return model.init(jax.random.PRNGKey(0), ids, **kw)

    shapes = jax.eval_shape(_init)
    assert "cache" in shapes, (
        "model has no 'cache' collection — construct it with a decode=True "
        "config (inference engine does this automatically)")
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
