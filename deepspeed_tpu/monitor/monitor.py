"""Experiment monitors.

Re-creation of ``deepspeed/monitor/monitor.py:30`` (``MonitorMaster`` fanning
out to TensorBoard / W&B / CSV writers).  Events are ``(name, value, step)``
tuples written at gradient-accumulation boundaries by the engine.
"""
from __future__ import annotations

import csv
import os
from typing import Any, List, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: List[Event]) -> None:  # pragma: no cover
        raise NotImplementedError


class CSVMonitor(Monitor):
    """``csv_monitor`` config subtree (reference ``csv_monitor.py:12``).

    Per-series file handles stay open across ``write_events`` calls
    (one ``open()`` per series for the process's lifetime, not one per
    event — a serving-health flush emits dozens of series per step).
    Rows are flushed per call so concurrent readers see them; ``close``
    releases the handles.
    """

    def __init__(self, config):
        super().__init__(config)
        self.output_path = getattr(config, "output_path", "") or "./csv_monitor"
        self.job_name = getattr(config, "job_name", "DeepSpeedTPUJobName")
        self._files = {}                   # series name -> (handle, writer)
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def _writer(self, name: str):
        ent = self._files.get(name)
        if ent is None:
            fname = os.path.join(self.output_path, self.job_name,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            f = open(fname, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name])
            ent = self._files[name] = (f, w)
        return ent

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        touched = set()
        for name, value, step in events:
            f, w = self._writer(name)
            w.writerow([step, value])
            touched.add(name)
        for name in touched:
            self._files[name][0].flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files = {}

    def __del__(self):  # best-effort: rows are already flushed per call
        self.close()


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(
                    getattr(config, "output_path", "") or "./runs",
                    getattr(config, "job_name", "DeepSpeedTPUJobName"))
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"TensorBoard unavailable ({e}); disabled")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled or self.writer is None:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        if self.enabled:
            try:
                import wandb

                wandb.init(project=getattr(config, "project", "deepspeed_tpu"),
                           group=getattr(config, "group", None))
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabled")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self._wandb.log({name: value}, step=step)


class CometMonitor(Monitor):
    """``comet`` config subtree (reference ``monitor/comet.py:23``
    CometMonitor): stream to a comet_ml experiment, throttling each
    metric name to every ``samples_log_interval`` samples."""

    def __init__(self, config):
        super().__init__(config)
        self.samples_log_interval = int(
            getattr(config, "samples_log_interval", 100) or 100)
        self._last_logged = {}
        self.experiment = None
        if self.enabled:
            try:
                import comet_ml

                self.experiment = comet_ml.start(
                    api_key=getattr(config, "api_key", None),
                    project=getattr(config, "project", None),
                    workspace=getattr(config, "workspace", None),
                    experiment_key=getattr(config, "experiment_key", None),
                    mode=getattr(config, "mode", None),
                    online=getattr(config, "online", None))
                name = getattr(config, "experiment_name", None)
                if name:
                    self.experiment.set_name(name)
            except Exception as e:
                logger.warning(f"comet_ml unavailable ({e}); disabled")
                self.enabled = False

    def _needs_logging(self, name: str, step: int) -> bool:
        last = self._last_logged.get(name)
        if last is not None and step - last < self.samples_log_interval:
            return False
        self._last_logged[name] = step
        return True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled or self.experiment is None:
            return
        for name, value, step in events:
            if self._needs_logging(name, step):
                self.experiment.log_metric(name=name, value=value,
                                           step=step)


class MonitorMaster(Monitor):
    """Fan-out to every enabled writer; only process 0 writes."""

    def __init__(self, monitor_config):
        self.tb = TensorBoardMonitor(monitor_config.tensorboard)
        self.csv = CSVMonitor(monitor_config.csv_monitor)
        self.wandb = WandbMonitor(monitor_config.wandb)
        self.comet = CometMonitor(getattr(monitor_config, "comet", None))
        self.enabled = (self.tb.enabled or self.csv.enabled or
                        self.wandb.enabled or self.comet.enabled)

    def close(self) -> None:
        """Release writer resources (the CSV monitor's open per-series
        handles; TB flushes per write already)."""
        self.csv.close()

    def write_events(self, events: List[Event]) -> None:
        import jax

        if jax.process_index() != 0:
            return
        for m in (self.tb, self.csv, self.wandb, self.comet):
            if m.enabled:
                m.write_events(events)

    def write_sdc_health(self, sdc_counters: dict, step: int) -> None:
        """Surface the swap path's silent-data-corruption counters
        (``NvmeOptimizerSwapper.sdc_counters`` — cumulative detection /
        re-read-recovery / quarantine totals).  A host with flaky
        DRAM/NVMe shows up as a climbing ``Sdc/mismatches`` series long
        before it would have surfaced as unexplained loss drift."""
        self.write_events([(f"Sdc/{name}", float(value), step)
                           for name, value in sorted(sdc_counters.items())])

    def write_serving_health(self, serving_stages: dict,
                             step: int) -> None:
        """Surface a serving engine's host-path breakdown
        (``engine.serving_stages()`` — per-dispatch
        plan/upload/dispatch/device/harvest ms plus
        ``host_bound_fraction``) as ``Serving/*`` series.  A serving
        fleet whose ``Serving/host_bound_fraction`` climbs toward 1.0
        is wasting its accelerators on host scheduling — the signal the
        pipelined host path exists to drive down.  One-level sub-dicts
        (the ``speculation`` acceptance breakdown, the ``kv_tiering``
        spill/restore counters) flatten to ``Serving/<group>/<name>``
        series — a falling ``Serving/speculation/acceptance_rate``
        means the draft has stopped earning its keep, and a climbing
        ``Serving/kv_tiering/quarantined`` flags a host whose spill
        media is corrupting parked KV pages."""
        events = []
        for name, value in sorted(serving_stages.items()):
            if isinstance(value, dict):
                events += [(f"Serving/{name}/{k}", float(v), step)
                           for k, v in sorted(value.items())
                           if isinstance(v, (int, float))]
            elif isinstance(value, (int, float)):
                events.append((f"Serving/{name}", float(value), step))
        self.write_events(events)

    def write_metrics(self, registry: Any = None, step: int = 0) -> None:
        """Surface the telemetry :class:`MetricsRegistry` as
        ``Metrics/*`` series: counters and gauges by value, histograms
        as ``_count``/``_sum``/``_p50``/``_p99`` scalars (the registry's
        ``scalar_summary()`` view).  ``registry`` defaults to the
        process singleton; a dict is accepted for pre-flattened views.
        The SLO burn per objective rides along when an ``SLOSet`` is
        attached to the registry — ``Metrics/slo/<objective>_burn_rate``
        crossing 1.0 is the page-the-operator signal."""
        if registry is None:
            from deepspeed_tpu.telemetry.metrics import metrics as registry
        summary = (dict(registry) if isinstance(registry, dict)
                   else registry.scalar_summary())
        events = [(f"Metrics/{name}", float(value), step)
                  for name, value in sorted(summary.items())
                  if isinstance(value, (int, float))]
        slo = getattr(registry, "slo", None)
        if slo is not None:
            events += [(f"Metrics/slo/{k}", float(v), step)
                       for k, v in sorted(slo.flat_summary().items())
                       if isinstance(v, (int, float))]
        self.write_events(events)

    def write_comm_health(self, straggler_report: dict, step: int) -> None:
        """Surface the cross-rank straggler report
        (``comm.straggler_report()``) as metric events: per-op latency
        spread plus the named straggler rank (-1 when no rank cleared
        the naming thresholds).  A real slow rank shows up as a
        persistent nonnegative ``straggler_rank`` series."""
        events: List[Event] = []
        for op, rec in sorted(straggler_report.items()):
            rank = rec.get("straggler_rank")
            events.append((f"Comm/{op}/straggler_rank",
                           float(-1 if rank is None else rank), step))
            events.append((f"Comm/{op}/straggler_spread_ms",
                           float(rec.get("spread_ms", 0.0)), step))
        if events:
            self.write_events(events)
