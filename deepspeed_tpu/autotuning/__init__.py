from deepspeed_tpu.autotuning.autotuner import Autotuner, ModelInfo
from deepspeed_tpu.autotuning.scheduler import (ExperimentScheduler,
                                                GridSearchTuner,
                                                ModelBasedTuner,
                                                RandomTuner, expand_space,
                                                make_subprocess_runner,
                                                tune_space)

__all__ = ["Autotuner", "ModelInfo", "ExperimentScheduler",
           "GridSearchTuner", "RandomTuner", "ModelBasedTuner",
           "expand_space", "make_subprocess_runner", "tune_space"]
