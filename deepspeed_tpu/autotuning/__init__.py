from deepspeed_tpu.autotuning.autotuner import Autotuner, ModelInfo

__all__ = ["Autotuner", "ModelInfo"]
