"""Autotuning orchestration: experiment scheduler + search tuners.

TPU-native re-design of the reference orchestration tier
(``autotuning/scheduler.py ResourceManager`` — subprocess experiment
launches with result scraping, ``tuner/base_tuner.py BaseTuner``,
``tuner/index_based_tuner.py GridSearchTuner/RandomTuner``,
``tuner/model_based_tuner.py ModelBasedTuner`` + XGBoost cost model).

- :class:`ExperimentScheduler` runs each candidate ds_config in a FRESH
  python subprocess (``exp_runner`` below): a config that OOMs, fails to
  compile, or wedges the TPU runtime kills its own interpreter, not the
  tuner — the reference's reason for subprocess isolation, plus the TPU
  twist that a poisoned client/tunnel often cannot recover in-process.
  Failures are quarantined as records with the error string.
- Tuners search a ``tuning_space`` dict-of-lists (e.g. zero stage,
  micro-batch, remat, offload).  ``ModelBasedTuner`` fits a ridge
  regression on the numeric config features (the XGBoost rank model
  collapses to closed-form least squares — no GPU tree library on the
  image, and the spaces are hundreds of points, not millions) and
  evaluates the predicted-best configs each round with epsilon random
  exploration.
"""
from __future__ import annotations

import copy
import itertools
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from numbers import Number
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# tuning space -> experiment list (reference autotuning/utils.py
# get_all_configs)
# ---------------------------------------------------------------------------

def _set_path(cfg: Dict, dotted: str, value) -> None:
    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def expand_space(base_config: Dict[str, Any],
                 tuning_space: Dict[str, Sequence]) -> List[Dict[str, Any]]:
    """Cartesian product of a {dotted.key: [values]} space applied onto
    ``base_config`` — one ds_config per point."""
    keys = list(tuning_space)
    configs = []
    for combo in itertools.product(*(tuning_space[k] for k in keys)):
        cfg = copy.deepcopy(base_config)
        for k, v in zip(keys, combo):
            _set_path(cfg, k, v)
        cfg["_tuning_point"] = dict(zip(keys, combo))
        configs.append(cfg)
    return configs


def config_features(cfg: Dict[str, Any]) -> List[float]:
    """Numeric feature vector from a config's tuning point (the
    reference flattens the whole ds_config; the tuning point is the part
    that varies)."""
    import zlib

    feats = []
    for _, v in sorted(cfg.get("_tuning_point", {}).items()):
        if isinstance(v, bool):
            feats.append(float(v))
        elif isinstance(v, Number):
            feats.append(float(v))
        else:
            # stable across interpreters (hash() is salted, which would
            # break seed reproducibility of the cost model)
            feats.append(float(zlib.crc32(str(v).encode()) % 97))
    return feats


# ---------------------------------------------------------------------------
# subprocess experiment scheduler (reference ResourceManager)
# ---------------------------------------------------------------------------

def record_experiment_metrics(metric_val: Optional[float],
                              seconds: float) -> None:
    """Mirror one experiment record into the MetricsRegistry.

    The JSON sidecar (``exps_dir`` / ``Autotuner.records``) used to be
    the only sink, so sweeps were invisible to ``trace_summarize
    --metrics`` and the flight-dump header.  Registering here puts
    experiment counts, wall seconds, and the running metric value in
    every registry export — including the flight dump's embedded
    metrics block — for free."""
    from deepspeed_tpu.telemetry.metrics import metrics as _metrics

    if not _metrics.enabled:
        return
    status = "ok" if metric_val is not None else "error"
    _metrics.counter(
        "dstpu_autotune_experiments_total",
        "Autotuning experiments by outcome",
        labels=("status",)).labels(status=status).inc()
    _metrics.histogram(
        "dstpu_autotune_experiment_seconds",
        "Wall seconds per autotuning experiment").observe(
            float(seconds))
    if metric_val is not None:
        _metrics.gauge(
            "dstpu_autotune_last_metric",
            "Most recent successful experiment's metric value").set(
                float(metric_val))


@dataclass
class Experiment:
    exp_id: int
    ds_config: Dict[str, Any]
    metric_val: Optional[float] = None
    error: Optional[str] = None
    seconds: float = 0.0
    record: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.metric_val is not None


class ExperimentScheduler:
    """Run experiments through ``runner`` with quarantine.

    ``runner`` defaults to :func:`subprocess_runner`-style isolation via
    ``make_subprocess_runner``; inject a callable ``(ds_config) ->
    float`` for in-process measurement (unit tests, CPU sweeps).
    """

    def __init__(self, runner: Callable[[Dict], float],
                 exps_dir: Optional[str] = None):
        self.runner = runner
        self.exps_dir = exps_dir
        self.finished: List[Experiment] = []
        self._next = itertools.count()

    def run_experiments(self, configs: List[Dict[str, Any]]
                        ) -> List[Experiment]:
        out = []
        for cfg in configs:
            exp = Experiment(exp_id=next(self._next),
                             ds_config=copy.deepcopy(cfg))
            t0 = time.perf_counter()
            try:
                exp.metric_val = float(self.runner(cfg))
            except Exception as e:           # quarantined, tuner continues
                exp.error = f"{type(e).__name__}: {e}"
                logger.info(f"autotuning exp {exp.exp_id} quarantined: "
                            f"{exp.error[:200]}")
            exp.seconds = time.perf_counter() - t0
            exp.record = {"exp_id": exp.exp_id,
                          "tuning_point": cfg.get("_tuning_point", {}),
                          "metric_val": exp.metric_val,
                          "error": exp.error,
                          "seconds": round(exp.seconds, 3)}
            record_experiment_metrics(exp.metric_val, exp.seconds)
            self.finished.append(exp)
            if self.exps_dir:
                os.makedirs(self.exps_dir, exist_ok=True)
                path = os.path.join(self.exps_dir,
                                    f"exp_{exp.exp_id}.json")
                with open(path, "w") as f:
                    json.dump({"ds_config": exp.ds_config,
                               **exp.record}, f, indent=2)
            out.append(exp)
        return out


def make_subprocess_runner(factory: str, steps: int = 3,
                           timeout: float = 600.0,
                           python: Optional[str] = None,
                           env: Optional[Dict[str, str]] = None
                           ) -> Callable[[Dict], float]:
    """Isolated measurement: each config runs in a fresh interpreter via
    ``python -m deepspeed_tpu.autotuning.exp_runner`` (reference
    ResourceManager launching the user script with ``--autotuning run``).

    ``factory``: ``"pkg.module:fn"`` importable in the subprocess;
    ``fn()`` must return ``(model, batch_fn)`` where ``batch_fn(global
    _batch_size)`` yields a training batch.  OOM / compile failure /
    hang (timeout) surface as exceptions here and quarantine upstream.
    """

    def run(ds_config: Dict[str, Any]) -> float:
        with tempfile.TemporaryDirectory(prefix="dstpu_autotune_") as td:
            cfg_path = os.path.join(td, "config.json")
            out_path = os.path.join(td, "result.json")
            cfg = {k: v for k, v in ds_config.items()
                   if k != "_tuning_point"}
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            cmd = [python or sys.executable, "-m",
                   "deepspeed_tpu.autotuning.exp_runner",
                   "--config", cfg_path, "--factory", factory,
                   "--out", out_path, "--steps", str(steps)]
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout,
                                  env={**os.environ, **(env or {})})
            if proc.returncode != 0 or not os.path.exists(out_path):
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"experiment subprocess failed (rc={proc.returncode}): "
                    f"{tail[-500:]}")
            with open(out_path) as f:
                return float(json.load(f)["metric_val"])

    return run


# ---------------------------------------------------------------------------
# tuners (reference tuner/{base,index_based,model_based}_tuner.py)
# ---------------------------------------------------------------------------

class BaseTuner:
    def __init__(self, configs: List[Dict[str, Any]],
                 scheduler: ExperimentScheduler):
        self.pool = list(configs)
        self.scheduler = scheduler
        self.best: Optional[Experiment] = None

    def has_next(self) -> bool:
        return bool(self.pool)

    def next_batch(self, sample_size: int) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def update(self, exps: List[Experiment]) -> None:
        pass

    def tune(self, sample_size: int = 1, n_trials: int = 1000,
             early_stopping: Optional[int] = None) -> Optional[Experiment]:
        """Reference ``BaseTuner.tune``: batched evaluation with optional
        no-improvement early stop (counted in experiments)."""
        i = 0
        best_at = 0
        while i < n_trials and self.has_next():
            batch = self.next_batch(sample_size)
            exps = self.scheduler.run_experiments(batch)
            improved = False
            for e in exps:
                if e.ok and (self.best is None or
                             e.metric_val > self.best.metric_val):
                    self.best = e
                    improved = True
            i += len(exps)
            if improved:
                # count from AFTER the improving batch, else any
                # sample_size >= early_stopping stops immediately
                best_at = i
            self.update(exps)
            if early_stopping is not None and i - best_at >= early_stopping:
                logger.info(f"autotuning early stop at {i} experiments "
                            f"(no improvement in {early_stopping})")
                break
        return self.best


class GridSearchTuner(BaseTuner):
    def next_batch(self, sample_size: int) -> List[Dict[str, Any]]:
        batch, self.pool = (self.pool[:sample_size],
                            self.pool[sample_size:])
        return batch


class RandomTuner(BaseTuner):
    def __init__(self, configs, scheduler, seed: int = 0):
        super().__init__(configs, scheduler)
        self._rng = np.random.default_rng(seed)

    def next_batch(self, sample_size: int) -> List[Dict[str, Any]]:
        n = min(sample_size, len(self.pool))
        idx = self._rng.choice(len(self.pool), size=n, replace=False)
        batch = [self.pool[i] for i in idx]
        for i in sorted(idx, reverse=True):
            self.pool.pop(i)
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided search: ridge regression over the tuning-point
    features predicts the metric; each round evaluates the predicted
    best configs, with ``explore_ratio`` random picks (reference
    ModelBasedTuner's XGBoost rank model + 0.2 random exploration)."""

    INIT_NUM = 2

    def __init__(self, configs, scheduler, seed: int = 0,
                 explore_ratio: float = 0.2):
        super().__init__(configs, scheduler)
        self._rng = np.random.default_rng(seed)
        self.explore_ratio = explore_ratio
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._ok_vals: List[float] = []
        self._init_left = min(self.INIT_NUM, len(self.pool))

    def _predict(self) -> np.ndarray:
        X = np.asarray([config_features(c) for c in self.pool], np.float64)
        if len(self._ok_vals) < 2:
            # need 2+ REAL observations before fitting: an all-failure
            # start would train the ridge purely on synthetic penalty
            # values whose scale says nothing about the metric
            return self._rng.standard_normal(len(self.pool))
        A = np.asarray(self._X, np.float64)
        y = np.asarray(self._y, np.float64)
        mu, sd = A.mean(0), A.std(0) + 1e-9

        def design(M):
            Mn = (M - mu) / sd
            # quadratic basis: tuning surfaces (throughput vs batch,
            # stage) are concave with interior optima a linear model
            # would extrapolate past
            return np.c_[Mn, Mn ** 2, np.ones(len(M))]

        An = design(A)
        w = np.linalg.lstsq(An.T @ An + 1e-3 * np.eye(An.shape[1]),
                            An.T @ y, rcond=None)[0]
        return design(X) @ w

    def next_batch(self, sample_size: int) -> List[Dict[str, Any]]:
        batch = []
        for _ in range(min(sample_size, len(self.pool))):
            if self._init_left > 0 or \
                    self._rng.random() < self.explore_ratio:
                i = int(self._rng.integers(len(self.pool)))
                self._init_left = max(self._init_left - 1, 0)
            else:
                i = int(np.argmax(self._predict()))
            batch.append(self.pool.pop(i))
        return batch

    def update(self, exps: List[Experiment]) -> None:
        for e in exps:
            feats = config_features(e.ds_config)
            self._X.append(feats)
            # failures train the model too: a fixed penalty one unit
            # below the worst REAL observation steers the search away
            # from the infeasible region (tracked separately — deriving
            # the floor from _y would cascade past penalties downward)
            if e.ok:
                self._ok_vals.append(e.metric_val)
                self._y.append(e.metric_val)
            else:
                floor = (min(self._ok_vals) if self._ok_vals else 0.0)
                self._y.append(floor - 1.0)


TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


def tune_space(base_config: Dict[str, Any],
               tuning_space: Dict[str, Sequence],
               runner: Callable[[Dict], float],
               tuner: str = "model_based",
               sample_size: int = 1, n_trials: int = 1000,
               early_stopping: Optional[int] = None,
               exps_dir: Optional[str] = None,
               seed: int = 0) -> Optional[Experiment]:
    """One-call orchestration: expand the space, pick a tuner, run."""
    configs = expand_space(base_config, tuning_space)
    sched = ExperimentScheduler(runner, exps_dir=exps_dir)
    cls = TUNERS[tuner]
    kw = {} if cls is GridSearchTuner else {"seed": seed}
    t = cls(configs, sched, **kw)
    best = t.tune(sample_size=sample_size, n_trials=n_trials,
                  early_stopping=early_stopping)
    if best is not None:
        logger.info(f"autotuning best: {best.record}")
        from deepspeed_tpu.telemetry.metrics import metrics as _metrics
        if _metrics.enabled and best.metric_val is not None:
            _metrics.gauge(
                "dstpu_autotune_best_metric",
                "Best metric value found by the last sweep").set(
                    float(best.metric_val))
    return best
