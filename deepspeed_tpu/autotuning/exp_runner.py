"""Single-experiment subprocess entry (reference: the training script
relaunched by the autotuner's ResourceManager with ``--autotuning run``).

Builds the user's model via the ``--factory`` import path, runs a few
timed ``train_batch`` steps under the candidate ds_config, and writes
``{"metric_val": samples_per_sec}`` to ``--out``.  Any failure (OOM,
compile error, bad config) exits nonzero — the parent quarantines it.
"""
from __future__ import annotations

import argparse
import importlib
import json
import time


def _load_factory(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    assert fn_name, f"--factory must be 'pkg.module:fn', got {spec!r}"
    return getattr(importlib.import_module(mod_name), fn_name)


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True)
    p.add_argument("--factory", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--steps", type=int, default=3)
    args = p.parse_args(argv)

    import jax

    import deepspeed_tpu

    with open(args.config) as f:
        ds_config = json.load(f)
    model, batch_fn = _load_factory(args.factory)()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=ds_config, example_batch=batch_fn(1),
        rng=jax.random.PRNGKey(0))
    batch = batch_fn(engine.config.train_batch_size)
    engine.train_batch(batch=batch)             # compile
    t0 = time.perf_counter()
    loss = None
    for _ in range(args.steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    with open(args.out, "w") as f:
        json.dump({"metric_val": engine.config.train_batch_size / dt,
                   "seconds_per_step": dt}, f)


if __name__ == "__main__":
    main()
