"""Autotuner: discover the best ZeRO stage + micro-batch configuration.

TPU-native re-design of the reference autotuner
(``autotuning/autotuner.py:42 Autotuner``: memory-model stage pruning
``:278``, micro-batch search ``:851``, experiment records ``:708``,
``write_optimal_config:1075``).  The reference launches subprocess
experiment sweeps and scrapes metrics; on TPU two things collapse the
cost:

- **model info is free**: parameter counts come from ``jax.eval_shape``
  (no profile run), and
- **memory probes are compile-only**: ``jit(...).lower().compile()``
  reports XLA's exact per-device buffer usage without executing a step —
  an OOM shows up as a compile-time estimate, not a crashed run.

The tuning loop mirrors the reference strategy: rank ZeRO stages by the
Adam memory model (``:278`` formulas), prune stages whose instantiation
memory cannot fit, then for each surviving stage search micro-batch
sizes (doubling sweep, like the reference's min/max probe + list sweep),
measure each candidate with the injected ``runner`` (by default: build a
real engine and time ``train_batch``), and keep records.  ``tune()``
returns the best config; ``write_optimal_config`` saves it.
"""
from __future__ import annotations

import copy
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

ADAM_BYTES_PER_PARAM_FP32 = 8        # two fp32 moments
MASTER_BYTES_PER_PARAM = 4


@dataclass
class ModelInfo:
    num_params: int
    hidden_size: int = 0
    num_layers: int = 0

    @staticmethod
    def from_model(model, example_batch, rng=None) -> "ModelInfo":
        import jax
        import numpy as np

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        shapes = jax.eval_shape(
            lambda: model.init({"params": rng, "dropout": rng},
                               example_batch))
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(shapes))
        return ModelInfo(num_params=n)


class Autotuner:
    """``Autotuner(model_info, base_config, runner).tune()`` -> best
    (ds_config, metric).  ``runner(ds_config) -> samples_per_sec`` (or
    any higher-is-better metric); raise/return None for OOM/failure."""

    def __init__(self, model_info: ModelInfo, base_config: Dict[str, Any],
                 runner: Optional[Callable[[Dict], Optional[float]]] = None,
                 num_chips: Optional[int] = None,
                 hbm_bytes: Optional[float] = None,
                 metric: str = "throughput"):
        self.model_info = model_info
        self.base_config = copy.deepcopy(base_config)
        at = dict(self.base_config.pop("autotuning", {}))
        self.tuner_config = at
        self.metric_name = at.get("metric", metric)
        self.fast = bool(at.get("fast", True))
        self.max_mbs_cap = int(at.get("max_train_micro_batch_size_per_gpu",
                                      1024))
        self.start_mbs = int(at.get("min_train_micro_batch_size_per_gpu",
                                    1))
        self.stages = at.get("zero_stages", [0, 1, 2, 3])
        self.runner = runner or self._default_runner
        import jax

        self.num_chips = num_chips or len(jax.devices())
        self.hbm_bytes = hbm_bytes or self._detect_hbm()
        self.records: List[Dict[str, Any]] = []
        self.best: Optional[Tuple[Dict, float]] = None

    # -- hardware/memory model -----------------------------------------

    def _detect_hbm(self) -> float:
        import jax

        d = jax.devices()[0]
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        return float(stats.get("bytes_limit", 16e9))

    def instantiation_memory(self, zero_stage: int,
                             fp16: Optional[bool] = None) -> float:
        """Reference ``get_instantiation_memory_required_per_gpu:278``:
        Adam memory model per chip (params + grads + optimizer states,
        divided by the shards each stage introduces)."""
        n = self.model_info.num_params
        low_prec = fp16 if fp16 is not None else self._low_precision()
        params = n * (2 if low_prec else 4)
        grads = n * (2 if low_prec else 4)
        # master copy + both moments when training in low precision
        optimizer = n * ((MASTER_BYTES_PER_PARAM +
                          ADAM_BYTES_PER_PARAM_FP32) if low_prec
                         else ADAM_BYTES_PER_PARAM_FP32)
        shards = max(self.num_chips, 1)
        if zero_stage >= 1:
            optimizer /= shards
        if zero_stage >= 2:
            grads /= shards
        if zero_stage >= 3:
            params /= shards
        return params + grads + optimizer

    def _low_precision(self) -> bool:
        return bool(self.base_config.get("fp16", {}).get("enabled") or
                    self.base_config.get("bf16", {}).get("enabled"))

    def memory_fits(self, zero_stage: int, margin: float = 0.85) -> bool:
        return self.instantiation_memory(zero_stage) < \
            self.hbm_bytes * margin

    # -- experiment generation + search --------------------------------

    def _candidate_stages(self) -> List[int]:
        user = self.base_config.get("zero_optimization", {}).get("stage")
        stages = [user] if user is not None else list(self.stages)
        fits = [s for s in stages if self.memory_fits(s)]
        dropped = sorted(set(stages) - set(fits))
        if dropped:
            logger.info(f"autotuning: pruned zero stages {dropped} "
                        "(instantiation memory exceeds HBM)")
        # prefer lighter-comm stages first (reference tuning order)
        return sorted(fits)

    def _config_for(self, stage: int, mbs: int) -> Dict[str, Any]:
        cfg = copy.deepcopy(self.base_config)
        cfg.setdefault("zero_optimization", {})["stage"] = stage
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg.pop("train_batch_size", None)
        cfg.setdefault("gradient_accumulation_steps", 1)
        return cfg

    def _measure(self, stage: int, mbs: int) -> Optional[float]:
        cfg = self._config_for(stage, mbs)
        t0 = time.perf_counter()
        try:
            val = self.runner(cfg)
        except Exception as e:
            logger.info(f"autotuning: stage={stage} mbs={mbs} failed: {e}")
            val = None
        rec = {"zero_stage": stage, "micro_batch_size": mbs,
               self.metric_name: val,
               "tuning_seconds": time.perf_counter() - t0}
        from deepspeed_tpu.autotuning.scheduler import \
            record_experiment_metrics
        record_experiment_metrics(val, rec["tuning_seconds"])
        self.records.append(rec)
        if val is not None and (self.best is None or val > self.best[1]):
            self.best = (cfg, val)
        return val

    def tune(self) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        """Doubling micro-batch sweep per surviving stage; a stage stops
        when a size fails or the metric plateaus (reference
        ``tune_space`` early-stop semantics)."""
        for stage in self._candidate_stages():
            mbs = self.start_mbs
            prev = None
            while mbs <= self.max_mbs_cap:
                val = self._measure(stage, mbs)
                if val is None:
                    break
                if prev is not None and val < prev * 1.02:
                    break                      # throughput plateau
                prev = val
                mbs *= 2
            if self.fast and self.best is not None:
                # fast mode: first fitting stage's sweep is enough unless
                # a later stage is needed to fit at all
                break
        if self.best is None:
            logger.warning("autotuning: no configuration succeeded")
            return None, None
        return self.best

    # -- reporting (reference print_tuning_results / write_optimal) -----

    def print_tuning_results(self) -> None:
        logger.info("autotuning records:")
        for r in self.records:
            logger.info(f"  stage={r['zero_stage']} "
                        f"mbs={r['micro_batch_size']} "
                        f"{self.metric_name}={r[self.metric_name]}")
        if self.best is not None:
            logger.info(f"best: {json.dumps(self.best[0])} -> "
                        f"{self.best[1]:.2f} {self.metric_name}")

    def write_optimal_config(self, path: str) -> None:
        assert self.best is not None, "tune() first"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.best[0], f, indent=2)

    # -- default runner: real engine, timed steps -----------------------

    def _default_runner(self, ds_config: Dict[str, Any]
                        ) -> Optional[float]:
        raise NotImplementedError(
            "pass runner= (ds_config -> samples/sec); the engine-backed "
            "default needs model/example_batch context — use "
            "engine_runner(model, example_batch_fn)")


def engine_runner(model, batch_fn: Callable[[int], Any], steps: int = 3,
                  topology=None):
    """Build the default measurement runner: instantiate a real engine for
    each candidate config and time ``train_batch`` (samples/sec).
    ``batch_fn(global_batch_size)`` supplies a batch of that size."""
    import jax
    import numpy as np

    def run(ds_config: Dict[str, Any]) -> float:
        import deepspeed_tpu

        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=ds_config, topology=topology,
            example_batch=batch_fn(1), rng=jax.random.PRNGKey(0))
        batch = batch_fn(engine.config.train_batch_size)
        engine.train_batch(batch=batch)        # compile
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch=batch)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / steps
        return engine.config.train_batch_size / dt

    return run
