"""Accelerator abstraction: ``get_accelerator()``.

TPU-native counterpart of the reference L0 layer
(``accelerator/real_accelerator.py:52 get_accelerator`` returning a
``DeepSpeedAccelerator`` ABC with ~80 methods).  The reference needs a
vendor-dispatch facade because every backend brings its own streams,
events, allocators, and op builders; under JAX one runtime serves every
platform, so the facade collapses to a thin adapter over ``jax.devices``
— kept because user code and the reference's own subsystems call these
entry points by name (``device_name``, ``device_count``,
``total_memory``, ``synchronize``, ``communication_backend_name``, ...).

Stream/event/graph methods are intentionally absent: XLA owns scheduling
on TPU and there is nothing truthful for them to do.  Code portable with
the reference should feature-check via ``hasattr``.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np


class TPU_Accelerator:
    """The one accelerator (platform resolved from the live backend:
    tpu, or cpu under the test mesh)."""

    def __init__(self):
        self._name = jax.devices()[0].platform

    # -- identity -------------------------------------------------------

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def current_device_name(self) -> str:
        return self.device_name(0)

    def current_device(self) -> int:
        return 0

    def device_count(self) -> int:
        return jax.local_device_count()

    def is_available(self) -> bool:
        return len(jax.devices()) > 0

    def communication_backend_name(self) -> str:
        return "xla"            # ICI/DCN collectives compiled by XLA

    # -- capabilities ---------------------------------------------------

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is supported (loss-scaled); bf16 is the native
        # matmul dtype on TPU
        return True

    def is_triton_supported(self) -> bool:
        return False

    def device_kind(self) -> str:
        return jax.devices()[0].device_kind

    # -- memory ---------------------------------------------------------

    def _stats(self) -> dict:
        try:
            return jax.local_devices()[0].memory_stats() or {}
        except Exception:
            return {}

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self._stats().get("bytes_limit", 0))

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._stats().get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None
                             ) -> int:
        return int(self._stats().get("peak_bytes_in_use",
                                     self.memory_allocated()))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        s = self._stats()
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    def empty_cache(self) -> None:
        pass                    # XLA owns the arena

    # -- execution ------------------------------------------------------

    def synchronize(self, device_index: Optional[int] = None) -> None:
        jax.effects_barrier()

    def manual_seed(self, seed: int):
        return jax.random.PRNGKey(seed)

    def manual_seed_all(self, seed: int):
        return jax.random.PRNGKey(seed)

    # -- dtypes ---------------------------------------------------------

    def supported_dtypes(self):
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16]

    # -- misc parity ----------------------------------------------------

    def on_accelerator(self, x) -> bool:
        return isinstance(x, jax.Array)

    def pin_memory(self, x):
        return np.ascontiguousarray(np.asarray(x))

    def lazy_call(self, fn):
        return fn()


_ACCELERATOR: Optional[TPU_Accelerator] = None


def get_accelerator() -> TPU_Accelerator:
    """Reference ``get_accelerator()`` entry point."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TPU_Accelerator()
    return _ACCELERATOR
