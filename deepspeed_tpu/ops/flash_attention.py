"""Flash attention for TPU.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/`` softmax/attention CUDA kernels powering
``DeepSpeedTransformerLayer``, and the inference flash kernels under
``csrc/transformer/inference/``).  Two implementations behind one API:

- :func:`flash_attention` — a Pallas TPU kernel (online-softmax, blockwise,
  O(S) memory, causal skip, GQA via head-index mapping).  The grid is
  ``(B, H, num_q_blocks, num_k_blocks)``; TPU grids execute sequentially per
  core, so the running max/denominator/accumulator live in VMEM scratch
  across the innermost (k-block) grid steps.
- :func:`blockwise_attention` — a pure-XLA ``lax.scan`` formulation of the
  same math, used as the CPU fallback and as the memory-efficient custom
  backward (recompute-based, matching the flash-attention-2 backward).

Both return identical values; the custom VJP makes the Pallas forward
differentiable with blockwise-recompute gradients, so the full train step
stays O(S) in activation memory (the reference gets this from its fused
kernels + activation checkpointing).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x spells it TPUCompilerParams; modern jax CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)
_LANE = 128  # TPU lane width; scratch row-stat buffers are (bq, _LANE)


# ---------------------------------------------------------------------------
# Reference (naive) attention — used by tests and tiny shapes
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = True,
                  sm_scale: Optional[float] = None,
                  bias: Optional[jax.Array] = None) -> jax.Array:
    """Naive O(S^2)-memory attention. q: [B,H,S,D]; k,v: [B,Hkv,S,D]."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Blockwise XLA implementation (fallback fwd + custom bwd)
# ---------------------------------------------------------------------------

def _blockwise_fwd(q, k, v, *, sm_scale: float, causal: bool,
                   block_q: int, block_k: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """Online-softmax attention via lax.scan. Returns (out, lse).

    q: [B,H,S,D] (f32 compute), k/v already head-expanded to H.
    """
    B, H, S, D = q.shape
    Sk = k.shape[2]
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)
    q_pad = nq * block_q - S
    k_pad = nk * block_k - Sk
    qf = jnp.pad(q.astype(jnp.float32), ((0, 0), (0, 0), (0, q_pad), (0, 0)))
    kf = jnp.pad(k.astype(jnp.float32), ((0, 0), (0, 0), (0, k_pad), (0, 0)))
    vf = jnp.pad(v.astype(jnp.float32), ((0, 0), (0, 0), (0, k_pad), (0, 0)))
    qf = qf.reshape(B, H, nq, block_q, D)
    kf = kf.reshape(B, H, nk, block_k, D)
    vf = vf.reshape(B, H, nk, block_k, D)

    k_idx = jnp.arange(nk * block_k).reshape(nk, block_k)
    q_idx = jnp.arange(nq * block_q).reshape(nq, block_q)
    # bottom-right-aligned causal (matches mha_reference tril k=Sk-S): the
    # last query attends to the last key — the KV-cache decode convention
    causal_offset = Sk - S

    def q_block_step(_, qi):
        q_blk, qpos = qi  # [B,H,bq,D], [bq]

        def k_block_step(carry, ki):
            acc, m, l = carry
            k_blk, v_blk, kpos = ki
            s = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_blk) * sm_scale
            mask = (kpos[None, :] < Sk)
            if causal:
                mask = mask & (kpos[None, :] <= qpos[:, None] + causal_offset)
            s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v_blk)
            return (acc_new, m_new, l_new), None

        init = (jnp.zeros((B, H, block_q, D), jnp.float32),
                jnp.full((B, H, block_q), -jnp.inf, jnp.float32),
                jnp.zeros((B, H, block_q), jnp.float32))
        (acc, m, l), _ = jax.lax.scan(
            k_block_step, init,
            (kf.transpose(2, 0, 1, 3, 4), vf.transpose(2, 0, 1, 3, 4), k_idx))
        # rows with no valid key (causal with Sk < S) never raise m above the
        # mask value: emit 0 output and +inf lse so the backward sees p = 0
        valid = m > DEFAULT_MASK_VALUE * 0.5
        out = jnp.where(valid[..., None],
                        acc / jnp.maximum(l, 1e-30)[..., None], 0.0)
        lse = jnp.where(valid, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
        return None, (out, lse)

    _, (o_blocks, lse_blocks) = jax.lax.scan(
        q_block_step, None, (qf.transpose(2, 0, 1, 3, 4), q_idx))
    out = o_blocks.transpose(1, 2, 0, 3, 4).reshape(B, H, nq * block_q, D)
    lse = lse_blocks.transpose(1, 2, 0, 3).reshape(B, H, nq * block_q)
    return out[:, :, :S], lse[:, :, :S]


def _blockwise_bwd(q, k, v, o, lse, do, *, sm_scale: float, causal: bool,
                   block_q: int, block_k: int):
    """Flash-attention-2 style backward: recompute P blockwise from lse."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    nq = pl.cdiv(S, block_q)
    q_pad = nq * block_q - S

    def pad_q(x, fill=0.0):
        return jnp.pad(x.astype(jnp.float32),
                       ((0, 0), (0, 0), (0, q_pad)) + ((0, 0),) * (x.ndim - 3),
                       constant_values=fill)

    causal_offset = Sk - S
    qf = pad_q(q).reshape(B, H, nq, block_q, D)
    dof = pad_q(do).reshape(B, H, nq, block_q, D)
    # padded rows get lse=+inf → P = exp(-inf) = 0 → no gradient contribution
    lsef = pad_q(lse, fill=jnp.inf).reshape(B, H, nq, block_q)
    deltaf = pad_q(delta).reshape(B, H, nq, block_q)
    q_idx = jnp.arange(nq * block_q).reshape(nq, block_q)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    k_pos = jnp.arange(Sk)

    def q_block_step(carry, qi):
        dk_acc, dv_acc = carry
        q_blk, do_blk, lse_blk, delta_blk, qpos = qi
        s = jnp.einsum("bhqd,bhkd->bhqk", q_blk, kf) * sm_scale
        mask = jnp.ones((block_q, Sk), dtype=bool)
        if causal:
            mask = k_pos[None, :] <= qpos[:, None] + causal_offset
        s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse_blk[..., None])
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, do_blk)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_blk, vf)
        ds = p * (dp - delta_blk[..., None]) * sm_scale
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, q_blk)
        return (dk_acc, dv_acc), dq_blk

    init = (jnp.zeros((B, H, Sk, D), jnp.float32),
            jnp.zeros((B, H, Sk, D), jnp.float32))
    (dk, dv), dq_blocks = jax.lax.scan(
        q_block_step, init,
        (qf.transpose(2, 0, 1, 3, 4), dof.transpose(2, 0, 1, 3, 4),
         lsef.transpose(2, 0, 1, 3), deltaf.transpose(2, 0, 1, 3), q_idx))
    dq = dq_blocks.transpose(1, 2, 0, 3, 4).reshape(B, H, nq * block_q, D)
    return dq[:, :, :S], dk, dv


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _block_mask(i, j, *, causal: bool, block_q: int, block_k: int,
                seq_k: int, causal_offset: int):
    """[bq, bk] bool mask for block (i, j), or None when fully valid.

    ``i``/``j`` are traced program ids, so the mask *computation* is traced —
    but whether a mask is needed at all is decided per-block inside the
    kernel via ``pl.when`` on :func:`_block_is_edge`, keeping the interior
    (the vast majority of blocks) free of VPU mask work.
    """
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = kpos < seq_k
    if causal:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, kpos <= qpos + causal_offset)
    return mask


def _block_is_edge(i, j, *, causal: bool, block_q: int, block_k: int,
                   seq_k: int, causal_offset: int):
    """True when block (i, j) needs masking: it crosses the causal diagonal
    or contains padded key columns."""
    edge = (j + 1) * block_k > seq_k  # padded tail columns
    if causal:
        # crosses the shifted diagonal: some (qpos, kpos) in the block has
        # kpos > qpos + offset while the block is not skipped entirely
        edge = jnp.logical_or(
            edge, (j + 1) * block_k - 1 > i * block_q + causal_offset)
    return edge


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_sc, m_sc, l_sc, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, seq_q: int, seq_k: int):
    del sm_scale  # folded into q by the caller
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)
    # bottom-right-aligned causal diagonal (KV-cache decode convention)
    causal_offset = seq_k - seq_q

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[:] = jnp.zeros_like(l_sc)

    # causal: block row i only reaches key blocks starting at or below its
    # shifted diagonal
    run = jnp.logical_or(
        not causal, j * block_k <= (i + 1) * block_q - 1 + causal_offset)
    geom = dict(causal=causal, block_q=block_q, block_k=block_k,
                seq_k=seq_k, causal_offset=causal_offset)

    def _tile(masked: bool):
        # dots stay in the input dtype (bf16 on TPU -> full MXU rate) with
        # fp32 accumulation; only the softmax statistics run in fp32
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        v = v_ref[0, 0]                                # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk] f32
        if masked:
            s = jnp.where(_block_mask(i, j, **geom), s, DEFAULT_MASK_VALUE)
        m_prev = m_sc[:, 0]                            # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:] = (l_sc[:] * alpha[:, None] +
                   jnp.broadcast_to(jnp.sum(p, axis=-1)[:, None],
                                    l_sc.shape))
        acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)

    edge = _block_is_edge(i, j, **geom)

    @pl.when(jnp.logical_and(run, edge))
    def _compute_masked():
        _tile(masked=True)

    @pl.when(jnp.logical_and(run, jnp.logical_not(edge)))
    def _compute_interior():
        _tile(masked=False)

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, 0], 1e-30)             # [bq]
        m = m_sc[:, 0]
        # rows that never saw a valid key: 0 output, +inf lse (bwd p = 0)
        valid = m > DEFAULT_MASK_VALUE * 0.5
        o_ref[0, 0] = jnp.where(valid[:, None], acc_sc[:] / l[:, None],
                                0.0).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(valid, m + jnp.log(l), jnp.inf)[None, :]


def _flash_fwd_pallas(q, k, v, *, sm_scale: float, causal: bool,
                      block_q: int, block_k: int,
                      interpret: bool = False
                      ) -> Tuple[jax.Array, jax.Array]:
    """q: [B,H,S,D]; k,v: [B,Hkv,Sk,D] (GQA: Hkv divides H)."""
    B, H, S, D = q.shape
    _, Hkv, Sk, _ = k.shape
    groups = H // Hkv
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)
    q_pad = nq * block_q - S
    k_pad = nk * block_k - Sk
    # scale folded into q host-side: one mul per q element instead of one
    # per score element inside the kernel
    q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    if q_pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
    if k_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, k_pad), (0, 0)))

    grid = (B, H, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          seq_q=S, seq_k=Sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // groups, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // groups, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, nq * block_q, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, nq * block_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :S], lse[:, :, 0, :S]


# ---------------------------------------------------------------------------
# Pallas TPU backward kernels (flash-attention-2 dq / dk,dv)
# ---------------------------------------------------------------------------
#
# TPU-native equivalent of the reference's fused attention backward CUDA
# kernels (csrc/transformer/). Two kernels with opposite loop orders:
# - dq: for each q block, accumulate ds @ K over k blocks (same sweep as fwd)
# - dk/dv: for each k block, accumulate ds^T @ Q and P^T @ dO over q blocks
# P is recomputed blockwise from the saved logsumexp — O(S) memory, and every
# dot hits the MXU in fp32 accumulation.

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_sc, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int, seq_q: int, seq_k: int):
    # q arrives pre-scaled by sm_scale; the caller rescales dq afterwards
    del sm_scale
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)
    causal_offset = seq_k - seq_q

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    run = jnp.logical_or(
        not causal, j * block_k <= (i + 1) * block_q - 1 + causal_offset)
    geom = dict(causal=causal, block_q=block_q, block_k=block_k,
                seq_k=seq_k, causal_offset=causal_offset)

    def _tile(masked: bool):
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        v = v_ref[0, 0]                                # [bk, d]
        do = do_ref[0, 0]                              # [bq, d]
        lse = lse_ref[0, 0, 0]                         # [bq] f32
        delta = delta_ref[0, 0, 0]                     # [bq] f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if masked:
            s = jnp.where(_block_mask(i, j, **geom), s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])                  # masked/invalid -> 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        dq_sc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    edge = _block_is_edge(i, j, **geom)

    @pl.when(jnp.logical_and(run, edge))
    def _compute_masked():
        _tile(masked=True)

    @pl.when(jnp.logical_and(run, jnp.logical_not(edge)))
    def _compute_interior():
        _tile(masked=False)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_sc, dv_sc, *, sm_scale: float,
                          causal: bool, block_q: int, block_k: int,
                          seq_q: int, seq_k: int):
    # q arrives pre-scaled: dk = ds^T @ (q * scale) absorbs the rescale
    del sm_scale
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    j = pl.program_id(2)
    causal_offset = seq_k - seq_q

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    run = jnp.logical_or(
        not causal, j * block_k <= (qi + 1) * block_q - 1 + causal_offset)
    geom = dict(causal=causal, block_q=block_q, block_k=block_k,
                seq_k=seq_k, causal_offset=causal_offset)

    def _tile(masked: bool):
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        v = v_ref[0, 0]                                # [bk, d]
        do = do_ref[0, 0]                              # [bq, d]
        lse = lse_ref[0, 0, 0]                         # [bq] f32
        delta = delta_ref[0, 0, 0]                     # [bq] f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if masked:
            s = jnp.where(_block_mask(qi, j, **geom), s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])                  # [bq, bk] f32
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk_sc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    edge = _block_is_edge(qi, j, **geom)

    @pl.when(jnp.logical_and(run, edge))
    def _compute_masked():
        _tile(masked=True)

    @pl.when(jnp.logical_and(run, jnp.logical_not(edge)))
    def _compute_interior():
        _tile(masked=False)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, *, sm_scale: float, causal: bool,
                      block_q: int, block_k: int, interpret: bool = False):
    """Returns (dq, dk, dv) with dk/dv per *q*-head ([B, H, Sk, D]); the
    caller sums GQA groups back onto the shared kv head."""
    B, H, S, D = q.shape
    _, Hkv, Sk, _ = k.shape
    groups = H // Hkv
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)
    q_pad = nq * block_q - S
    k_pad = nk * block_k - Sk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # q pre-scaled to match the forward's logits; dq is rescaled at the end
    q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    if q_pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
        # padded rows: lse=+inf -> p=0 -> no contribution to dk/dv
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, q_pad)),
                      constant_values=jnp.inf)
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, q_pad)))
    if k_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
    lse4 = lse[:, :, None, :]                          # [B,H,1,Sq_pad]
    delta4 = delta[:, :, None, :]

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, seq_q=S, seq_k=Sk)
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j: (b, h // groups, j, 0))
    row_spec = pl.BlockSpec((1, 1, 1, block_q),
                            lambda b, h, i, j: (b, h, 0, i))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(B, H, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, nq * block_q, D), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse4, delta4)[0]

    # dkv sweep: k block outer, q block inner (accumulate over q)
    kq_q_spec = pl.BlockSpec((1, 1, block_q, D),
                             lambda b, h, j, i: (b, h, i, 0))
    kq_k_spec = pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, j, i: (b, h // groups, j, 0))
    kq_row_spec = pl.BlockSpec((1, 1, 1, block_q),
                               lambda b, h, j, i: (b, h, 0, i))
    kq_out_spec = pl.BlockSpec((1, 1, block_k, D),
                               lambda b, h, j, i: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(B, H, nk, nq),
        in_specs=[kq_q_spec, kq_k_spec, kq_k_spec, kq_q_spec, kq_row_spec,
                  kq_row_spec],
        out_specs=[kq_out_spec, kq_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, nk * block_k, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, nk * block_k, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse4, delta4)
    # dq was computed against the pre-scaled q; undo the fold
    dq = dq * sm_scale
    return dq[:, :, :S], dk[:, :, :Sk], dv[:, :, :Sk]


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------

def _expand_kv(q, k, v):
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    return k, v


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_dispatch(q, k, v, sm_scale, causal, block_q, block_k,
                                 interpret)
    return out


def _flash_fwd_dispatch(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret):
    if _use_pallas() or interpret:
        return _flash_fwd_pallas(q, k, v, sm_scale=sm_scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    ke, ve = _expand_kv(q, k, v)
    out, lse = _blockwise_fwd(q, ke, ve, sm_scale=sm_scale, causal=causal,
                              block_q=block_q, block_k=block_k)
    return out.astype(q.dtype), lse


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_dispatch(q, k, v, sm_scale, causal, block_q,
                                   block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    n_kv = k.shape[1]
    groups = q.shape[1] // n_kv
    if _use_pallas() or interpret:
        dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, do,
                                       sm_scale=sm_scale, causal=causal,
                                       block_q=block_q, block_k=block_k,
                                       interpret=interpret)
    else:
        ke, ve = _expand_kv(q, k, v)
        dq, dk, dv = _blockwise_bwd(q, ke, ve, out, lse, do,
                                    sm_scale=sm_scale, causal=causal,
                                    block_q=block_q, block_k=block_k)
    if groups > 1:  # sum GQA group gradients back to the shared kv head
        B, H, Sk, D = dk.shape
        dk = dk.reshape(B, n_kv, groups, Sk, D).sum(axis=2)
        dv = dv.reshape(B, n_kv, groups, Sk, D).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: bool = False) -> jax.Array:
    """Flash attention.  q: [B, H, S, D]; k, v: [B, Hkv, Sk, D] where Hkv
    divides H (grouped-query attention).  Returns [B, H, S, D] in q.dtype.

    Pallas kernel on TPU; blockwise-XLA everywhere else; O(S)-memory custom
    backward in both cases.  ``interpret=True`` forces the Pallas kernel in
    interpreter mode (CPU testing).  Blocks clamp to the sequence length;
    the 1024 default measured fastest at the 2k-seq bench shape on v5e
    (fwd+bwd 1.57 ms vs 1.72 at 512, D=64 GQA) — VMEM comfortably holds
    [1024, D] tiles for the head dims in use.
    """
    assert q.shape[1] % k.shape[1] == 0, (
        f"q heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}")
    assert k.shape == v.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    # clamp here so EVERY backend sees it: the blockwise-XLA fallback pads
    # S up to a block multiple, so an unclamped default would compute (and
    # mask away) up to block_q/S times the work on short sequences
    block_q = min(int(block_q), q.shape[2])
    block_k = min(int(block_k), k.shape[2])
    return _flash(q, k, v, float(sm_scale), bool(causal), block_q,
                  block_k, bool(interpret))
