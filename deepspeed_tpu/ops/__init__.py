from deepspeed_tpu.ops.evoformer import (DS4Sci_EvoformerAttention,
                                         evoformer_attention)
from deepspeed_tpu.ops.flash_attention import flash_attention
from deepspeed_tpu.ops.fused_adam import (scale_by_fused_adam,
                                          scale_by_fused_lion)
from deepspeed_tpu.ops.quantization import (dequantize, dequantize_fp6,
                                            dequantize_fp8, quantize,
                                            quantize_fp6, quantize_fp8)
from deepspeed_tpu.ops.ragged_paged_quant import ragged_paged_attention_quant
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention,
                                                block_sparse_attention)

__all__ = [
    "flash_attention", "evoformer_attention", "DS4Sci_EvoformerAttention", "scale_by_fused_adam", "scale_by_fused_lion",
    "quantize", "dequantize", "quantize_fp8", "dequantize_fp8",
    "quantize_fp6", "dequantize_fp6", "ragged_paged_attention_quant",
    "block_sparse_attention",
    "SparseSelfAttention", "FixedSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "DenseSparsityConfig",
]
