"""Block-sparse attention: sparsity layouts + a gathered blockwise kernel.

TPU-native re-design of the reference sparse-attention stack
(``ops/sparse_attention/sparsity_config.py:10`` layout family,
``sparse_self_attention.py:12 SparseSelfAttention``, triton SDD/DSD
``matmul.py`` + ``softmax.py``): a LAYOUT — a static boolean
``[heads, nq_blocks, nk_blocks]`` grid — says which key blocks each
query block may attend; the kernel touches only active blocks.

Where triton JIT-compiles per-layout sparse matmuls, the TPU version
exploits that the layout is STATIC: each (head, q-block) row's active
kv-block indices become a padded gather table baked into the compiled
program, so the whole computation is dense einsums over
``[..., max_active * block, ...]`` gathered tiles — MXU-shaped, fully
differentiable through plain AD, O(S * max_active * block) memory
instead of O(S^2).

Layouts implemented (constructor knobs follow the reference classes):

- :class:`DenseSparsityConfig` — everything active (testing).
- :class:`FixedSparsityConfig` — local windows of ``num_local_blocks``
  plus ``num_global_blocks`` global block(s) per window stride.
- :class:`BSLongformerSparsityConfig` — sliding window + chosen global
  blocks (attend-all + attended-by-all).
- :class:`BigBirdSparsityConfig` — sliding window + global edge blocks +
  per-row random blocks (seeded, static).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_MASK_VALUE = -1e30


# ---------------------------------------------------------------------------
# layouts (reference sparsity_config.py family)
# ---------------------------------------------------------------------------

class SparsityConfig:
    """Base: ``make_layout(seq_len)`` -> bool [num_heads, nb, nb]."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        assert seq_len % self.block == 0, (
            f"seq_len {seq_len} must be a multiple of block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), bool)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray
                                              ) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + global blocks (reference ``:95``): queries attend
    their own ``num_local_blocks`` window (lower-triangular part when
    ``attention="unidirectional"``), and the last ``num_global_blocks``
    of each window attend / are attended globally."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(layout.shape[0]):
            for start in range(0, nb, L):
                end = min(start + L, nb)
                for i in range(start, end):
                    hi = (i + 1) if self.attention == "unidirectional" \
                        else end
                    layout[h, i, start:hi] = True
            # global columns: the last G blocks of every window are
            # attended by everyone (past them, for unidirectional)
            for start in range(0, nb, L):
                g0 = min(start + L, nb) - G
                for g in range(max(g0, start), min(start + L, nb)):
                    if self.attention == "unidirectional":
                        layout[h, g + 1:, g] = True
                    else:
                        layout[h, :, g] = True
                    if self.horizontal_global_attention:
                        layout[h, g, :] = True
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + explicit global blocks (reference ``:546``)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Sequence[int] = (0,),
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for i in range(nb):
            lo = max(i - w, 0)
            hi = (i + 1) if self.attention == "unidirectional" \
                else min(i + w + 1, nb)
            layout[:, i, lo:hi] = True
        for g in self.global_block_indices:
            if g < nb:
                layout[:, g, :(nb if self.attention == "bidirectional"
                               else g + 1)] = True   # attends all
                layout[:, g:, g] = True              # attended by all
                if self.attention == "bidirectional":
                    layout[:, :, g] = True
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + explicit global windows + random blocks
    (reference ``sparsity_config.py:239``): ``local_window_blocks[i]``
    sizes the i-th local window (last entry repeats), global blocks come
    as indices or [start, end) ranges, plus seeded random blocks."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Sequence[int] = (4,),
                 global_block_indices: Sequence[int] = (0,),
                 global_block_end_indices: Optional[Sequence[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            assert len(global_block_end_indices) == \
                len(self.global_block_indices), (
                    "global_block_end_indices must pair 1:1 with "
                    "global_block_indices")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                assert s < e, f"global range [{s}, {e}) is empty"
        self.global_block_end_indices = (
            None if global_block_end_indices is None
            else list(global_block_end_indices))
        assert attention in ("unidirectional", "bidirectional")
        assert attention == "bidirectional" or \
            not horizontal_global_attention, (
                "horizontal global attention requires bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def _global_cols(self, nb: int):
        if self.global_block_end_indices is None:
            return [g for g in self.global_block_indices if g < nb]
        cols = []
        for s, e in zip(self.global_block_indices,
                        self.global_block_end_indices):
            cols.extend(range(s, min(e, nb)))
        return cols

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = np.random.default_rng(self.seed)
        heads = layout.shape[0] if self.different_layout_per_head else 1
        for h in range(heads):
            # variable-size local windows: sizes from the list, the last
            # size repeating for the remaining windows
            start = 0
            wi = 0
            while start < nb:
                size = self.local_window_blocks[
                    min(wi, len(self.local_window_blocks) - 1)]
                end = min(start + size, nb)
                for i in range(start, end):
                    hi = (i + 1) if self.attention == "unidirectional" \
                        else end
                    layout[h, i, start:hi] = True
                start = end
                wi += 1
            for g in self._global_cols(nb):
                if self.attention == "unidirectional":
                    layout[h, g:, g] = True          # attended by later
                else:
                    layout[h, :, g] = True           # attended by all
                if self.horizontal_global_attention:
                    layout[h, g, :] = True
            for i in range(nb):
                if not self.num_random_blocks:
                    break
                bound = (i + 1) if self.attention == "unidirectional" \
                    else nb
                choices = rng.integers(0, max(bound, 1),
                                       size=self.num_random_blocks)
                layout[h, i, choices] = True
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Purely-local sliding window (reference
    ``sparsity_config.py:674``): each query block attends the
    ``num_sliding_window_blocks`` centered on it (its causal half for
    unidirectional attention) — no global blocks at all."""

    def __init__(self, num_heads: int, block: int = 64,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        assert nb >= self.num_sliding_window_blocks, (
            f"need >= {self.num_sliding_window_blocks} blocks, "
            f"seq has {nb}")
        w = self.num_sliding_window_blocks // 2
        for i in range(nb):
            lo = max(0, i - w)
            hi = (min(i + w + 1, nb)
                  if self.attention == "bidirectional" else i + 1)
            layout[:, i, lo:hi] = True
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Sliding window + global edges + seeded random blocks (reference
    ``:411``)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        G = self.num_global_blocks
        rng = np.random.default_rng(self.seed)
        heads = layout.shape[0] if self.different_layout_per_head else 1
        for h in range(heads):
            for i in range(nb):
                lo = max(i - w, 0)
                hi = (i + 1) if self.attention == "unidirectional" \
                    else min(i + w + 1, nb)
                layout[h, i, lo:hi] = True
                bound = (i + 1) if self.attention == "unidirectional" \
                    else nb
                choices = rng.integers(0, max(bound, 1),
                                       size=self.num_random_blocks)
                layout[h, i, choices] = True
            layout[h, :, :G] = (
                np.tril(np.ones((nb, nb), bool))[:, :G]
                if self.attention == "unidirectional" else True)
            layout[h, :G, :] = (np.tril(np.ones((nb, nb), bool))[:G]
                                if self.attention == "unidirectional"
                                else True)
        return self.check_and_propagate_first_head_layout(layout)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _gather_tables(layout: np.ndarray):
    """Padded active-block index tables: idx [H, nq, A], valid same."""
    H, nq, nk = layout.shape
    counts = layout.sum(-1)
    A = max(int(counts.max()), 1)
    idx = np.zeros((H, nq, A), np.int32)
    valid = np.zeros((H, nq, A), bool)
    for h in range(H):
        for i in range(nq):
            js = np.nonzero(layout[h, i])[0]
            idx[h, i, :js.size] = js
            valid[h, i, :js.size] = True
    return idx, valid, A


def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           layout: np.ndarray, block: int,
                           causal: bool = False,
                           sm_scale: Optional[float] = None) -> jax.Array:
    """Attention restricted to the layout's active blocks.

    q/k/v: [B, H, S, D]; ``layout``: static bool [H, S/block, S/block].
    ``causal=True`` additionally masks inside blocks on/above the
    diagonal (use with a unidirectional layout).
    """
    B, H, S, D = q.shape
    nb = S // block
    assert layout.shape == (H, nb, nb), (layout.shape, (H, nb, nb))
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(D)
    idx_np, valid_np, A = _gather_tables(layout)
    idx = jnp.asarray(idx_np)                        # [H, nq, A]
    valid = jnp.asarray(valid_np)

    qb = q.reshape(B, H, nb, block, D)
    kb = k.reshape(B, H, nb, block, D)
    vb = v.reshape(B, H, nb, block, D)
    # gather each (h, i)'s active kv blocks: [B, H, nq, A, block, D]
    kg = jnp.take_along_axis(kb[:, :, None], idx[None, :, :, :, None,
                                                 None], axis=3)
    vg = jnp.take_along_axis(vb[:, :, None], idx[None, :, :, :, None,
                                                 None], axis=3)

    s = jnp.einsum("bhiqd,bhiakd->bhiqak", qb, kg,
                   preferred_element_type=jnp.float32) * sm_scale
    mask = valid[None, :, :, None, :, None]          # [1,H,nq,1,A,1]
    if causal:
        qpos = (jnp.arange(nb)[:, None] * block +
                jnp.arange(block)[None, :])          # [nq, block]
        kpos = (idx[..., None] * block +
                jnp.arange(block)[None, None, None, :])  # [H,nq,A,block]
        # cmask[h, i, bq, a, bk] = kpos <= qpos
        cmask = (kpos[:, :, None, :, :] <=
                 qpos[None, :, :, None, None])
        mask = mask & cmask[None]
    s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s.reshape(B, H, nb, block, -1), axis=-1)
    p = p.reshape(s.shape).astype(vg.dtype)
    out = jnp.einsum("bhiqak,bhiakd->bhiqd", p, vg)
    return out.reshape(B, H, S, D).astype(q.dtype)


class SparseSelfAttention:
    """Reference ``SparseSelfAttention`` surface: construct with a
    sparsity config, call with q/k/v."""

    def __init__(self, sparsity_config: SparsityConfig,
                 attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config
        self.attn_mask_mode = attn_mask_mode
        self._layouts = {}

    def __call__(self, q, k, v):
        S = q.shape[2]
        if S not in self._layouts:
            self._layouts[S] = self.sparsity_config.make_layout(S)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        return block_sparse_attention(
            q, k, v, self._layouts[S], self.sparsity_config.block,
            causal=causal)
