"""Fused optimizer kernels (Pallas).

TPU-native equivalent of the reference's multi-tensor fused optimizer CUDA
kernels (``csrc/adam/multi_tensor_adam.cu`` + ``fused_adam_frontend.cpp``
behind ``deepspeed/ops/adam/fused_adam.py:18 FusedAdam``; ``csrc/lion/``).
One Pallas kernel performs the whole update for a parameter tile — moment
updates, bias correction, decoupled/L2 weight decay, and the update
direction — in a single pass over HBM, which is exactly what the CUDA
multi-tensor apply buys the reference (bandwidth-bound optimizer math with
no intermediate round-trips).

The kernels produce the *update direction* ``u`` and new moments; the engine
applies ``p_new = p - lr * u`` inside the train step (lr stays outside so
schedule changes never retrace).  Exposed as optax-compatible transforms
(:func:`scale_by_fused_adam`, :func:`scale_by_fused_lion`) that the optimizer
factory substitutes for the stock optax path when ``fused=true`` on TPU.

CPU fallback: identical math in plain jnp (tests compare both, and run the
Pallas kernel in interpreter mode).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_BLOCK_ROWS = 512  # rows of 128 lanes per grid step


class ScaleByFusedAdamState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates


class ScaleByFusedLionState(NamedTuple):
    count: jax.Array
    mu: optax.Updates


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def _adam_kernel(sc_ref, g_ref, p_ref, m_ref, v_ref,
                 u_ref, m_out_ref, v_out_ref, *,
                 b1: float, b2: float, eps: float, wd: float, adam_w: bool):
    # sc_ref: [bc1, bc2] bias corrections, precomputed outside the kernel
    # (Mosaic has no pow lowering; they're scalars anyway)
    bc1 = sc_ref[0]
    bc2 = sc_ref[1]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = m_ref[:]
    v = v_ref[:]
    if wd and not adam_w:  # L2 mode: decay folded into the gradient
        g = g + wd * p
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if wd and adam_w:  # decoupled (AdamW) decay joins the direction
        u = u + wd * p
    u_ref[:] = u
    m_out_ref[:] = m_new
    v_out_ref[:] = v_new


def _lion_kernel(sc_ref, g_ref, p_ref, m_ref, u_ref, m_out_ref, *,
                 b1: float, b2: float, wd: float):
    del sc_ref  # lion has no bias correction
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = m_ref[:]
    u = jnp.sign(b1 * m + (1.0 - b1) * g)
    if wd:
        u = u + wd * p
    u_ref[:] = u
    m_out_ref[:] = b2 * m + (1.0 - b2) * g


def _block_rows(n: int) -> int:
    """Per-leaf block size: 8-row aligned, capped at _BLOCK_ROWS, so small
    leaves (biases, norms) pad to at most 8x128 instead of 512x128."""
    rows = pl.cdiv(max(n, 1), _LANE)
    rows = pl.cdiv(rows, 8) * 8
    return min(rows, _BLOCK_ROWS)


def _tile(x: jax.Array) -> jax.Array:
    """Flatten to (rows, 128) padded to the leaf's block-row multiple."""
    n = x.size
    blk = _block_rows(n)
    rows = pl.cdiv(max(n, 1), _LANE)
    rows = pl.cdiv(rows, blk) * blk
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32),
                   (0, rows * _LANE - n))
    return flat.reshape(rows, _LANE)


def _untile(x: jax.Array, shape, dtype) -> jax.Array:
    n = int(np.prod(shape)) if shape else 1
    return x.reshape(-1)[:n].reshape(shape).astype(dtype)


def _run_elementwise(kernel, scalars, tiles, n_outs: int, interpret: bool):
    """Run an elementwise optimizer kernel over same-shape (R,128) tiles.
    ``scalars`` is a small f32 vector handed to the kernel via SMEM."""
    rows = tiles[0].shape[0]
    blk_rows = _block_rows(rows * _LANE)
    grid = (rows // blk_rows,)
    blk = pl.BlockSpec((blk_rows, _LANE), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [blk] * len(tiles),
        out_specs=[blk] * n_outs,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)
                   ] * n_outs,
        interpret=interpret,
    )(scalars, *tiles)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Per-leaf updates (pallas on TPU / jnp elsewhere)
# ---------------------------------------------------------------------------

def adam_update_leaf(g, p, m, v, step, *, b1, b2, eps, wd, adam_w,
                     interpret: bool = False):
    """Returns (u, m_new, v_new) for one leaf."""
    if _on_tpu() or interpret:
        t = step.astype(jnp.float32)
        scalars = jnp.stack([1.0 - jnp.power(jnp.float32(b1), t),
                             1.0 - jnp.power(jnp.float32(b2), t)])
        kern = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                                 adam_w=adam_w)
        u, m_new, v_new = _run_elementwise(
            kern, scalars, [_tile(g), _tile(p), _tile(m), _tile(v)], 3,
            interpret)
        return (_untile(u, g.shape, jnp.float32),
                _untile(m_new, g.shape, jnp.float32),
                _untile(v_new, g.shape, jnp.float32))
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    if wd and not adam_w:
        gf = gf + wd * pf
    m_new = b1 * m + (1.0 - b1) * gf
    v_new = b2 * v + (1.0 - b2) * gf * gf
    t = step.astype(jnp.float32)
    u = (m_new / (1.0 - jnp.power(b1, t))) / (
        jnp.sqrt(v_new / (1.0 - jnp.power(b2, t))) + eps)
    if wd and adam_w:
        u = u + wd * pf
    return u, m_new, v_new


def lion_update_leaf(g, p, m, step, *, b1, b2, wd, interpret: bool = False):
    if _on_tpu() or interpret:
        kern = functools.partial(_lion_kernel, b1=b1, b2=b2, wd=wd)
        u, m_new = _run_elementwise(
            kern, jnp.zeros((2,), jnp.float32),
            [_tile(g), _tile(p), _tile(m)], 2, interpret)
        return (_untile(u, g.shape, jnp.float32),
                _untile(m_new, g.shape, jnp.float32))
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    u = jnp.sign(b1 * m + (1.0 - b1) * gf)
    if wd:
        u = u + wd * pf
    return u, b2 * m + (1.0 - b2) * gf


# ---------------------------------------------------------------------------
# optax-compatible transforms
# ---------------------------------------------------------------------------

def scale_by_fused_adam(b1: float = 0.9, b2: float = 0.999,
                        eps: float = 1e-8, weight_decay: float = 0.0,
                        adam_w_mode: bool = True,
                        interpret: bool = False
                        ) -> optax.GradientTransformation:
    """Fused Adam/AdamW (``deepspeed/ops/adam/fused_adam.py`` equivalent).
    Unlike stock optax chains, moments + bias correction + weight decay are
    one kernel per leaf. Requires params to be passed to ``update``."""

    def init_fn(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return ScaleByFusedAdamState(
            count=jnp.zeros([], jnp.int32), mu=zeros,
            nu=jax.tree_util.tree_map(jnp.copy, zeros))

    def update_fn(updates, state, params=None):
        assert params is not None, "fused adam needs params"
        count = state.count + 1
        out = jax.tree_util.tree_map(
            lambda g, p, m, v: adam_update_leaf(
                g, p, m, v, count, b1=b1, b2=b2, eps=eps, wd=weight_decay,
                adam_w=adam_w_mode, interpret=interpret),
            updates, params, state.mu, state.nu)
        u = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
        mu = jax.tree_util.tree_map(lambda t: t[1], out,
                                    is_leaf=lambda t: isinstance(t, tuple))
        nu = jax.tree_util.tree_map(lambda t: t[2], out,
                                    is_leaf=lambda t: isinstance(t, tuple))
        return u, ScaleByFusedAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def scale_by_fused_lion(b1: float = 0.9, b2: float = 0.99,
                        weight_decay: float = 0.0,
                        interpret: bool = False
                        ) -> optax.GradientTransformation:
    """Fused Lion (``csrc/lion`` equivalent)."""

    def init_fn(params):
        return ScaleByFusedLionState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update_fn(updates, state, params=None):
        assert params is not None, "fused lion needs params"
        count = state.count + 1
        out = jax.tree_util.tree_map(
            lambda g, p, m: lion_update_leaf(
                g, p, m, count, b1=b1, b2=b2, wd=weight_decay,
                interpret=interpret),
            updates, params, state.mu)
        u = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
        mu = jax.tree_util.tree_map(lambda t: t[1], out,
                                    is_leaf=lambda t: isinstance(t, tuple))
        return u, ScaleByFusedLionState(count=count, mu=mu)

    return optax.GradientTransformation(init_fn, update_fn)
