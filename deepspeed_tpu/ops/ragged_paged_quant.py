"""Quantized-pages ragged paged attention (Pallas TPU kernel).

The serving KV pool persists int8 / fp8-e4m3 pages with per-(row, head)
fp32 scales (``inference/paged.py``).  The full-width Pallas kernel the
paged path was written against (``jax.experimental.pallas.ops.tpu.
ragged_paged_attention``) reads float pages, so a quantized pool used to
be dequantized into a transient ``[P, page, 2*Hkv, D]`` float operand
every attention call — the capacity win was real but the bandwidth win
was negative.  This kernel removes that: it streams the 1-byte pages and
their scale rows straight from the pool and dequantizes ONE page tile at
a time in registers (VMEM), so HBM traffic per attended token is the
quantized byte count, never the full-width pool.

Layout contract (shared with :func:`~deepspeed_tpu.inference.paged.
ref_paged_attention`): pages are ``[num_pages, page_size, 2*Hkv, D]``
with K at even combined-head indices and V at odd; ``scales`` is the
matching ``[num_pages, page_size, 2*Hkv]`` fp32 buffer; ``page_indices``
pads unused entries with -1; ``kv_lens`` includes the current tick's
tokens.

Grid: ``(num_seq_slots, pages_per_seq)`` with the page dim minor, so the
streaming-softmax accumulators (m, l, acc) carry across one sequence's
pages in VMEM scratch.  The ragged metadata rides scalar prefetch
(:class:`~jax.experimental.pallas.tpu.PrefetchScalarGridSpec`): page ids
feed the page/scale BlockSpec index maps, so the DMA engine fetches only
attended pages.  The output block is constant-indexed and revisited —
each sequence's programs write only their own query rows at their last
page step.

Head dim must be 128 (the MXU lane width, same constraint as the
full-width kernel).  ``interpret=True`` runs the identical kernel through
the Pallas interpreter — that is how tier-1 covers this file on the CPU
container (``tests/unit/inference/test_paged_quant.py`` parity suite).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# same mask value family as ops/flash_attention.py: large enough to
# vanish under softmax, small enough that (mask - mask) stays exact 0
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _quant_kernel(kv_lens_ref, pi_ref, cu_ref, ns_ref,   # scalar prefetch
                  *refs, page: int, groups: int,
                  sliding_window: Optional[int], has_carry: bool,
                  out_stats: bool):
    # positional refs vary with the carry/stats variants: inputs are
    # (q, pages, scales[, m_in, l_in, acc_in]), outputs are
    # (o[, m_out, l_out, acc_out]), then the three VMEM scratch buffers
    q_ref, pages_ref, scales_ref = refs[0], refs[1], refs[2]
    n = 3
    if has_carry:
        mi_ref, li_ref, acci_ref = refs[n], refs[n + 1], refs[n + 2]
        n += 3
    o_ref = refs[n]
    n += 1
    if out_stats:
        mo_ref, lo_ref, acco_ref = refs[n], refs[n + 1], refs[n + 2]
        n += 3
    acc_sc, m_sc, l_sc = refs[n], refs[n + 1], refs[n + 2]

    i = pl.program_id(0)                   # sequence slot
    j = pl.program_id(1)                   # page ordinal within the slot
    pp = pl.num_programs(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _zero_out():
        o_ref[...] = jnp.zeros_like(o_ref)
        if out_stats:
            mo_ref[...] = jnp.full_like(mo_ref, _MASK_VALUE)
            lo_ref[...] = jnp.zeros_like(lo_ref)
            acco_ref[...] = jnp.zeros_like(acco_ref)

    @pl.when(j == 0)
    def _reset_seq():
        # an incoming chunk-scan carry seeds the accumulators instead of
        # the neutral element — the explicit carry INPUT
        if has_carry:
            acc_sc[...] = acci_ref[...]
            m_sc[...] = mi_ref[...]
            l_sc[...] = li_ref[...]
        else:
            acc_sc[...] = jnp.zeros_like(acc_sc)
            m_sc[...] = jnp.full_like(m_sc, _MASK_VALUE)
            l_sc[...] = jnp.zeros_like(l_sc)

    q0 = cu_ref[i]
    q1 = cu_ref[i + 1]
    kvl = kv_lens_ref[i]
    live = jnp.logical_and(i < ns_ref[0], q1 > q0)
    # page j holds attended rows AND is resident: a -1 entry is padding
    # or a parked partial-residency hole — its tile (the index map
    # clamped it to the trash page) is skipped, and because kv
    # positions derive from the column ordinal j the surviving columns
    # keep their true absolute positions
    in_range = jnp.logical_and(j * page < kvl, pi_ref[i, j] >= 0)

    @pl.when(jnp.logical_and(live, in_range))
    def _tile():
        T, H, D = q_ref.shape
        Hkv = pages_ref.shape[2] // 2
        # dequantize THIS page tile only, in registers: 1-byte rows and
        # one fp32 scale per (row, combined head)
        tile = pages_ref[0].astype(jnp.float32)          # [page, 2Hkv, D]
        kvf = tile * scales_ref[0][..., None]
        kvf = kvf.reshape(page, Hkv, 2, D)
        k = kvf[:, :, 0, :]                              # [page, Hkv, D]
        v = kvf[:, :, 1, :]

        qf = q_ref[...].astype(jnp.float32)              # pre-scaled
        qg = qf.reshape(T, Hkv, groups, D)
        att = jnp.einsum("thgd,phd->thgp", qg, k,
                         preferred_element_type=jnp.float32)

        t_idx = jax.lax.broadcasted_iota(jnp.int32, (T, page), 0)
        kv_idx = jax.lax.broadcasted_iota(jnp.int32, (T, page), 1) + \
            j * page
        q_pos = kvl - (q1 - q0) + (t_idx - q0)           # abs position
        mask = ((t_idx >= q0) & (t_idx < q1) &
                (kv_idx <= q_pos) & (kv_idx < kvl))
        if sliding_window is not None:
            mask = mask & (kv_idx > q_pos - sliding_window)
        att = jnp.where(mask[:, None, None, :], att, _MASK_VALUE)

        att2 = att.reshape(T, Hkv * groups, page)        # [T, H, page]
        curr_m = jnp.max(att2, axis=-1)                  # [T, H]
        m_new = jnp.maximum(m_sc[...], curr_m)
        alpha = jnp.exp(m_sc[...] - m_new)
        p = jnp.exp(att2 - m_new[..., None])             # [T, H, page]
        pv = jnp.einsum("thgp,phd->thgd",
                        p.reshape(T, Hkv, groups, page), v,
                        preferred_element_type=jnp.float32)
        acc_sc[...] = (acc_sc[...] * alpha[..., None] +
                       pv.reshape(T, Hkv * groups, D))
        l_sc[...] = l_sc[...] * alpha + jnp.sum(p, axis=-1)
        m_sc[...] = m_new

    @pl.when(jnp.logical_and(live, j == pp - 1))
    def _finalize():
        T = q_ref.shape[0]
        l = jnp.maximum(l_sc[...], 1e-30)
        # rows that never matched a key keep 0 (the engine's padding
        # rows and other sequences' rows are written by their own
        # programs or stay at the j==0 zero fill)
        valid = m_sc[...] > _MASK_VALUE * 0.5            # [T, H]
        val = jnp.where(valid[..., None], acc_sc[...] / l[..., None], 0.0)
        rows = jax.lax.broadcasted_iota(jnp.int32, (T,), 0)
        mine = jnp.logical_and(rows >= q0, rows < q1)    # [T]
        o_ref[...] = jnp.where(mine[:, None, None],
                               val.astype(o_ref.dtype), o_ref[...])
        if out_stats:
            # the explicit carry OUTPUT: raw (un-normalized) stats, so a
            # later dispatch can keep folding
            mo_ref[...] = jnp.where(mine[:, None], m_sc[...], mo_ref[...])
            lo_ref[...] = jnp.where(mine[:, None], l_sc[...], lo_ref[...])
            acco_ref[...] = jnp.where(mine[:, None, None], acc_sc[...],
                                      acco_ref[...])


def ragged_paged_attention_quant(
        q: jax.Array, pages: jax.Array, scales: jax.Array,
        kv_lens: jax.Array, page_indices: jax.Array,
        cu_q_lens: jax.Array, num_seqs: jax.Array, *, sm_scale: float,
        sliding_window: Optional[int] = None,
        carry=None, return_stats: bool = False,
        interpret: bool = False):
    """Ragged paged attention over a QUANTIZED page pool.

    q: ``[T, H, D]`` float; pages: ``[P, page, 2*Hkv, D]`` int8 or
    fp8_e4m3; scales: ``[P, page, 2*Hkv]`` fp32; metadata as the
    full-width kernel (``page_indices`` may pad with -1 — trailing
    padding OR interior partial-residency holes; hole tiles are skipped
    and the surviving columns keep their true positions).  Returns
    ``[T, H, D]`` in ``q.dtype``.  D must be 128 — the kernel contract
    it shares with the full-width vLLM-TPU kernel; other head dims use
    :func:`~deepspeed_tpu.inference.paged.ref_paged_attention_quant`.

    The flash carry is an explicit input/output for the chunked
    partial-residency scan: ``carry=(m [T,H], l [T,H], acc [T,H,D])``
    (fp32) seeds the streaming accumulators instead of the neutral
    element, and ``return_stats=True`` returns
    ``(out, (m, l, acc))`` so a later dispatch can keep folding.
    """
    T, H, D = q.shape
    P, page, combined, _ = pages.shape
    Hkv = combined // 2
    S, pp = page_indices.shape
    assert D == 128, (
        f"ragged_paged_attention_quant requires head_dim 128, got {D} "
        "(use ref_paged_attention_quant for other dims)")
    assert H % Hkv == 0, (H, Hkv)
    groups = H // Hkv
    assert pages.dtype in (jnp.int8, jnp.float8_e4m3fn), pages.dtype

    # fold sm_scale into q host-side (one mul per q element, exactly as
    # ops/flash_attention.py) and pad q rows to the f32 sublane multiple
    qf = q.astype(jnp.float32) * jnp.float32(sm_scale)
    Tp = (T + 7) // 8 * 8
    if Tp != T:
        qf = jnp.pad(qf, ((0, Tp - T), (0, 0), (0, 0)))

    # raw page ids ride the scalar prefetch so the kernel can SKIP -1
    # tiles; the BlockSpec index maps clamp to the trash page only to
    # keep the DMA address legal for skipped tiles
    pi = page_indices.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((Tp, H, D), lambda i, j, *refs: (0, 0, 0)),
        pl.BlockSpec((1, page, combined, D),
                     lambda i, j, kvl, pi, cu, ns: (
                         jnp.maximum(pi[i, j], 0), 0, 0, 0)),
        pl.BlockSpec((1, page, combined),
                     lambda i, j, kvl, pi, cu, ns: (
                         jnp.maximum(pi[i, j], 0), 0, 0)),
    ]
    operands = [qf, pages, scales]
    if carry is not None:
        m0, l0, acc0 = carry
        if Tp != T:
            # padded rows belong to no sequence; neutral-pad them so the
            # seeded accumulators stay finite
            m0 = jnp.pad(m0.astype(jnp.float32), ((0, Tp - T), (0, 0)),
                         constant_values=_MASK_VALUE)
            l0 = jnp.pad(l0.astype(jnp.float32), ((0, Tp - T), (0, 0)))
            acc0 = jnp.pad(acc0.astype(jnp.float32),
                           ((0, Tp - T), (0, 0), (0, 0)))
        in_specs += [
            pl.BlockSpec((Tp, H), lambda i, j, *refs: (0, 0)),
            pl.BlockSpec((Tp, H), lambda i, j, *refs: (0, 0)),
            pl.BlockSpec((Tp, H, D), lambda i, j, *refs: (0, 0, 0)),
        ]
        operands += [m0.astype(jnp.float32), l0.astype(jnp.float32),
                     acc0.astype(jnp.float32)]

    out_shape = jax.ShapeDtypeStruct((Tp, H, D), q.dtype)
    out_spec = pl.BlockSpec((Tp, H, D), lambda i, j, *refs: (0, 0, 0))
    if return_stats:
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((Tp, H), jnp.float32),
                     jax.ShapeDtypeStruct((Tp, H), jnp.float32),
                     jax.ShapeDtypeStruct((Tp, H, D), jnp.float32))
        out_spec = (out_spec,
                    pl.BlockSpec((Tp, H), lambda i, j, *refs: (0, 0)),
                    pl.BlockSpec((Tp, H), lambda i, j, *refs: (0, 0)),
                    pl.BlockSpec((Tp, H, D),
                                 lambda i, j, *refs: (0, 0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, pp),
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((Tp, H, D), jnp.float32),
            pltpu.VMEM((Tp, H), jnp.float32),
            pltpu.VMEM((Tp, H), jnp.float32),
        ],
    )
    res = pl.pallas_call(
        functools.partial(_quant_kernel, page=page, groups=groups,
                          sliding_window=sliding_window,
                          has_carry=carry is not None,
                          out_stats=return_stats),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(kv_lens.astype(jnp.int32), pi, cu_q_lens.astype(jnp.int32),
      num_seqs.astype(jnp.int32), *operands)
    if return_stats:
        out, m, l, acc = res
        return out[:T], (m[:T], l[:T], acc[:T])
    return res[:T]
