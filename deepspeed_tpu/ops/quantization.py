"""Quantization kernels (Pallas int8 + fp8 casts).

TPU-native equivalent of the reference's quantization CUDA kernels
(``csrc/quantization/``: quantize/dequantize int4/int8 symmetric/asymmetric
with group-wise scales, used by ZeRO++ qwZ weight all-gather and qgZ
quantized gradient reduce, and ``csrc/fp_quantizer/`` FP8).  Group-wise
layout: values are viewed as ``(num_groups, group_size)``; each group gets
its own scale (and offset when asymmetric) so a single outlier only damages
its group — the same layout the reference's swizzled-quant kernels use.

APIs:
- :func:`quantize` / :func:`dequantize` — int8 blockwise, symmetric or
  asymmetric, Pallas on TPU with identical-math jnp fallback.
- :func:`quantize_fp8` / :func:`dequantize_fp8` — scaled fp8 (e4m3) cast.
- the ZeRO++ qwZ/qgZ collectives live in ``comm/quantized.py`` and call
  these kernels for the wire payloads.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


class QuantizedTensor(NamedTuple):
    """int8 payload + per-group scale/offset + original shape/dtype."""
    values: jax.Array        # int8 [num_groups, group_size]
    scale: jax.Array         # f32 [num_groups, 1]
    offset: jax.Array        # f32 [num_groups, 1] (zeros when symmetric)
    shape: Tuple[int, ...]
    dtype: jnp.dtype


def _quant_kernel(x_ref, v_ref, s_ref, o_ref, *, symmetric: bool,
                  q_max: float):
    x = x_ref[:].astype(jnp.float32)  # [rows=groups_block, group_size]
    if symmetric:
        absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        scale = jnp.maximum(absmax, 1e-12) / q_max
        offset = jnp.zeros_like(scale)
    else:
        mx = jnp.max(x, axis=1, keepdims=True)
        mn = jnp.min(x, axis=1, keepdims=True)
        scale = jnp.maximum(mx - mn, 1e-12) / (2.0 * q_max)
        offset = (mx + mn) * 0.5
    q = jnp.clip(jnp.round((x - offset) / scale), -q_max, q_max)
    v_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale
    o_ref[:] = offset


def _dequant_kernel(v_ref, s_ref, o_ref, x_ref):
    x_ref[:] = (v_ref[:].astype(jnp.float32) * s_ref[:] + o_ref[:]
                ).astype(x_ref.dtype)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _grouped(x: jax.Array, group_size: int) -> Tuple[jax.Array, int]:
    n = x.size
    num_groups = pl.cdiv(n, group_size)
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32),
                   (0, num_groups * group_size - n))
    return flat.reshape(num_groups, group_size), num_groups


def quantize(x: jax.Array, num_bits: int = 8, group_size: int = 2048,
             symmetric: bool = True, interpret: bool = False
             ) -> QuantizedTensor:
    """Blockwise int8/int4-range quantization (int4 values are stored in an
    int8 payload with the int4 range, matching the reference's unpacked
    debug layout; dense 2x4-bit packing is a wire-format concern of the
    qgZ collective)."""
    assert num_bits in (4, 8)
    q_max = float(2 ** (num_bits - 1) - 1)
    xg, num_groups = _grouped(x, group_size)

    if _on_tpu() or interpret:
        rows_blk = min(256, num_groups)
        grid = (pl.cdiv(num_groups, rows_blk),)
        pad_rows = grid[0] * rows_blk - num_groups
        if pad_rows:
            xg = jnp.pad(xg, ((0, pad_rows), (0, 0)))
        blk = pl.BlockSpec((rows_blk, group_size), lambda i: (i, 0))
        sblk = pl.BlockSpec((rows_blk, 1), lambda i: (i, 0))
        v, s, o = pl.pallas_call(
            functools.partial(_quant_kernel, symmetric=symmetric,
                              q_max=q_max),
            grid=grid,
            in_specs=[blk],
            out_specs=[blk, sblk, sblk],
            out_shape=[
                jax.ShapeDtypeStruct(xg.shape, jnp.int8),
                jax.ShapeDtypeStruct((xg.shape[0], 1), jnp.float32),
                jax.ShapeDtypeStruct((xg.shape[0], 1), jnp.float32),
            ],
            interpret=interpret,
        )(xg)
        v, s, o = v[:num_groups], s[:num_groups], o[:num_groups]
    else:
        if symmetric:
            absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
            s = jnp.maximum(absmax, 1e-12) / q_max
            o = jnp.zeros_like(s)
        else:
            mx = jnp.max(xg, axis=1, keepdims=True)
            mn = jnp.min(xg, axis=1, keepdims=True)
            s = jnp.maximum(mx - mn, 1e-12) / (2.0 * q_max)
            o = (mx + mn) * 0.5
        v = jnp.clip(jnp.round((xg - o) / s), -q_max, q_max).astype(jnp.int8)
    return QuantizedTensor(values=v, scale=s, offset=o, shape=tuple(x.shape),
                           dtype=x.dtype)


def dequantize(qt: QuantizedTensor, interpret: bool = False) -> jax.Array:
    if _on_tpu() or interpret:
        num_groups, group_size = qt.values.shape
        rows_blk = min(256, num_groups)
        grid = (pl.cdiv(num_groups, rows_blk),)
        pad_rows = grid[0] * rows_blk - num_groups
        v, s, o = qt.values, qt.scale, qt.offset
        if pad_rows:
            v = jnp.pad(v, ((0, pad_rows), (0, 0)))
            s = jnp.pad(s, ((0, pad_rows), (0, 0)))
            o = jnp.pad(o, ((0, pad_rows), (0, 0)))
        blk = pl.BlockSpec((rows_blk, group_size), lambda i: (i, 0))
        sblk = pl.BlockSpec((rows_blk, 1), lambda i: (i, 0))
        x = pl.pallas_call(
            _dequant_kernel,
            grid=grid,
            in_specs=[blk, sblk, sblk],
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct(v.shape, jnp.float32),
            interpret=interpret,
        )(v, s, o)[:num_groups]
    else:
        x = qt.values.astype(jnp.float32) * qt.scale + qt.offset
    n = int(np.prod(qt.shape)) if qt.shape else 1
    return x.reshape(-1)[:n].reshape(qt.shape).astype(qt.dtype)


# ---------------------------------------------------------------------------
# FP8 (``csrc/fp_quantizer`` equivalent — straightforward on TPU: native
# fp8 dtypes + per-tensor scale)
# ---------------------------------------------------------------------------

class FP8Tensor(NamedTuple):
    values: jax.Array   # float8_e4m3fn
    scale: jax.Array    # f32 scalar
    shape: Tuple[int, ...]
    dtype: jnp.dtype


def quantize_fp8(x: jax.Array) -> FP8Tensor:
    absmax = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), 1e-12)
    fp8_max = float(jnp.finfo(jnp.float8_e4m3fn).max)
    scale = absmax / fp8_max
    v = (x.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn)
    return FP8Tensor(values=v, scale=scale, shape=tuple(x.shape),
                     dtype=x.dtype)


def dequantize_fp8(ft: FP8Tensor) -> jax.Array:
    return (ft.values.astype(jnp.float32) * ft.scale).astype(ft.dtype)
