"""Quantization kernels (Pallas int8 + fp8 casts).

TPU-native equivalent of the reference's quantization CUDA kernels
(``csrc/quantization/``: quantize/dequantize int4/int8 symmetric/asymmetric
with group-wise scales, used by ZeRO++ qwZ weight all-gather and qgZ
quantized gradient reduce, and ``csrc/fp_quantizer/`` FP8).  Group-wise
layout: values are viewed as ``(num_groups, group_size)``; each group gets
its own scale (and offset when asymmetric) so a single outlier only damages
its group — the same layout the reference's swizzled-quant kernels use.

APIs:
- :func:`quantize` / :func:`dequantize` — int8 blockwise, symmetric or
  asymmetric, Pallas on TPU with identical-math jnp fallback.
- :func:`quantize_fp8` / :func:`dequantize_fp8` — scaled fp8 (e4m3) cast.
- the ZeRO++ qwZ/qgZ collectives live in ``comm/quantized.py`` and call
  these kernels for the wire payloads.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


class QuantizedTensor(NamedTuple):
    """int8 payload + per-group scale/offset + original shape/dtype."""
    values: jax.Array        # int8 [num_groups, group_size]
    scale: jax.Array         # f32 [num_groups, 1]
    offset: jax.Array        # f32 [num_groups, 1] (zeros when symmetric)
    shape: Tuple[int, ...]
    dtype: jnp.dtype


def _quant_kernel(x_ref, v_ref, s_ref, o_ref, *, symmetric: bool,
                  q_max: float):
    x = x_ref[:].astype(jnp.float32)  # [rows=groups_block, group_size]
    if symmetric:
        absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        scale = jnp.maximum(absmax, 1e-12) / q_max
        offset = jnp.zeros_like(scale)
    else:
        mx = jnp.max(x, axis=1, keepdims=True)
        mn = jnp.min(x, axis=1, keepdims=True)
        scale = jnp.maximum(mx - mn, 1e-12) / (2.0 * q_max)
        offset = (mx + mn) * 0.5
    q = jnp.clip(jnp.round((x - offset) / scale), -q_max, q_max)
    v_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale
    o_ref[:] = offset


def _dequant_kernel(v_ref, s_ref, o_ref, x_ref):
    x_ref[:] = (v_ref[:].astype(jnp.float32) * s_ref[:] + o_ref[:]
                ).astype(x_ref.dtype)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _grouped(x: jax.Array, group_size: int) -> Tuple[jax.Array, int]:
    n = x.size
    num_groups = pl.cdiv(n, group_size)
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32),
                   (0, num_groups * group_size - n))
    return flat.reshape(num_groups, group_size), num_groups


def quantize(x: jax.Array, num_bits: int = 8, group_size: int = 2048,
             symmetric: bool = True, interpret: bool = False
             ) -> QuantizedTensor:
    """Blockwise int8/int4-range quantization (int4 values are stored in an
    int8 payload with the int4 range, matching the reference's unpacked
    debug layout; dense 2x4-bit packing is a wire-format concern of the
    qgZ collective)."""
    assert num_bits in (4, 8)
    q_max = float(2 ** (num_bits - 1) - 1)
    xg, num_groups = _grouped(x, group_size)

    if _on_tpu() or interpret:
        rows_blk = min(256, num_groups)
        grid = (pl.cdiv(num_groups, rows_blk),)
        pad_rows = grid[0] * rows_blk - num_groups
        if pad_rows:
            xg = jnp.pad(xg, ((0, pad_rows), (0, 0)))
        blk = pl.BlockSpec((rows_blk, group_size), lambda i: (i, 0))
        sblk = pl.BlockSpec((rows_blk, 1), lambda i: (i, 0))
        v, s, o = pl.pallas_call(
            functools.partial(_quant_kernel, symmetric=symmetric,
                              q_max=q_max),
            grid=grid,
            in_specs=[blk],
            out_specs=[blk, sblk, sblk],
            out_shape=[
                jax.ShapeDtypeStruct(xg.shape, jnp.int8),
                jax.ShapeDtypeStruct((xg.shape[0], 1), jnp.float32),
                jax.ShapeDtypeStruct((xg.shape[0], 1), jnp.float32),
            ],
            interpret=interpret,
        )(xg)
        v, s, o = v[:num_groups], s[:num_groups], o[:num_groups]
    else:
        if symmetric:
            absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
            s = jnp.maximum(absmax, 1e-12) / q_max
            o = jnp.zeros_like(s)
        else:
            mx = jnp.max(xg, axis=1, keepdims=True)
            mn = jnp.min(xg, axis=1, keepdims=True)
            s = jnp.maximum(mx - mn, 1e-12) / (2.0 * q_max)
            o = (mx + mn) * 0.5
        v = jnp.clip(jnp.round((xg - o) / s), -q_max, q_max).astype(jnp.int8)
    return QuantizedTensor(values=v, scale=s, offset=o, shape=tuple(x.shape),
                           dtype=x.dtype)


def dequantize(qt: QuantizedTensor, interpret: bool = False) -> jax.Array:
    if _on_tpu() or interpret:
        num_groups, group_size = qt.values.shape
        rows_blk = min(256, num_groups)
        grid = (pl.cdiv(num_groups, rows_blk),)
        pad_rows = grid[0] * rows_blk - num_groups
        v, s, o = qt.values, qt.scale, qt.offset
        if pad_rows:
            v = jnp.pad(v, ((0, pad_rows), (0, 0)))
            s = jnp.pad(s, ((0, pad_rows), (0, 0)))
            o = jnp.pad(o, ((0, pad_rows), (0, 0)))
        blk = pl.BlockSpec((rows_blk, group_size), lambda i: (i, 0))
        sblk = pl.BlockSpec((rows_blk, 1), lambda i: (i, 0))
        x = pl.pallas_call(
            _dequant_kernel,
            grid=grid,
            in_specs=[blk, sblk, sblk],
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct(v.shape, jnp.float32),
            interpret=interpret,
        )(v, s, o)[:num_groups]
    else:
        x = qt.values.astype(jnp.float32) * qt.scale + qt.offset
    n = int(np.prod(qt.shape)) if qt.shape else 1
    return x.reshape(-1)[:n].reshape(qt.shape).astype(qt.dtype)


# ---------------------------------------------------------------------------
# FP8 (``csrc/fp_quantizer`` equivalent — straightforward on TPU: native
# fp8 dtypes + per-tensor scale)
# ---------------------------------------------------------------------------

class FP8Tensor(NamedTuple):
    values: jax.Array   # float8_e4m3fn
    scale: jax.Array    # f32 scalar
    shape: Tuple[int, ...]
    dtype: jnp.dtype


def quantize_fp8(x: jax.Array) -> FP8Tensor:
    absmax = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), 1e-12)
    fp8_max = float(jnp.finfo(jnp.float8_e4m3fn).max)
    scale = absmax / fp8_max
    v = (x.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn)
    return FP8Tensor(values=v, scale=scale, shape=tuple(x.shape),
                     dtype=x.dtype)


def dequantize_fp8(ft: FP8Tensor) -> jax.Array:
    return (ft.values.astype(jnp.float32) * ft.scale).astype(ft.dtype)


# ---------------------------------------------------------------------------
# FP6 e3m2 (``csrc/fp6`` / FP6-LLM equivalent).  No native fp6 dtype
# exists, so encode/decode is explicit bit math: 1 sign + 3 exponent
# (bias 3) + 2 mantissa bits, subnormals at exponent 0, max normal 28.
# Four 6-bit codes pack into three bytes — 6 bits/param in HBM.
# ---------------------------------------------------------------------------

FP6_MAX = 28.0                       # (1 + 3/4) * 2^(7-3)
_FP6_BIAS = 3


class FP6Tensor(NamedTuple):
    values: jax.Array   # uint8 [num_groups, group_size * 3 // 4] packed
    scale: jax.Array    # f32 [num_groups, 1]
    shape: Tuple[int, ...]
    dtype: jnp.dtype
    group_size: int


def _fp6_encode(a: jax.Array) -> jax.Array:
    """|x| in [0, FP6_MAX] -> 5-bit magnitude code (3 exp | 2 mantissa),
    round-to-nearest."""
    a = jnp.clip(a, 0.0, FP6_MAX)
    e = jnp.floor(jnp.log2(jnp.maximum(a, 1e-30)))
    e = jnp.clip(e, 1 - _FP6_BIAS, 4)              # normal exponents
    sub = a < 2.0 ** (1 - _FP6_BIAS)               # subnormal range
    # subnormal: a = m/4 * 2^(1-bias) -> m = a * 16
    m_sub = jnp.round(a * (4.0 / 2.0 ** (1 - _FP6_BIAS)))
    # normal: a = (1 + m/4) * 2^e -> m = (a/2^e - 1) * 4
    m_norm = jnp.round((a / 2.0 ** e - 1.0) * 4.0)
    # mantissa rounding overflow (m == 4) bumps the exponent
    bump = m_norm >= 4.0
    e = jnp.where(bump, e + 1.0, e)
    m_norm = jnp.where(bump, 0.0, m_norm)
    over = e > 4.0
    e = jnp.where(over, 4.0, e)
    m_norm = jnp.where(over, 3.0, m_norm)
    exp_bits = jnp.where(sub, 0.0, e + _FP6_BIAS)
    m = jnp.where(sub, jnp.minimum(m_sub, 3.0), m_norm)
    return (exp_bits.astype(jnp.uint8) << 2) | m.astype(jnp.uint8)


def _fp6_decode(code: jax.Array) -> jax.Array:
    """5-bit magnitude code -> float32 value."""
    exp_bits = (code >> 2) & jnp.uint8(0x7)
    m = (code & jnp.uint8(0x3)).astype(jnp.float32)
    sub = exp_bits == 0
    val_sub = m / 4.0 * 2.0 ** (1 - _FP6_BIAS)
    val_norm = (1.0 + m / 4.0) * 2.0 ** (
        exp_bits.astype(jnp.float32) - _FP6_BIAS)
    return jnp.where(sub, val_sub, val_norm)


def _pack6(codes: jax.Array) -> jax.Array:
    """[G, gs] 6-bit codes -> [G, gs*3/4] packed bytes (4 codes/3 bytes)."""
    g, gs = codes.shape
    q = codes.reshape(g, gs // 4, 4).astype(jnp.uint32)
    word = (q[..., 0] | (q[..., 1] << 6) | (q[..., 2] << 12)
            | (q[..., 3] << 18))                   # 24 bits
    b0 = (word & 0xFF).astype(jnp.uint8)
    b1 = ((word >> 8) & 0xFF).astype(jnp.uint8)
    b2 = ((word >> 16) & 0xFF).astype(jnp.uint8)
    return jnp.stack([b0, b1, b2], axis=-1).reshape(g, gs * 3 // 4)


def _unpack6(packed: jax.Array, group_size: int) -> jax.Array:
    g = packed.shape[0]
    b = packed.reshape(g, group_size // 4, 3).astype(jnp.uint32)
    word = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
    codes = jnp.stack([word & 0x3F, (word >> 6) & 0x3F,
                       (word >> 12) & 0x3F, (word >> 18) & 0x3F], axis=-1)
    return codes.reshape(g, group_size).astype(jnp.uint8)


def quantize_fp6(x: jax.Array, group_size: int = 512) -> FP6Tensor:
    """Blockwise-scaled fp6 e3m2 quantization (reference FP6-LLM weight
    format, ``csrc/fp6``): each group scales its absmax onto FP6_MAX,
    then values round to the fp6 grid and pack 6 bits each."""
    shape, dtype = tuple(x.shape), x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    numel = flat.size
    gs = group_size
    while gs > 4 and (numel % gs or gs % 4):
        gs -= 1
    if numel % gs or gs % 4:
        pad = (-numel) % 4
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        gs = 4
    groups = flat.reshape(-1, gs)
    absmax = jnp.maximum(jnp.max(jnp.abs(groups), axis=1, keepdims=True),
                         1e-12)
    scale = absmax / FP6_MAX
    scaled = groups / scale
    sign = (scaled < 0).astype(jnp.uint8) << 5
    codes = sign | _fp6_encode(jnp.abs(scaled))
    return FP6Tensor(values=_pack6(codes), scale=scale, shape=shape,
                     dtype=dtype, group_size=gs)


def dequantize_fp6(ft: FP6Tensor) -> jax.Array:
    codes = _unpack6(ft.values, ft.group_size)
    mag = _fp6_decode(codes)
    sign = jnp.where((codes >> 5) & jnp.uint8(1), -1.0, 1.0)
    x = sign * mag * ft.scale
    n = int(np.prod(ft.shape)) if ft.shape else 1
    return x.reshape(-1)[:n].reshape(ft.shape).astype(ft.dtype)
