"""Evoformer attention (DeepSpeed4Science parity).

TPU-native equivalent of the reference's CUTLASS Evoformer kernel
(``csrc/deepspeed4science/evoformer_attn/``, Python surface
``deepspeed/ops/deepspeed4science/evoformer_attn.py``
``DS4Sci_EvoformerAttention``): attention over AlphaFold-family
activations ``[batch, n_seq, seq_len, heads, dim]`` with up to two
additive biases — the MSA mask bias ``[B, N, 1, 1, S]`` and the pair
bias ``[B, 1, H, S, S]`` — broadcast onto the logits.

Where the reference hand-fuses a CUTLASS kernel for memory efficiency,
this is a blockwise online-softmax ``lax.scan`` over key blocks: O(S)
live memory per query row, fp32 accumulation, differentiable through
JAX AD (wrap in ``jax.checkpoint`` for long-sequence training).  The
MXU sees plain batched matmuls, which is exactly what XLA tiles best —
no custom kernel is load-bearing here, so none is written.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -0.7 * float(np.finfo(np.float32).max)


def evoformer_attention_reference(q, k, v, biases: Sequence = (),
                                  sm_scale: Optional[float] = None):
    """Naive O(S^2)-memory oracle (the reference's torch fallback)."""
    B, N, S, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    logits = jnp.einsum("bnqhd,bnkhd->bnhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    for b in biases:
        logits = logits + b.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnhqk,bnkhd->bnqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def evoformer_attention(q, k, v, biases: Sequence = (),
                        sm_scale: Optional[float] = None,
                        block_k: int = 512):
    """Memory-efficient Evoformer attention.

    q, k, v: ``[B, N, S, H, D]``; ``biases``: up to two arrays
    broadcastable to ``[B, N, H, S, S]`` (reference contract: the mask
    bias ``[B, N, 1, 1, S]`` and the pair bias ``[B, 1, H, S, S]``).
    Returns ``[B, N, S, H, D]`` in ``q.dtype``.
    """
    B, N, S, H, D = q.shape
    assert k.shape == v.shape == q.shape, (q.shape, k.shape, v.shape)
    assert len(biases) <= 2, "reference API accepts at most two biases"
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    block_k = min(block_k, S)
    nk = -(-S // block_k)
    pad = nk * block_k - S

    # head-major layout for the scan: [B, N, H, S, D]
    qt = (q.astype(jnp.float32) * scale).transpose(0, 1, 3, 2, 4)
    kt = k.astype(jnp.float32).transpose(0, 1, 3, 2, 4)
    vt = v.astype(jnp.float32).transpose(0, 1, 3, 2, 4)
    if pad:
        kt = jnp.pad(kt, ((0, 0),) * 3 + ((0, pad), (0, 0)))
        vt = jnp.pad(vt, ((0, 0),) * 3 + ((0, pad), (0, 0)))
    # biases are SLICED per key block inside the scan — never broadcast
    # to the full [B, N, H, S, S] (an N*H*S*S blow-up for the typical
    # [B,N,1,1,S] mask + [B,1,H,S,S] pair pair); only each bias's last
    # (key) dim is padded to the block grid
    biases_p = []
    for b in biases:
        b = b.astype(jnp.float32)
        assert b.shape[-1] == S, (
            f"bias key dim {b.shape[-1]} != seq len {S}")
        if pad:
            b = jnp.pad(b, ((0, 0),) * (b.ndim - 1) + ((0, pad),),
                        constant_values=_NEG)
        biases_p.append(b)
    key_valid = (jnp.arange(nk * block_k) < S)

    kb = kt.reshape(B, N, H, nk, block_k, D).transpose(3, 0, 1, 2, 4, 5)
    vb = vt.reshape(B, N, H, nk, block_k, D).transpose(3, 0, 1, 2, 4, 5)
    validb = key_valid.reshape(nk, block_k)

    def step(carry, blk):
        acc, m, l = carry
        j, kblk, vblk, vmask = blk
        s = jnp.einsum("bnhqd,bnhkd->bnhqk", qt, kblk)
        for b in biases_p:
            s = s + jax.lax.dynamic_slice_in_dim(
                b, j * block_k, block_k, axis=b.ndim - 1)
        s = jnp.where(vmask[None, None, None, None, :], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bnhqk,bnhkd->bnhqd", p, vblk)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((B, N, H, S, D), jnp.float32)
    m0 = jnp.full((B, N, H, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, N, H, S), jnp.float32)
    xs = (jnp.arange(nk), kb, vb, validb)
    (acc, m, l), _ = jax.lax.scan(step, (acc0, m0, l0), xs)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 1, 3, 2, 4).astype(q.dtype)


# reference-named alias (deepspeed/ops/deepspeed4science/evoformer_attn.py)
DS4Sci_EvoformerAttention = evoformer_attention
