"""Random layer token dropping (random-LTD, arXiv:2211.11586).

Re-design of the reference ``data_routing/basic_layer.py:14
RandomLayerTokenDrop`` + ``scheduler.py:38 RandomLTDScheduler`` +
``ops/random_ltd/dropping_utils.py`` CUDA gather/scatter: wrapped
transformer layers run on a RANDOM SUBSET of tokens (the "reserved"
tokens); dropped tokens skip the layer and rejoin afterwards, unchanged —
cutting per-layer FLOPs by reserved/seq while training quality follows
the random-LTD schedule that grows reserved length back to full.

TPU-native shape discipline: the reserved length is a STATIC argument —
each new schedule value compiles one new program (the scheduler's
``increase_step`` quantizes values exactly so this stays bounded, the
same role the reference's "multiple of 8 for tensor cores" rule plays).
Gathers/scatters are ``jnp.take_along_axis`` / ``.at[].set`` — XLA's
native dynamic-gather, no custom kernel needed.

Decoder sampling keeps indices SORTED per row (the reference
``gpt_sample_tokens``) so causal order is preserved on the subsequence;
RoPE/position embeddings can consume the returned indices as positions.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def sample_token_indices(rng: jax.Array, batch: int, seq: int,
                         reserved: int, sorted_indices: bool = True
                         ) -> jax.Array:
    """[B, reserved] per-row token indices without replacement (sorted for
    decoder models — reference ``gpt_sample_tokens``; unsorted permutation
    sampling matches ``bert_sample_tokens``)."""
    # per-row random scores; top-`reserved` positions = uniform sample
    # without replacement
    scores = jax.random.uniform(rng, (batch, seq))
    _, idx = jax.lax.top_k(scores, reserved)
    if sorted_indices:
        idx = jnp.sort(idx, axis=-1)
    return idx


def gather_tokens(x: jax.Array, idx: jax.Array) -> jax.Array:
    """[B, S, H], [B, r] -> [B, r, H] (reference ``GatherTokens``)."""
    return jnp.take_along_axis(x, idx[..., None], axis=1)


def scatter_tokens(x: jax.Array, part: jax.Array, idx: jax.Array
                   ) -> jax.Array:
    """Write the layer's outputs back at their original positions
    (reference ``ScatterTokens``); un-sampled tokens pass through."""
    b = jnp.arange(x.shape[0])[:, None]
    return x.at[b, idx].set(part.astype(x.dtype))


class RandomLayerTokenDrop(nn.Module):
    """Wrap one transformer block: run it on ``reserved_length`` sampled
    tokens.  ``layer_fn`` builds/applies the wrapped block given the
    gathered hidden states and their positions."""

    layer: Any                       # nn.Module taking (x, *args)
    model_type: str = "decoder"      # decoder (sorted) | encoder

    @nn.compact
    def __call__(self, x, reserved_length: int, *layer_args,
                 rng: Optional[jax.Array] = None):
        B, S = x.shape[0], x.shape[1]
        if reserved_length >= S:
            return self.layer(x, *layer_args)
        if rng is None:
            rng = self.make_rng("random_ltd")
        idx = sample_token_indices(rng, B, S, reserved_length,
                                   sorted_indices=self.model_type ==
                                   "decoder")
        part = gather_tokens(x, idx)
        out = self.layer(part, *layer_args)
        return scatter_tokens(x, out, idx)


class RandomLTDScheduler:
    """Reserved-length schedule + layer-token accounting (reference
    ``scheduler.py:38``).  ``fixed_linear``: min -> max over
    ``require_steps``, quantized to ``increase_step`` multiples."""

    def __init__(self, config: Dict[str, Any]):
        self.model_layer_num = int(config["total_layer_num"])
        self.random_ltd_layer_num = int(config["random_ltd_layer_num"])
        self.global_batch_size = int(config.get("global_batch_size", 1))
        sched = config["random_ltd_schedule"]
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        if self.schedule_type != "fixed_linear":
            raise RuntimeError(
                f"unsupported random-LTD schedule {self.schedule_type!r}")
        self.state: Dict[str, Any] = {
            "min_value": int(sched["min_value"]),
            "max_value": int(sched["max_value"]),
            "current_value": int(sched["min_value"]),
            "require_steps": int(sched["schedule_config"]["require_steps"]),
            "increase_step": int(sched["schedule_config"]["seq_per_step"]),
            "consumed_layer_tokens": 0,
            "current_step": -1,
        }

    def get_value(self, global_steps: int) -> int:
        lo, hi = self.state["min_value"], self.state["max_value"]
        frac = float(global_steps) / self.state["require_steps"]
        val = math.floor(frac * (hi - lo) + lo)
        val -= val % self.state["increase_step"]
        return min(val, hi)

    def get_current_seq(self) -> int:
        return self.state["current_value"]

    def set_current_seq(self, v: int) -> None:
        self.state["current_value"] = v

    def get_random_ltd_layer_num(self) -> int:
        return self.random_ltd_layer_num

    def update_seq(self, global_steps: int) -> int:
        if self.state["current_value"] < self.state["max_value"]:
            self.state["current_value"] = self.get_value(global_steps)
        if global_steps != self.state["current_step"]:
            self.state["consumed_layer_tokens"] += self.global_batch_size * (
                self.state["current_value"] * self.random_ltd_layer_num +
                self.state["max_value"] *
                (self.model_layer_num - self.random_ltd_layer_num))
            self.state["current_step"] = global_steps
        return self.state["current_value"]

    def get_total_layer_tokens(self, train_iters: int) -> int:
        for step in range(train_iters):
            self.update_seq(step)
        return self.state["consumed_layer_tokens"]

    def state_dict(self) -> Dict[str, Any]:
        return dict(self.state)

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.state.update(sd)
