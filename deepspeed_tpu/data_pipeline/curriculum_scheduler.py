"""Curriculum learning difficulty scheduler.

Re-implements the reference ``data_pipeline/curriculum_scheduler.py:11
CurriculumScheduler`` semantics: a difficulty value (typically max
sequence length) that grows over training steps by one of four schedules.
Pure step math — identical on TPU; the TPU-specific part is WHERE the
difficulty lands: the engine truncates token batches to the current
difficulty, which quantizes compile shapes, so ``difficulty_step``
(multiple-of-8 in the reference for tensor cores) here also bounds the
number of XLA retraces over a run.

Schedules:

- ``fixed_discrete``: explicit (difficulty, max_step) staircase;
- ``fixed_linear``: min -> max linearly over ``total_curriculum_step``;
- ``fixed_root``: min -> max along ``(t/T)^(1/root_degree)``;
- ``custom``: user function via :meth:`set_custom_get_difficulty`.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            assert key in config, f"curriculum learning requires {key!r}"
        self.state: Dict[str, Any] = {
            "min_difficulty": int(config["min_difficulty"]),
            "max_difficulty": int(config["max_difficulty"]),
            "current_difficulty": int(config["min_difficulty"]),
            "schedule_type": config["schedule_type"],
        }
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None
        stype = config["schedule_type"]
        sconf = dict(config.get("schedule_config", {}))
        if stype == "fixed_discrete":
            diffs = sconf.get("difficulty")
            steps = sconf.get("max_step")
            assert diffs and steps is not None, (
                "fixed_discrete needs schedule_config.difficulty and "
                ".max_step")
            assert len(diffs) == len(steps) + 1, (
                "difficulty must have one more entry than max_step (the "
                "last difficulty holds forever)")
            self.state["schedule"] = {"difficulty": list(diffs),
                                      "max_step": list(steps)}
        elif stype in ("fixed_linear", "fixed_root"):
            assert "total_curriculum_step" in sconf, (
                f"{stype} needs schedule_config.total_curriculum_step")
            assert "difficulty_step" in sconf, (
                f"{stype} needs schedule_config.difficulty_step")
            if stype == "fixed_root":
                assert "root_degree" in sconf, (
                    "fixed_root needs schedule_config.root_degree")
            if int(sconf["difficulty_step"]) % 8 != 0:
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "curriculum difficulty_step should be a multiple of 8 "
                    "for seqlen metrics: it quantizes compiled shapes "
                    "(bounding XLA retraces) and keeps the MXU tiled")
            self.state["schedule"] = sconf
        elif stype == "custom":
            pass
        else:
            raise RuntimeError(f"unsupported curriculum schedule {stype!r}")

    # -- reference API --------------------------------------------------

    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def get_state(self) -> Dict[str, Any]:
        return self.state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.state = state

    # -- schedules ------------------------------------------------------

    def _discrete(self, step: int) -> int:
        sched = self.state["schedule"]
        for diff, max_step in zip(sched["difficulty"], sched["max_step"]):
            if step <= max_step:
                return diff
        return sched["difficulty"][-1]

    def _root(self, step: int, degree: float) -> int:
        sched = self.state["schedule"]
        lo, hi = self.state["min_difficulty"], self.state["max_difficulty"]
        frac = (float(step) / sched["total_curriculum_step"]) ** (1.0 / degree)
        diff = math.floor(frac * (hi - lo) + lo)
        diff -= diff % sched["difficulty_step"]
        return min(diff, hi)

    def get_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == "fixed_discrete":
            return self._discrete(global_steps)
        if stype == "fixed_linear":
            return self._root(global_steps, 1.0)
        if stype == "fixed_root":
            return self._root(global_steps,
                              self.state["schedule"]["root_degree"])
        assert self.custom_get_difficulty is not None, (
            "custom schedule: call set_custom_get_difficulty first")
        return self.custom_get_difficulty(global_steps)

    def update_difficulty(self, global_steps: int) -> int:
        d = self.get_difficulty(global_steps)
        self.state["current_difficulty"] = d
        return d
