from deepspeed_tpu.data_pipeline.curriculum_scheduler import \
    CurriculumScheduler
from deepspeed_tpu.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.data_pipeline.indexed_dataset import (
    IndexedDatasetBuilder, MMapIndexedDataset)
from deepspeed_tpu.data_pipeline.random_ltd import (RandomLayerTokenDrop,
                                                    RandomLTDScheduler)

__all__ = ["CurriculumScheduler", "DeepSpeedDataSampler",
           "IndexedDatasetBuilder", "MMapIndexedDataset",
           "RandomLayerTokenDrop", "RandomLTDScheduler"]
