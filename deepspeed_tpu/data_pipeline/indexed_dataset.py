"""Memory-mapped ragged-sequence dataset.

TPU-native equivalent of the reference's ``MMapIndexedDataset``
(``runtime/data_pipeline/data_sampling/indexed_dataset.py:369``, the
Megatron ``.bin``/``.idx`` pair): token sequences of varying length
stored contiguously in one binary blob, with an index giving each
sequence's dtype, length, and byte offset.  Reads are ``np.memmap``
views — no copy, no parse, O(1) open time regardless of corpus size —
which is what keeps host-side input pipelines off the profile at
training time.

Format (little-endian):

    <path>.bin   raw sample data, concatenated
    <path>.idx   magic 'DSTPUIDX' | version u32 | dtype code u32 |
                 count u64 | sizes u64[count] | offsets u64[count]

Offsets are in ELEMENTS (not bytes) into the flat blob, so a slice is
``blob[offsets[i] : offsets[i] + sizes[i]]``.
"""
from __future__ import annotations

import os
import struct
from typing import Sequence, Union

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

# stable on-disk dtype codes (subset of the reference's _code_to_dtype)
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class IndexedDatasetBuilder:
    """Streaming writer: ``add_item`` per sequence, then ``finalize``.

    (reference ``MMapIndexedDatasetBuilder``; also supports
    ``merge_file_`` for combining per-worker shards.)
    """

    def __init__(self, prefix: str, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        d = os.path.dirname(prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        self._data = open(data_file_path(prefix), "wb")
        self._sizes: list = []
        self._offsets: list = []
        self._tell = 0                      # in elements

    def add_item(self, array: Union[np.ndarray, Sequence]) -> None:
        arr = np.ascontiguousarray(np.asarray(array), dtype=self.dtype)
        if arr.ndim != 1:
            arr = arr.reshape(-1)
        self._data.write(arr.tobytes(order="C"))
        self._offsets.append(self._tell)
        self._sizes.append(arr.size)
        self._tell += arr.size

    def merge_file_(self, other_prefix: str) -> None:
        """Append another indexed dataset written with the same dtype
        (per-worker shard merging, reference ``merge_file_``)."""
        other = MMapIndexedDataset(other_prefix)
        if other._dtype != self.dtype:
            raise ValueError(
                f"dtype mismatch: {other._dtype} vs {self.dtype}")
        with open(data_file_path(other_prefix), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                self._data.write(chunk)
        for size in other.sizes:
            self._offsets.append(self._tell)
            self._sizes.append(int(size))
            self._tell += int(size)

    def finalize(self) -> None:
        self._data.close()
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(np.asarray(self._sizes, np.uint64).tobytes())
            f.write(np.asarray(self._offsets, np.uint64).tobytes())


class MMapIndexedDataset:
    """Zero-copy reads of a finalized dataset: ``ds[i]`` is a memmap
    view (wrap in ``np.array`` to own the memory)."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(
                    f"{index_file_path(prefix)}: not a DSTPU indexed "
                    f"dataset (bad magic {magic!r})")
            version, code = struct.unpack("<II", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (count,) = struct.unpack("<Q", f.read(8))
            raw_sizes = f.read(8 * count)
            raw_offsets = f.read(8 * count)
            if len(raw_sizes) != 8 * count or len(raw_offsets) != 8 * count:
                raise ValueError(
                    f"{index_file_path(prefix)}: truncated index "
                    f"(expected {count} entries)")
            self.sizes = np.frombuffer(raw_sizes, np.uint64)
            self._offsets = np.frombuffer(raw_offsets, np.uint64)
        self._dtype = np.dtype(_DTYPES[code])
        if os.path.getsize(data_file_path(prefix)) == 0:
            # np.memmap refuses empty files; an empty shard is legal
            self._blob = np.empty((0,), self._dtype)
        else:
            self._blob = np.memmap(data_file_path(prefix),
                                   dtype=self._dtype, mode="r")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        off, size = int(self._offsets[i]), int(self.sizes[i])
        return self._blob[off:off + size]

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        return (os.path.exists(index_file_path(prefix)) and
                os.path.exists(data_file_path(prefix)))
