"""Curriculum-aware data sampler.

Re-design of the reference ``data_sampling/data_sampler.py:37
DeepSpeedDataSampler``: an index iterator that, under curriculum
learning, restricts each global batch to samples whose difficulty metric
is within the current threshold, growing the eligible pool as training
progresses.  The reference pipelines mmap-indexed offline metric files
produced by its ``data_analyzer`` (880 LoC of distributed map-reduce);
here metric values are plain in-memory numpy arrays — on TPU hosts the
metric table for even a billion-sample corpus (one int per sample) fits
host RAM, and anything bigger can memory-map the array itself.

Semantics kept from the reference:

- ``difficulty_type``: "value" (samples with metric <= difficulty) or
  "percentile" (samples whose metric percentile <= difficulty);
- ``clustering_type``: "single_cluster" (one pool, no curriculum order
  within) vs "schedule_based" (new difficulty admits a freshly shuffled
  cluster appended to the pool);
- deterministic given the seed; each data-parallel rank draws its
  disjoint micro-batch slice; state save/load for resume.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class DeepSpeedDataSampler:
    def __init__(self, total_samples: int, micro_batch_size: int,
                 data_parallel_rank: int, data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 curriculum_metrics: Optional[Dict[str, np.ndarray]] = None,
                 curriculum_schedulers: Optional[Dict[str, Any]] = None,
                 difficulty_type: Optional[Dict[str, str]] = None,
                 clustering_type: Optional[Dict[str, str]] = None,
                 seed: int = 1234, drop_last: bool = True):
        from deepspeed_tpu.data_pipeline.curriculum_scheduler import \
            CurriculumScheduler

        self.total_samples = int(total_samples)
        self.micro_batch_size = int(micro_batch_size)
        self.dp_rank = int(data_parallel_rank)
        self.dp_size = int(data_parallel_size)
        self.gas = int(gradient_accumulation_steps)
        self.global_batch_size = (self.micro_batch_size * self.dp_size *
                                  self.gas)
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(seed)
        self.consumed_samples = 0
        self.curriculum_step = 0

        self.metrics = curriculum_metrics or {}
        self.schedulers: Dict[str, CurriculumScheduler] = {}
        for name, cfg in (curriculum_schedulers or {}).items():
            self.schedulers[name] = (cfg if isinstance(cfg,
                                                       CurriculumScheduler)
                                     else CurriculumScheduler(cfg))
        self.difficulty_type = difficulty_type or {
            n: "value" for n in self.metrics}
        self.clustering_type = clustering_type or {
            n: "schedule_based" for n in self.metrics}
        for name in self.schedulers:
            assert name in self.metrics, (
                f"curriculum metric {name!r} has a scheduler but no "
                "metric values")
            assert len(self.metrics[name]) == self.total_samples, (
                f"metric {name!r} has {len(self.metrics[name])} values "
                f"for {self.total_samples} samples")

        # the eligible pool: sample indices admitted so far, in admission
        # order (each admission wave shuffled independently)
        self._pool: np.ndarray = np.empty((0,), np.int64)
        self._admitted = np.zeros((self.total_samples,), bool)
        self._pool_pos = 0
        if not self.schedulers:           # no curriculum: admit everything
            self._admit(np.arange(self.total_samples))

    def __len__(self) -> int:
        return self.total_samples

    # -- curriculum pool management -------------------------------------

    def _admit(self, idx: np.ndarray) -> None:
        idx = idx[~self._admitted[idx]]
        if idx.size == 0:
            return
        self._admitted[idx] = True
        wave = idx.copy()
        self.np_rng.shuffle(wave)
        self._pool = np.concatenate([self._pool, wave])

    def _eligible(self, name: str, difficulty: float) -> np.ndarray:
        vals = np.asarray(self.metrics[name])
        if self.difficulty_type[name] == "percentile":
            thresh = np.percentile(vals, difficulty)
            return np.nonzero(vals <= thresh)[0]
        return np.nonzero(vals <= difficulty)[0]

    def _update_curriculum(self) -> None:
        if not self.schedulers:
            return
        self.curriculum_step += 1
        admitted: Optional[np.ndarray] = None
        for name, sched in self.schedulers.items():
            d = sched.update_difficulty(self.curriculum_step)
            ok = self._eligible(name, d)
            admitted = ok if admitted is None else np.intersect1d(admitted,
                                                                  ok)
        if self.clustering_type.get(next(iter(self.schedulers)),
                                    "schedule_based") == "single_cluster":
            # one flat pool: re-admit everything eligible, keep flat order
            self._admit(admitted)
        else:
            self._admit(admitted)

    # -- iteration ------------------------------------------------------

    def _next_global_batch(self) -> Optional[np.ndarray]:
        self._update_curriculum()
        need = self.global_batch_size
        remaining = self._pool.size - self._pool_pos
        if remaining < need:
            if self.drop_last or remaining == 0:
                # wrap: reshuffle the whole admitted pool and restart
                if self._pool.size < need:
                    return None           # not enough eligible samples yet
                wrapped = self._pool.copy()
                self.np_rng.shuffle(wrapped)
                self._pool = wrapped
                self._pool_pos = 0
            else:
                batch = self._pool[self._pool_pos:]
                self._pool_pos = self._pool.size
                return batch
        batch = self._pool[self._pool_pos:self._pool_pos + need]
        self._pool_pos += need
        return batch

    def __iter__(self) -> Iterator[List[int]]:
        """Yields this rank's micro-batch index lists, ``gas`` per global
        batch (reference ``__iter__`` contract: rank-sliced)."""
        while True:
            batch = self._next_global_batch()
            if batch is None:
                return
            self.consumed_samples += batch.size
            per_rank = batch.reshape(self.gas, self.dp_size,
                                     -1)[:, self.dp_rank, :] \
                if batch.size == self.global_batch_size else None
            if per_rank is None:
                # ragged tail (drop_last=False): round-robin slice
                tail = batch[self.dp_rank::self.dp_size]
                if tail.size:
                    yield tail.tolist()
                return
            for micro in per_rank:
                yield micro.tolist()

    # -- resume ---------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "consumed_samples": self.consumed_samples,
            "curriculum_step": self.curriculum_step,
            "pool": self._pool.copy(),
            "pool_pos": self._pool_pos,
            "admitted": self._admitted.copy(),
            "rng": self.np_rng.bit_generator.state,
            "schedulers": {n: s.get_state()
                           for n, s in self.schedulers.items()},
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.consumed_samples = sd["consumed_samples"]
        self.curriculum_step = sd["curriculum_step"]
        self._pool = np.asarray(sd["pool"])
        self._pool_pos = sd["pool_pos"]
        self._admitted = np.asarray(sd["admitted"])
        self.np_rng.bit_generator.state = sd["rng"]
        for n, st in sd.get("schedulers", {}).items():
            self.schedulers[n].set_state(st)
