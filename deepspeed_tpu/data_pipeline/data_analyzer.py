"""Offline dataset analysis for curriculum learning.

Re-design of the reference ``data_sampling/data_analyzer.py:22
DataAnalyzer`` (+ ``:455 DistributedDataAnalyzer``): compute per-sample
difficulty metrics over a dataset once, persist them, and hand them to
:class:`~deepspeed_tpu.data_pipeline.DeepSpeedDataSampler`.  The
reference shards the scan across ranks and merges mmap index files;
here the scan is a plain (optionally process-parallel) map that writes
``.npy`` arrays — the metric table is one scalar per sample, so even
billion-sample corpora fit host storage trivially, and the sampler
memory-maps the result.

Built-in metrics mirror the reference's curriculum examples:
``seqlen`` (non-padding token count) and ``vocab_rarity``
(mean -log frequency of the sample's tokens).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterable, Mapping, Optional

import numpy as np


def seqlen_metric(sample, pad_token_id: int = 0) -> int:
    ids = np.asarray(sample["input_ids"] if isinstance(sample, Mapping)
                     else sample)
    return int((ids != pad_token_id).sum())


def make_vocab_rarity_metric(token_counts: np.ndarray):
    """Mean -log p(token) under the corpus unigram distribution — the
    reference's vocab-rarity curriculum metric."""
    p = token_counts.astype(np.float64)
    p = p / max(p.sum(), 1.0)
    neglogp = -np.log(np.maximum(p, 1e-12))

    def metric(sample) -> float:
        ids = np.asarray(sample["input_ids"] if isinstance(sample, Mapping)
                         else sample).reshape(-1)
        return float(neglogp[ids].mean())

    return metric


class DataAnalyzer:
    """``run(dataset)`` -> {metric_name: np.ndarray[num_samples]}.

    ``metric_functions``: {name: fn(sample) -> number}.  ``save_path``
    persists each metric as ``<name>_metric_values.npy`` (the reference's
    ``*_metric_values`` file naming) for later ``load_metrics``.
    """

    def __init__(self, metric_functions: Dict[str, Callable[[Any], float]],
                 save_path: Optional[str] = None, num_workers: int = 1,
                 worker_id: int = 0):
        assert metric_functions, "no metric functions given"
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.num_workers = max(int(num_workers), 1)
        self.worker_id = int(worker_id)

    def run(self, dataset) -> Dict[str, np.ndarray]:
        """Scan this worker's stride of the dataset.  With
        ``num_workers > 1`` each worker computes samples
        ``worker_id::num_workers`` (reference rank-sharded scan); merge
        with :meth:`merge_worker_results`."""
        n = len(dataset)
        idxs = range(self.worker_id, n, self.num_workers)
        out = {name: np.zeros((n,), np.float32)
               for name in self.metric_functions}
        mask = np.zeros((n,), bool)
        for i in idxs:
            sample = dataset[i]
            mask[i] = True
            for name, fn in self.metric_functions.items():
                out[name][i] = fn(sample)
        if self.num_workers > 1:
            out["_computed_mask"] = mask.astype(np.float32)
        if self.save_path is not None:
            os.makedirs(self.save_path, exist_ok=True)
            suffix = (f"_w{self.worker_id}" if self.num_workers > 1
                      else "")
            for name, vals in out.items():
                np.save(os.path.join(
                    self.save_path, f"{name}_metric_values{suffix}.npy"),
                    vals)
        return out

    @staticmethod
    def merge_worker_results(results: Iterable[Dict[str, np.ndarray]]
                             ) -> Dict[str, np.ndarray]:
        """Combine per-worker strided scans into full metric tables."""
        results = list(results)
        assert results
        merged: Dict[str, np.ndarray] = {}
        masks = [r["_computed_mask"].astype(bool) for r in results]
        for name in results[0]:
            if name == "_computed_mask":
                continue
            vals = np.zeros_like(results[0][name])
            for r, m in zip(results, masks):
                vals[m] = r[name][m]
            merged[name] = vals
        covered = np.zeros_like(masks[0])
        for m in masks:
            covered |= m
        assert covered.all(), "workers did not cover every sample"
        return merged

    @staticmethod
    def load_metrics(save_path: str) -> Dict[str, np.ndarray]:
        out = {}
        for fname in os.listdir(save_path):
            if fname.endswith("_metric_values.npy"):
                out[fname[:-len("_metric_values.npy")]] = np.load(
                    os.path.join(save_path, fname), mmap_mode="r")
        return out


# ---------------------------------------------------------------------------
# Distributed map/reduce tier (reference data_analyzer.py:455
# DistributedDataAnalyzer)
# ---------------------------------------------------------------------------

# fork-inherited worker context: pool.map pickles its ARGUMENTS even
# under the fork start method, which breaks closure-based metrics (e.g.
# make_vocab_rarity_metric) and serializes the whole dataset through a
# pipe — globals set before Pool() are inherited by fork for free
_DDA_CTX: Dict[str, Any] = {}


def _dda_worker(worker_id: int):
    """One worker's map phase (module-level for multiprocessing; reads
    the fork-inherited context, receives only its worker id).  Returns
    ``(single_value_results, accumulate_partials)`` — accumulate metrics
    sum their strided partials associatively in the parent reduce."""
    ds = _DDA_CTX["dataset"]
    w = _DDA_CTX["w"]
    singles = (DataAnalyzer(_DDA_CTX["fns"], num_workers=w,
                            worker_id=worker_id).run(ds)
               if _DDA_CTX["fns"] else {})
    accums = {}
    for name, fn in _DDA_CTX["accums"].items():
        acc = None
        for i in range(worker_id, len(ds), w):
            v = np.asarray(fn(ds[i]), np.float64)
            acc = v if acc is None else acc + v
        accums[name] = acc
    return singles, accums


class DistributedDataAnalyzer:
    """Map/reduce dataset analysis across worker processes.

    Re-design of the reference ``DistributedDataAnalyzer``
    (``data_analyzer.py:455``): the reference maps over torch-dist ranks
    with per-rank thread splits and merges via collective sorts; here the
    map phase forks ``num_workers`` local processes (each scanning its
    stride — one JAX host process drives all chips, so dataset analysis
    parallelism is process-level, not rank-level), and the reduce phase
    merges in the parent and writes the metric tables plus sorted
    sample-order indices.

    ``metric_types`` per metric (reference semantics):

    - ``"single_value_per_sample"`` (default): one float per sample;
      merged table ``[num_samples]``, plus
      ``<name>_index_to_sample_sorted.npy`` — sample ids ordered by
      metric value (the reference's metric_to_sample index, used to form
      curriculum difficulty buckets).
    - ``"accumulate_value_over_samples"``: the metric returns an ARRAY
      accumulated (summed) over samples — e.g. a vocabulary histogram;
      merged by summing worker partials.
    """

    def __init__(self, metric_functions: Dict[str, Callable[[Any], Any]],
                 metric_types: Optional[Dict[str, str]] = None,
                 save_path: Optional[str] = None,
                 num_workers: Optional[int] = None):
        assert metric_functions, "no metric functions given"
        self.metric_functions = dict(metric_functions)
        self.metric_types = dict(metric_types or {})
        for name, t in self.metric_types.items():
            assert t in ("single_value_per_sample",
                         "accumulate_value_over_samples"), t
            assert name in self.metric_functions, name
        self.save_path = save_path
        self.num_workers = num_workers or min(os.cpu_count() or 1, 8)

    def _split(self):
        singles = {n: f for n, f in self.metric_functions.items()
                   if self.metric_types.get(n, "single_value_per_sample")
                   == "single_value_per_sample"}
        accums = {n: f for n, f in self.metric_functions.items()
                  if n not in singles}
        return singles, accums

    def run(self, dataset) -> Dict[str, np.ndarray]:
        import multiprocessing as mp

        singles, accums = self._split()
        n = len(dataset)
        if n == 0:
            return {}
        w = max(1, min(self.num_workers, n))
        merged: Dict[str, np.ndarray] = {}
        if w == 1:
            parts = []
            if singles:
                merged.update(DataAnalyzer(singles).run(dataset))
            for name, fn in accums.items():
                acc = None
                for i in range(n):
                    v = np.asarray(fn(dataset[i]), np.float64)
                    acc = v if acc is None else acc + v
                merged[name] = acc.astype(np.float32)
        else:
            ctx = mp.get_context("fork")
            _DDA_CTX.update(dataset=dataset, fns=singles, accums=accums,
                            w=w)
            try:
                with ctx.Pool(w) as pool:
                    parts = pool.map(_dda_worker, range(w))
            finally:
                _DDA_CTX.clear()
            if singles:
                merged.update(DataAnalyzer.merge_worker_results(
                    [p[0] for p in parts]))
            for name in accums:
                partials = [p[1][name] for p in parts
                            if p[1][name] is not None]
                merged[name] = sum(partials).astype(np.float32)
        if self.save_path is not None:
            os.makedirs(self.save_path, exist_ok=True)
            for name, vals in merged.items():
                np.save(os.path.join(self.save_path,
                                     f"{name}_metric_values.npy"), vals)
                if name in singles:
                    np.save(os.path.join(
                        self.save_path,
                        f"{name}_index_to_sample_sorted.npy"),
                        np.argsort(vals, kind="stable").astype(np.int64))
        return merged
