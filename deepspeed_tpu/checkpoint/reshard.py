"""World re-slicing math: map a partitioned leaf from world W to W′.

The ZeRO stage-3 flat layout (reference ``zero_to_fp32`` /
``ds_to_universal``) pads every param to ``ceil(numel / world)`` elements
per rank and round-robins the padded slices — so a checkpoint written at
world W cannot be read back at W′ by reinterpreting offsets; the slices
must be gathered to the full tensor and re-cut.  This module holds that
math in one place, shared by the reference-checkpoint importer
(:mod:`~deepspeed_tpu.checkpoint.ds_import`), the NVMe moment swapper's
topology-change path (:mod:`~deepspeed_tpu.runtime.swap_tensor`), and
the elastic agent's re-slice story.

Everything here is per-LEAF and pure numpy: callers iterate leaves so no
more than one full tensor is ever materialized at a time, which is what
keeps W→W′ re-sharding inside the memory budget of a single host.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "padded_partition_size",
    "partition_padded",
    "gather_padded_partitions",
    "reshard_padded_partitions",
    "assemble_from_slices",
]

# explicit slice record: ((start, stop), ...) — one (start, stop) pair
# per dimension, matching swap_tensor's normalized index form
Slices = Tuple[Tuple[int, int], ...]


def padded_partition_size(numel: int, world: int) -> int:
    """``ceil(numel / world)`` — the per-rank padded slice length of the
    stage-3 round-robin layout."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return -(-int(numel) // int(world))


def partition_padded(full: np.ndarray, world: int) -> List[np.ndarray]:
    """Cut ``full`` (any shape) into ``world`` padded flat slices.

    Every slice has exactly ``padded_partition_size(numel, world)``
    elements; the tail of the last slice is zero-padded (the reference
    layout's round-robin padding).  Inverse of
    :func:`gather_padded_partitions`.
    """
    flat = np.ascontiguousarray(full).reshape(-1)
    per = padded_partition_size(flat.size, world)
    parts: List[np.ndarray] = []
    for rk in range(world):
        sl = flat[rk * per:(rk + 1) * per]
        if sl.size < per:                      # uneven tail -> pad
            sl = np.concatenate(
                [sl, np.zeros(per - sl.size, dtype=flat.dtype)])
        parts.append(sl)
    return parts


def gather_padded_partitions(parts: Sequence[np.ndarray],
                             numel: int) -> np.ndarray:
    """Concatenate per-rank padded slices and strip the padding — the
    flat full tensor (caller reshapes).  Inverse of
    :func:`partition_padded`."""
    world = len(parts)
    if world < 1:
        raise ValueError("gather needs at least one partition")
    per = padded_partition_size(numel, world)
    for rk, p in enumerate(parts):
        if p.size != per:
            raise ValueError(
                f"partition {rk} holds {p.size} elements, layout expects "
                f"{per} (numel {numel} @ world {world})")
    return np.concatenate([np.asarray(p).reshape(-1)
                           for p in parts])[:numel]


def reshard_padded_partitions(parts: Sequence[np.ndarray], numel: int,
                              new_world: int) -> List[np.ndarray]:
    """Map one leaf's padded partitions from world ``len(parts)`` to
    ``new_world`` — gather then re-cut, materializing only this leaf."""
    return partition_padded(gather_padded_partitions(parts, numel),
                            new_world)


def assemble_from_slices(
        shape: Sequence[int],
        shards: Iterable[Tuple[Slices, np.ndarray]],
        dtype=np.float32,
        fill: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Rebuild one full leaf from explicit slice records.

    ``shards`` yields ``(slices, data)`` where ``slices`` is the
    normalized ``((start, stop), ...)`` index (one pair per dim, the
    form swap_tensor records in ``swap_meta``) and ``data`` the shard's
    values (flat or shaped).  Returns ``(full, covered)`` — the
    assembled array and a bool mask of which elements some shard
    provided, so the caller can distinguish "re-sharded" from "restarts
    from zero" per element.  Overlapping shards are last-writer-wins
    (identical by construction when they come from one save).
    """
    shape = tuple(int(d) for d in shape)
    full = np.full(shape, fill, dtype=dtype)
    covered = np.zeros(shape, dtype=bool)
    for slices, data in shards:
        idx = tuple(slice(int(a), int(b)) for a, b in slices)
        ext = tuple(int(b) - int(a) for a, b in slices)
        full[idx] = np.asarray(data, dtype=dtype).reshape(ext)
        covered[idx] = True
    return full, covered
