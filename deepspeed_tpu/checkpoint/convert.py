"""Offline ZeRO-checkpoint consolidation CLI.

Reference: ``deepspeed/utils/zero_to_fp32.py`` — the script users run
next to a checkpoint directory to merge ZeRO shards into one fp32 state
dict.  The sharded store here is topology-independent, so "consolidation"
is just reading every record at full shape (no per-stage merge logic)::

    python -m deepspeed_tpu.checkpoint.convert <ckpt_dir> <out.pkl>
    python -m deepspeed_tpu.checkpoint.convert <ckpt_dir> <out.npz> --tag t5

(The module is named ``convert`` so it does not shadow the package's
``zero_to_fp32`` *function* export.)

Output: ``.npz`` (numpy archive) when the filename ends in .npz, else a
pickle of ``{param_path: np.float32 ndarray}`` — loadable without jax,
torch, or this package.
"""
from __future__ import annotations

import argparse
import os
import pickle


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="merge a deepspeed_tpu checkpoint's module weights "
                    "into a single fp32 state dict (offline; no devices)")
    p.add_argument("checkpoint_dir", help="directory passed to "
                   "save_checkpoint (holds 'latest' + tag dirs)")
    p.add_argument("output_file", help="destination .pkl or .npz")
    p.add_argument("--tag", default=None,
                   help="checkpoint tag (default: the 'latest' file)")
    args = p.parse_args(argv)

    import json

    from deepspeed_tpu.checkpoint import sharded
    from deepspeed_tpu.checkpoint.engine import (LATEST_FILE, META_FILE,
                                                 zero_to_fp32)

    tag = args.tag
    if tag is None:
        with open(os.path.join(args.checkpoint_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    # incomplete multi-process saves (crash / still-writing) would
    # silently drop the missing processes' tensors — refuse, like
    # load_checkpoint does
    meta_path = os.path.join(args.checkpoint_dir, tag, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            procs = json.load(f).get("process_count", 1)
        if not sharded.is_complete(os.path.join(args.checkpoint_dir, tag),
                                   procs):
            raise SystemExit(
                f"checkpoint {tag!r} is incomplete: not all of its "
                f"{procs} processes finished writing")

    state = zero_to_fp32(args.checkpoint_dir, tag=tag)
    out = args.output_file
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    if out.endswith(".npz"):
        import numpy as np

        np.savez(out, **state)
    else:
        with open(out, "wb") as f:
            pickle.dump(state, f)
    total = sum(v.size for v in state.values())
    print(f"wrote {len(state)} tensors ({total:,} fp32 elements) -> {out}")


if __name__ == "__main__":
    main()
