"""Sharded, topology-independent checkpoint store.

Re-design of the reference's scalable checkpoint machinery — universal
checkpoint (``deepspeed/checkpoint/ds_to_universal.py:112`` extract shards,
``:232`` merge tp slices), per-rank ZeRO shard files
(``engine.py:3213 _save_zero_checkpoint``), and the async Nebula engine
(``runtime/checkpoint_engine/nebula_checkpoint_engine.py``) — built
TPU-first instead of as an offline conversion step:

- **Universal by default.** Every leaf is keyed by its pytree path with its
  GLOBAL shape; shard records carry the global index (slice per dim) they
  cover.  No (dp, tp, pp)-specific layout exists on disk, so there is
  nothing to convert: any mesh loads any checkpoint.
- **Per-process sharded write.** Each process writes only the addressable
  shards whose ``replica_id == 0`` (exactly one copy of each array region
  cluster-wide) into one binary blob + JSON index per process.  Host memory
  per process is bounded by its largest shard, never the model size — the
  reference's rank-0 ``torch.save`` of consolidated state is exactly what
  this avoids.
- **Reshard on load.** ``jax.make_array_from_callback`` asks for precisely
  the global slices each destination device needs; the reader assembles
  them from whichever saved shard records overlap, so an 8-way ZeRO-3
  checkpoint loads onto a 4-way TP=2 mesh (or a single host) without ever
  materializing a full array per device.
- **Async save.** D2H transfer happens synchronously (a snapshot), file IO
  runs on a background thread (Nebula's "tier-1" semantics); ``wait()``
  joins the in-flight save.
"""
from __future__ import annotations

import json
import os
import threading
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.retry import retriable
from deepspeed_tpu.utils.logging import logger

INDEX_FILE = "index_p{proc}.json"
BLOB_FILE = "shards_p{proc}.bin"
DONE_FILE = "done_p{proc}"


def path_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in kp)


def _index_to_slices(index, shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_tree(tree: Any, path: str, materialize: bool = True
              ) -> Dict[str, Any]:
    """Plan this process's shard writes of ``tree`` (jax.Arrays) under
    ``path``; hand the result to :func:`write_snapshot`.

    ``materialize=True`` copies every shard to host up front — a consistent
    snapshot safe to write asynchronously while training donates/overwrites
    the source buffers.  Host memory: this process's full partition (the
    async cost).  ``materialize=False`` keeps device references and
    :func:`write_snapshot` streams them shard-by-shard — host memory
    bounded by the largest single shard, but the tree must not be mutated
    until the write completes (sync saves).
    """
    records, buffers = [], []
    offset = 0
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        leaf = jax.numpy.asarray(leaf)
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue               # exactly one copy cluster-wide
            nbytes = int(np.prod(shard.data.shape) *
                         shard.data.dtype.itemsize)
            records.append({
                "path": path_str(kp),
                "dtype": np.dtype(shard.data.dtype).name,
                "global_shape": list(leaf.shape),
                "slices": _index_to_slices(shard.index, leaf.shape),
                "offset": offset,
                "nbytes": nbytes,
            })
            # D2H one shard at a time when materializing
            buffers.append(np.asarray(shard.data) if materialize
                           else shard.data)
            offset += nbytes
    return {"records": records, "buffers": buffers, "dir": path,
            "proc": jax.process_index()}


def _aio_handle():
    """Thread-pooled native writer (deepspeed_tpu.io, the DeepNVMe
    equivalent); None when no toolchain is available."""
    global _AIO
    if _AIO is _UNSET:
        _AIO = None
        try:
            from deepspeed_tpu.io import AsyncIOBuilder

            if AsyncIOBuilder().is_compatible():
                _AIO = AsyncIOBuilder().load().aio_handle(
                    block_size=8 << 20, thread_count=4)
        except Exception as e:  # pragma: no cover - toolchain-dependent
            from deepspeed_tpu.utils.logging import logger

            logger.warning(f"native aio unavailable ({e}); checkpoint "
                           "writes fall back to buffered python IO")
    return _AIO


_UNSET = object()
_AIO = _UNSET


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """Durably record directory entries (the rename that commits a tag
    is only crash-safe once its parent directory is synced)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform without dir-open
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@retriable(retry_on=(OSError,))
def _write_blob_python(blob: str, buffers, records) -> None:
    """Buffered-python blob write: one contiguous record stream, crc32
    recorded per record, fsync'd before the manifest is written.
    Idempotent (rewrites from the start), so transient OSErrors retry
    with backoff."""
    faults.hook("ckpt.write_blob", path=blob)
    with open(blob, "wb") as f:
        for i, (buf, rec) in enumerate(zip(buffers, records)):
            data = np.ascontiguousarray(np.asarray(buf)).tobytes()
            action = faults.hook("ckpt.write_record", path=blob, index=i,
                                 nbytes=len(data))
            if action is not None and action[0] == "torn":
                f.write(data[:max(1, int(len(data) * action[1]))])
                _fsync_file(f)
                raise faults.SimulatedCrash(
                    f"torn write: record {i} of {blob} cut short")
            rec["crc32"] = zlib.crc32(data)
            f.write(data)
        _fsync_file(f)


@retriable(retry_on=(OSError,))
def _write_index(index: str, records) -> None:
    faults.hook("ckpt.write_index", path=index)
    with open(index, "w") as f:
        json.dump({"records": records}, f)
        _fsync_file(f)


def write_snapshot(snap: Dict[str, Any]) -> None:
    """File IO half of a save (runs on the async thread).  Writes the blob
    + index, then a per-process ``done`` marker — readers treat a
    checkpoint as complete only when every process's marker exists.
    Each record's byte-length and crc32 go into the manifest so loads
    can verify integrity; blob and manifest are fsync'd.  The blob write
    goes through the native chunk-parallel aio engine
    (``deepspeed_tpu/io/csrc/aio.cpp``) when available (the buffered
    python path when a fault injector is active — injection points are
    per-record)."""
    proc = snap["proc"]
    os.makedirs(snap["dir"], exist_ok=True)
    blob = os.path.join(snap["dir"], BLOB_FILE.format(proc=proc))
    aio = None if faults.active() is not None else _aio_handle()
    if aio is not None:
        offset = 0
        ops = []
        bufs = [np.ascontiguousarray(np.asarray(b))
                for b in snap["buffers"]]
        total = sum(b.nbytes for b in bufs)
        from deepspeed_tpu.io.aio import _pretruncate

        _pretruncate(blob, total)
        for buf, rec in zip(bufs, snap["records"]):
            rec["crc32"] = zlib.crc32(buf)
            if buf.nbytes:
                ops.append(aio.async_pwrite(buf, blob, offset,
                                            _truncate=False))
            offset += buf.nbytes
        for op in ops:
            aio.wait(op)
        with open(blob, "rb+") as f:
            _fsync_file(f)
    else:
        _write_blob_python(blob, snap["buffers"], snap["records"])
    index = os.path.join(snap["dir"], INDEX_FILE.format(proc=proc))
    _write_index(index, snap["records"])
    with open(os.path.join(snap["dir"], DONE_FILE.format(proc=proc)),
              "w") as f:
        f.write("ok")
        _fsync_file(f)


def is_complete(path: str, process_count: int) -> bool:
    """All processes' done markers present?  (No collective needed: the
    markers live on the shared checkpoint filesystem.)"""
    return all(os.path.exists(os.path.join(path, DONE_FILE.format(proc=p)))
               for p in range(process_count))


def verify_tag(path: str, process_count: Optional[int] = None,
               deep: bool = True) -> Tuple[bool, str]:
    """Integrity check of one tag directory: every process's manifest
    parses, its done marker exists, the blob holds exactly the bytes the
    manifest claims, and (``deep``) every record's crc32 matches.

    Returns ``(ok, reason)`` — never raises.  Pre-hardening checkpoints
    (no crc32 in the manifest) pass the structural checks only.
    ``deep=False`` is the cheap structural variant GC uses."""
    if not os.path.isdir(path):
        return False, "tag directory missing"
    try:
        idx_files = sorted(f for f in os.listdir(path)
                           if f.startswith("index_p") and
                           f.endswith(".json"))
    except OSError as e:
        return False, f"unreadable tag directory ({e})"
    if not idx_files:
        return False, "no shard manifests"
    if process_count is not None and len(idx_files) != process_count:
        return False, (f"{len(idx_files)} of {process_count} process "
                       "manifests present")
    for fname in idx_files:
        proc = int(fname[len("index_p"):-len(".json")])
        if not os.path.exists(os.path.join(path, DONE_FILE.format(proc=proc))):
            return False, f"process {proc} never finished writing"
        try:
            with open(os.path.join(path, fname)) as f:
                records = json.load(f)["records"]
        except (OSError, ValueError, KeyError) as e:
            return False, f"manifest {fname} unreadable ({e})"
        blob = os.path.join(path, BLOB_FILE.format(proc=proc))
        try:
            size = os.path.getsize(blob)
        except OSError:
            return False, f"blob for process {proc} missing"
        total = sum(int(r["nbytes"]) for r in records)
        if total != size:
            return False, (f"blob for process {proc} holds {size} bytes, "
                           f"manifest claims {total} (torn write?)")
        if deep:
            with open(blob, "rb") as f:
                for r in records:
                    if "crc32" not in r:
                        continue          # pre-hardening record
                    f.seek(int(r["offset"]))
                    data = f.read(int(r["nbytes"]))
                    if len(data) != int(r["nbytes"]) or \
                            zlib.crc32(data) != int(r["crc32"]):
                        return False, (f"crc mismatch in {r['path']!r} "
                                       f"(process {proc})")
    return True, "ok"


class _Reader:
    """Assembles requested global slices from saved shard records."""

    def __init__(self, path: str):
        self.by_path: Dict[str, List[Dict]] = {}
        self.blobs: Dict[int, str] = {}
        for fname in sorted(os.listdir(path)):
            if not (fname.startswith("index_p") and fname.endswith(".json")):
                continue
            proc = int(fname[len("index_p"):-len(".json")])
            with open(os.path.join(path, fname)) as f:
                for rec in json.load(f)["records"]:
                    rec["proc"] = proc
                    self.by_path.setdefault(rec["path"], []).append(rec)
            self.blobs[proc] = os.path.join(path,
                                            BLOB_FILE.format(proc=proc))
        self._lock = threading.Lock()
        self._files: Dict[int, Any] = {}
        self._cache: Dict[Tuple[int, int], np.ndarray] = {}

    def paths(self) -> Sequence[str]:
        return list(self.by_path)

    def meta(self, path: str) -> Tuple[Tuple[int, ...], np.dtype]:
        rec = self.by_path[path][0]
        return tuple(rec["global_shape"]), np.dtype(rec["dtype"])

    def _read_record(self, rec: Dict) -> np.ndarray:
        # small LRU: consecutive make_array_from_callback callbacks for
        # neighbouring destination shards hit the same saved records, so
        # caching a few avoids O(dest_shards x record_bytes) re-reads
        key = (rec["proc"], rec["offset"])
        with self._lock:
            if key in self._cache:
                return self._cache[key]
            raw = self._pread(rec)
            shape = [b - a for a, b in rec["slices"]]
            arr = np.frombuffer(raw,
                                dtype=np.dtype(rec["dtype"])).reshape(shape)
            self._cache[key] = arr
            while len(self._cache) > 4:
                self._cache.pop(next(iter(self._cache)))
            return arr

    @retriable(retry_on=(OSError,))
    def _pread(self, rec: Dict) -> bytes:
        """Raw record read; transient OSErrors (flaky network mount)
        retry with backoff after dropping the cached file handle."""
        faults.hook("ckpt.read_record", path=rec["path"],
                    proc=rec["proc"])
        f = self._files.get(rec["proc"])
        try:
            if f is None:
                f = open(self.blobs[rec["proc"]], "rb")
                self._files[rec["proc"]] = f
            f.seek(rec["offset"])
            return f.read(rec["nbytes"])
        except OSError:
            if f is not None:
                self._files.pop(rec["proc"], None)
                try:
                    f.close()
                except OSError:
                    pass
            raise

    def read_slice(self, path: str, index: Tuple[slice, ...]) -> np.ndarray:
        """Global-slice read: union of overlapping saved records."""
        recs = self.by_path.get(path)
        if not recs:
            raise KeyError(f"checkpoint has no entry for {path!r}")
        shape, dtype = self.meta(path)
        want = _index_to_slices(index, shape)
        out_shape = [b - a for a, b in want]
        out = np.empty(out_shape, dtype)
        filled = 0
        for rec in recs:
            have = rec["slices"]
            inter = [[max(w[0], h[0]), min(w[1], h[1])]
                     for w, h in zip(want, have)]
            if any(a >= b for a, b in inter):
                continue
            src = self._read_record(rec)
            src_sel = tuple(slice(a - h[0], b - h[0])
                            for (a, b), h in zip(inter, have))
            dst_sel = tuple(slice(a - w[0], b - w[0])
                            for (a, b), w in zip(inter, want))
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != int(np.prod(out_shape)):
            raise ValueError(
                f"{path!r}: saved shards cover {filled} of "
                f"{int(np.prod(out_shape))} requested elements "
                "(incomplete checkpoint?)")
        return out

    def close(self):
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._cache.clear()


def load_tree(template: Any, shardings: Any, path: str,
              cast: bool = True, reader: Optional["_Reader"] = None) -> Any:
    """Load a tree saved by :func:`save_tree` onto ``shardings``
    (a matching tree of ``jax.sharding.Sharding``), resharding as needed.
    ``template`` supplies the pytree structure and leaf dtypes (host-side
    dtype cast when the stored dtype differs and ``cast`` is set).
    ``reader``: reuse an already-open :class:`_Reader` for ``path``
    (closed on return either way).
    """
    reader = reader if reader is not None else _Reader(path)
    try:
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        shard_flat = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda s: isinstance(s, jax.sharding.Sharding))
        assert len(flat) == len(shard_flat), (
            f"template has {len(flat)} leaves, shardings {len(shard_flat)}")
        out = []
        for (kp, leaf), sharding in zip(flat, shard_flat):
            key = path_str(kp)
            shape = tuple(np.shape(leaf))
            # dtype without any D2H transfer (template leaves may span
            # non-addressable devices on multi-host meshes)
            want_dtype = (np.dtype(getattr(leaf, "dtype", None) or
                                   np.result_type(leaf)) if cast else None)

            def cb(index, key=key, want_dtype=want_dtype):
                arr = reader.read_slice(key, index)
                if want_dtype is not None and arr.dtype != want_dtype:
                    arr = arr.astype(want_dtype)
                return arr

            saved_shape, _ = reader.meta(key)
            if saved_shape != shape:
                raise ValueError(
                    f"{key!r}: checkpoint shape {saved_shape} != model "
                    f"shape {shape} (different model config?)")
            out.append(jax.make_array_from_callback(shape, sharding, cb))
    finally:
        reader.close()
    return jax.tree_util.tree_unflatten(treedef, out)


def read_full_tree(path: str) -> Dict[str, np.ndarray]:
    """Flat {pytree path: full ndarray} view of a saved tree (offline
    consolidation — ``zero_to_fp32`` support)."""
    reader = _Reader(path)
    out = {}
    for key in reader.paths():
        shape, _ = reader.meta(key)
        out[key] = reader.read_slice(key, tuple(slice(0, d) for d in shape))
    reader.close()
    return out


class AsyncSaver:
    """One-slot background writer (Nebula-equivalent async save)."""

    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt-writer")
        self._inflight: Optional[Future] = None

    def submit(self, fn: Callable[[], None]) -> None:
        self.wait()
        fut = self._pool.submit(fn)

        def _log_failure(f: Future) -> None:
            # surface failures immediately — an unobserved Future would
            # swallow e.g. a disk-full on the run's final save
            if f.exception() is not None:
                logger.error(f"async checkpoint save FAILED: "
                             f"{f.exception()!r}")

        fut.add_done_callback(_log_failure)
        self._inflight = fut

    def wait(self) -> None:
        if self._inflight is not None:
            exc = self._inflight.exception()
            self._inflight = None
            if exc is not None:
                raise exc
