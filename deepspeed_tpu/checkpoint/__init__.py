from deepspeed_tpu.checkpoint.engine import (load_checkpoint,
                                              save_16bit_model,
                                              save_checkpoint,
                                              wait_checkpoint, zero_to_fp32)
from deepspeed_tpu.checkpoint.sharded import verify_tag

__all__ = ["save_checkpoint", "load_checkpoint", "wait_checkpoint",
           "save_16bit_model", "zero_to_fp32", "verify_tag"]
