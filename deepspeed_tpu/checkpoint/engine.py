"""Checkpoint save/load.

Covers the reference's engine checkpoint path (``engine.py:3213
save_checkpoint`` / ``:2867 load_checkpoint`` +
``runtime/checkpoint_engine/torch_checkpoint_engine.py``), redesigned for
TPU around the sharded, topology-independent store in
``checkpoint/sharded.py`` (universal-by-default: any mesh loads any
checkpoint; per-process shard writes bound host memory by the largest
shard, not the model).  Async save (Nebula-equivalent,
``nebula_checkpoint_engine.py``) runs file IO on a background thread after
a synchronous D2H snapshot.  The directory layout mirrors the reference
(``<dir>/<tag>/...`` + a ``latest`` file).

Legacy single-pickle checkpoints (the round-1 format) still load.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.checkpoint import sharded
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.retry import retriable
from deepspeed_tpu.runtime.train_state import TrainState
from deepspeed_tpu.utils.logging import log_dist, logger

MODEL_FILE = "model_states.pt"          # legacy consolidated format
EXTRA_FILE = "extra_states.pt"          # scalars + lr scheduler + client
META_FILE = "ds_meta.json"
LATEST_FILE = "latest"
STAGING_PREFIX = "tmp."                 # uncommitted tag being written
CORRUPT_SUFFIX = ".corrupt"             # quarantined tag


def _tag_of(engine, tag: Optional[str]) -> str:
    return tag if tag is not None else f"global_step{engine.global_steps}"


def _saver(engine) -> sharded.AsyncSaver:
    if getattr(engine, "_ckpt_saver", None) is None:
        engine._ckpt_saver = sharded.AsyncSaver()
    return engine._ckpt_saver


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None,
                    save_latest: bool = True,
                    async_save: Optional[bool] = None) -> str:
    """Sharded save.  Each process writes only its addressable shards
    (never the consolidated state); with ``async_save`` (default from
    ``checkpoint.async_save`` config) file IO runs on a background thread
    and :func:`wait_checkpoint` / the next save joins it.

    Hardened commit protocol (resilience/): everything is written into a
    ``tmp.<tag>`` staging directory and the tag becomes visible only via
    an atomic ``os.rename`` once every process's shards are down — a
    crash at ANY point leaves either the previous state or a complete
    new tag, never a partially-written visible one."""
    if async_save is None:
        async_save = engine.config.checkpoint.async_save
    tag = _tag_of(engine, tag)
    path = os.path.join(save_dir, tag)
    stage = os.path.join(save_dir, STAGING_PREFIX + tag)

    _saver(engine).wait()                     # one in-flight save at a time
    if jax.process_index() == 0 and os.path.isdir(stage):
        # leftover staging from a crashed save of the same tag
        shutil.rmtree(stage, ignore_errors=True)
    if jax.process_count() > 1:
        # no peer may start writing into the staging dir until the
        # leftover cleanup above has run — without this barrier a fast
        # peer's early staging files (the NVMe swapper meta copies) get
        # swept by process 0's rmtree and silently miss the committed
        # tag.  Runs under the collective watchdog when one is armed.
        from deepspeed_tpu.comm import barrier

        barrier()
    os.makedirs(stage, exist_ok=True)
    # async: copy shards to host up front (training mutates/donates the
    # state buffers); sync: stream shard-by-shard, bounded host memory
    snap = sharded.save_tree(
        {"module": engine.state.params, "optimizer": engine.state.opt_state},
        stage, materialize=bool(async_save))
    if getattr(engine, "nvme_swapper", None) is not None:
        # NVMe-swapped moments already live on disk: checkpointing them is
        # a file copy (reference engine.py:3277 copies offloaded state
        # alongside)
        engine.nvme_swapper.save_to(stage)
    extra = {
        "loss_scale": jax.device_get(engine.state.scale),
        "step": int(jax.device_get(engine.state.step)),
        "rng": np.asarray(jax.device_get(engine.state.rng)),
        "skipped_steps": int(jax.device_get(engine.state.skipped_steps)),
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "client_state": client_state or {},
    }
    # dataloader cursor (seed, epoch, in-epoch batch) rides along so a
    # resumed run CONTINUES mid-epoch instead of replaying/skipping data
    dl = getattr(engine, "training_dataloader", None)
    if dl is not None and callable(getattr(dl, "state_dict", None)):
        dl_state = dl.state_dict()
        if dl_state is not None:
            extra["dataloader"] = dl_state
    meta = {
        "tag": tag,
        "format": "sharded-v1",
        "zero_stage": engine.zero_stage,
        "world_size": engine.topology.world_size,
        "process_count": jax.process_count(),
        "mesh": engine.topology.shape,
        "dtype": str(engine.compute_dtype.__name__),
    }

    keep_last_k = engine.config.resilience.keep_last_k
    process_count = jax.process_count()

    def finish():
        sharded.write_snapshot(snap)
        if jax.process_index() == 0:
            _write_pickle(os.path.join(stage, EXTRA_FILE), extra)
            _write_json(os.path.join(stage, META_FILE), meta)
            _commit_tag(save_dir, tag, process_count,
                        save_latest=save_latest, keep_last_k=keep_last_k)
        else:
            # a save only "returns" once the tag is VISIBLE: without
            # this barrier a non-zero process could try to resume before
            # process 0's commit rename lands
            _await_commit(save_dir, tag)

    if async_save:
        _saver(engine).submit(finish)
        log_dist(f"async checkpoint {path} snapshot taken; writing in "
                 "background", ranks=[0])
    else:
        finish()
        log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


@retriable(retry_on=(OSError,))
def _write_pickle(path: str, obj) -> None:
    with open(path, "wb") as f:
        pickle.dump(obj, f)
        sharded._fsync_file(f)


@retriable(retry_on=(OSError,))
def _write_json(path: str, obj) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2)
        sharded._fsync_file(f)


def _commit_tag(save_dir: str, tag: str, process_count: int,
                save_latest: bool, keep_last_k: int) -> None:
    """Atomically publish ``tmp.<tag>`` as ``<tag>`` (process 0 only),
    update ``latest``, and garbage-collect old tags.  Waits for every
    process's done marker first — the rename is the commit point."""
    stage = os.path.join(save_dir, STAGING_PREFIX + tag)
    final = os.path.join(save_dir, tag)
    from deepspeed_tpu.resilience.retry import _sleep

    for _ in range(10_000):                  # bounded multi-host wait
        if sharded.is_complete(stage, process_count):
            break
        _sleep(0.05)
    else:
        raise RuntimeError(
            f"commit of {tag!r}: not all {process_count} processes "
            "finished writing their shards (crashed peer?)")
    faults.hook("ckpt.commit", tag=tag)
    if os.path.isdir(final):
        # re-saving an existing tag: replace it.  (Not crash-atomic for
        # the overwrite case — new-tag saves, the training-loop path,
        # are.)
        shutil.rmtree(final)
    os.rename(stage, final)
    sharded.fsync_dir(save_dir)
    if save_latest:
        # the pointer is written AFTER the commit and via rename, so it
        # never names a tag that does not fully exist
        tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(tag)
            sharded._fsync_file(f)
        os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    if keep_last_k > 0:
        _gc_tags(save_dir, keep_last_k)


def _await_commit(save_dir: str, tag: str, attempts: int = 10_000) -> None:
    """Non-zero processes: block until process 0's commit rename makes
    ``tag`` visible (bounded — a dead process 0 must not hang peers
    forever)."""
    from deepspeed_tpu.resilience.retry import _sleep

    final = os.path.join(save_dir, tag)
    for _ in range(attempts):
        if os.path.isdir(final):
            return
        _sleep(0.05)
    raise RuntimeError(
        f"save of {tag!r}: process 0 never committed the tag "
        "(crashed before the rename?)")


def _committed_tags(ckpt_dir: str) -> List[str]:
    """Visible (committed) tag names under ``ckpt_dir``, newest first.
    Staging dirs and quarantined tags are excluded."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    for name in names:
        if name.startswith(STAGING_PREFIX) or name.endswith(CORRUPT_SUFFIX):
            continue
        p = os.path.join(ckpt_dir, name)
        if not os.path.isdir(p):
            continue
        if os.path.exists(os.path.join(p, EXTRA_FILE)) or \
                os.path.exists(os.path.join(p, MODEL_FILE)):
            out.append((os.stat(p).st_mtime_ns, name))
    return [name for _, name in sorted(out, reverse=True)]


def _gc_tags(ckpt_dir: str, keep_last_k: int) -> None:
    """Delete committed tags beyond the newest ``keep_last_k`` — but
    never the only structurally-verified tag (a disk full of corrupt
    checkpoints must not lose its one good resume point)."""
    tags = _committed_tags(ckpt_dir)
    keep, candidates = tags[:keep_last_k], tags[keep_last_k:]

    def ok(name):
        return sharded.verify_tag(os.path.join(ckpt_dir, name),
                                  deep=False)[0]

    survivor_verified = any(ok(t) for t in keep)
    for t in candidates:
        if not survivor_verified and ok(t):
            survivor_verified = True
            continue                         # spared: the only good tag
        shutil.rmtree(os.path.join(ckpt_dir, t), ignore_errors=True)
        logger.info(f"checkpoint GC: removed old tag {t!r} "
                    f"(keep_last_k={keep_last_k})")


def _quarantine_tag(ckpt_dir: str, tag: str, reason: str) -> str:
    """Move a corrupt tag aside as ``<tag>.corrupt`` (never delete —
    the bytes may matter for postmortem) and return the new path."""
    src = os.path.join(ckpt_dir, tag)
    dst = src + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{CORRUPT_SUFFIX}.{n}"
    os.rename(src, dst)
    logger.error(f"checkpoint {tag!r} FAILED verification ({reason}); "
                 f"quarantined to {os.path.basename(dst)}")
    return dst


def wait_checkpoint(engine) -> None:
    """Join an in-flight async save (no-op otherwise)."""
    _saver(engine).wait()


def _resolve_verified_tag(engine, load_dir: str, tag: Optional[str]
                          ) -> Optional[str]:
    """Tag-selection half of a hardened load: resolve ``latest`` (or the
    newest committed tag when the pointer is gone), verify manifests +
    checksums, quarantine corrupt tags, and fall back to the newest tag
    that DOES verify.  An explicitly-requested corrupt tag raises —
    silently loading a different tag than asked would be worse than
    failing."""
    explicit = tag is not None
    verify = engine.config.resilience.verify_on_load
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            committed = _committed_tags(load_dir)
            if not committed:
                logger.warning(f"no 'latest' file in {load_dir}; "
                               "nothing loaded")
                return None
            # crash between tag commit and pointer write: the newest
            # committed tag is still a valid resume point
            tag = committed[0]
            logger.warning(f"no 'latest' pointer in {load_dir}; using "
                           f"newest committed tag {tag!r}")
    tried = set()
    while True:
        tried.add(tag)
        path = os.path.join(load_dir, tag)
        if os.path.exists(os.path.join(path, MODEL_FILE)) and \
                not os.path.exists(os.path.join(path, EXTRA_FILE)):
            return tag                     # legacy pickle: no manifests
        if not os.path.exists(os.path.join(path, EXTRA_FILE)):
            if explicit or not os.path.isdir(path):
                logger.warning(f"checkpoint {path} missing; "
                               "nothing loaded")
                return None
            ok, reason = False, "no extra_states (interrupted pre-" \
                                "hardening save?)"
        elif verify:
            saved_procs = None
            meta_path = os.path.join(path, META_FILE)
            if os.path.exists(meta_path):
                try:
                    with open(meta_path) as f:
                        saved_procs = json.load(f).get("process_count", 1)
                except (OSError, ValueError):
                    saved_procs = None
            ok, reason = sharded.verify_tag(path, process_count=saved_procs,
                                            deep=True)
        else:
            ok, reason = True, "ok"
        if ok:
            return tag
        _quarantine_tag(load_dir, tag, reason)
        if explicit:
            raise RuntimeError(
                f"checkpoint {path} failed verification ({reason}) and "
                "was quarantined; pass tag=None to fall back to the "
                "newest verified tag")
        remaining = [t for t in _committed_tags(load_dir)
                     if t not in tried]
        if not remaining:
            logger.warning(f"no verified checkpoint remains in "
                           f"{load_dir}; nothing loaded")
            return None
        tag = remaining[0]
        logger.warning(f"falling back to tag {tag!r}")


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True
                    ) -> Tuple[Optional[str], Optional[Dict]]:
    _saver(engine).wait()
    requested = tag
    tag = _resolve_verified_tag(engine, load_dir, tag)
    if tag is None:
        return None, None
    path = os.path.join(load_dir, tag)
    if not os.path.exists(os.path.join(path, EXTRA_FILE)):
        # not the sharded format; fall back to the round-1 pickle
        return _load_legacy(engine, path, load_optimizer_states,
                            load_lr_scheduler_states)

    meta_path = os.path.join(path, META_FILE)
    if not engine.config.resilience.verify_on_load and \
            os.path.exists(meta_path):
        with open(meta_path) as f:
            saved_procs = json.load(f).get("process_count", 1)
        if not sharded.is_complete(path, saved_procs):
            raise RuntimeError(
                f"checkpoint {path} is incomplete: not all of its "
                f"{saved_procs} processes finished writing (crashed or "
                "still-running save?)")

    if requested is None and jax.process_index() == 0:
        # a fallback may have landed on a different tag than 'latest'
        # named; repoint it so the next resume skips the scan
        latest = os.path.join(load_dir, LATEST_FILE)
        try:
            stale = True
            if os.path.exists(latest):
                with open(latest) as f:
                    stale = f.read().strip() != tag
            if stale:
                tmp = latest + ".tmp"
                with open(tmp, "w") as f:
                    f.write(tag)
                os.replace(tmp, latest)
        except OSError:
            pass                            # read-only checkpoint mount

    with open(os.path.join(path, EXTRA_FILE), "rb") as f:
        extra = pickle.load(f)

    shardings = engine._state_shardings
    # cross-mode resume guard: an NVMe-offload run checkpoints opt_state as
    # an empty tuple (the moments travel as files, see nvme_optimizer/),
    # so a device-resident engine restoring it must not expect
    # "optimizer/..." records — warn and keep fresh moments instead of
    # crashing mid-restore
    reader = None
    if load_optimizer_states and \
            jax.tree_util.tree_leaves(engine.state.opt_state):
        reader = sharded._Reader(path)
        try:
            has_opt = any(p.startswith("optimizer/")
                          for p in reader.paths())
        except Exception:
            reader.close()
            raise
        if not has_opt:
            logger.warning(
                f"checkpoint {path} holds no optimizer records (saved by "
                "an NVMe-offload engine?); optimizer state starts fresh")
            load_optimizer_states = False
            reader.close()
            reader = None
    if load_optimizer_states:
        tree = sharded.load_tree(
            {"module": engine.state.params,
             "optimizer": engine.state.opt_state},
            {"module": shardings.params, "optimizer": shardings.opt_state},
            path, reader=reader)
        params, opt_state = tree["module"], tree["optimizer"]
    else:
        params = sharded.load_tree(
            {"module": engine.state.params},
            {"module": shardings.params}, path)["module"]
        opt_state = engine.state.opt_state

    engine.state = TrainState(
        step=jnp.asarray(extra["step"], jnp.int32),
        params=params,
        opt_state=opt_state,
        scale=jax.device_put(extra["loss_scale"]),
        rng=jnp.asarray(extra["rng"]),
        skipped_steps=jnp.asarray(extra["skipped_steps"], jnp.int32))
    engine.global_steps = int(extra["global_steps"])
    engine.global_samples = int(extra.get("global_samples", 0))
    if load_lr_scheduler_states and engine.lr_scheduler is not None:
        engine.lr_scheduler.load_state_dict(extra["lr_scheduler"])
    dl_state = extra.get("dataloader")
    dl = getattr(engine, "training_dataloader", None)
    if dl_state is not None and dl is not None and \
            callable(getattr(dl, "load_state_dict", None)):
        dl.load_state_dict(dl_state)
        engine._data_iter = None          # re-enter at the restored cursor
    if load_optimizer_states and \
            getattr(engine, "nvme_swapper", None) is not None:
        if not engine.nvme_swapper.load_from(path):
            # resume compat: the checkpoint may have been saved by the
            # device/fused offload path (optimizer records in the sharded
            # store, no swap files) — ingest its Adam moments instead of
            # silently restarting them from zero
            _ingest_fused_opt_state(engine, path)
    log_dist(f"loaded checkpoint {path} (global_steps="
             f"{engine.global_steps})", ranks=[0])
    return path, extra.get("client_state")


def _load_legacy(engine, path: str, load_optimizer_states: bool,
                 load_lr_scheduler_states: bool):
    """Round-1 consolidated-pickle format."""
    with open(os.path.join(path, MODEL_FILE), "rb") as f:
        ckpt = pickle.load(f)
    shardings = engine._state_shardings
    params = jax.tree_util.tree_map(jax.device_put, ckpt["module"],
                                    shardings.params)
    opt_state = (jax.tree_util.tree_map(jax.device_put, ckpt["optimizer"],
                                        shardings.opt_state)
                 if load_optimizer_states else engine.state.opt_state)
    engine.state = TrainState(
        step=jnp.asarray(ckpt["step"], jnp.int32),
        params=params,
        opt_state=opt_state,
        scale=jax.device_put(ckpt["loss_scale"]),
        rng=jnp.asarray(ckpt["rng"]),
        skipped_steps=jnp.asarray(ckpt["skipped_steps"], jnp.int32))
    engine.global_steps = int(ckpt["global_steps"])
    engine.global_samples = int(ckpt.get("global_samples", 0))
    if load_lr_scheduler_states and engine.lr_scheduler is not None:
        engine.lr_scheduler.load_state_dict(ckpt["lr_scheduler"])
    log_dist(f"loaded legacy checkpoint {path}", ranks=[0])
    return path, ckpt.get("client_state")


def zero_to_fp32(checkpoint_dir: str, tag: Optional[str] = None
                 ) -> Dict[str, np.ndarray]:
    """Consolidated fp32 state dict from a checkpoint directory (reference
    offline ``deepspeed/utils/zero_to_fp32.py:188``).  Reads shard records
    directly — no engine, no devices."""
    if tag is None:
        with open(os.path.join(checkpoint_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    path = os.path.join(checkpoint_dir, tag)
    if os.path.exists(os.path.join(path, MODEL_FILE)):   # legacy
        with open(os.path.join(path, MODEL_FILE), "rb") as f:
            ckpt = pickle.load(f)
        flat = {}
        for kp, leaf in jax.tree_util.tree_flatten_with_path(
                ckpt["module"])[0]:
            flat[sharded.path_str(kp)] = np.asarray(leaf, dtype=np.float32)
        return flat
    full = sharded.read_full_tree(path)
    prefix = "module/"
    return {k[len(prefix):]: v.astype(np.float32)
            for k, v in full.items() if k.startswith(prefix)}


def save_16bit_model(engine, save_dir: str,
                     output_file: str = "pytorch_model.bin") -> str:
    """Consolidated half-precision weights for serving handoff (reference
    ``engine.save_16bit_model`` / ``stage3_gather_16bit_weights_on_model_
    save``): params only — no optimizer state — cast to the engine's
    compute dtype, gathered leaf-by-leaf so host memory holds one full
    leaf at a time, written as a flat {path: array} pickle."""
    import jax.numpy as jnp

    os.makedirs(save_dir, exist_ok=True)
    dtype = engine.compute_dtype
    if dtype == jnp.float32:
        logger.warning("save_16bit_model with fp32 compute dtype: weights "
                       "are written in fp32 (enable bf16/fp16 for a "
                       "half-precision export)")
    flat: Dict[str, np.ndarray] = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(
            engine.state.params)[0]:
        # cast BEFORE the transfer (half the D2H bytes) and assemble
        # cross-process shards when the leaf spans hosts
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            leaf = leaf.astype(dtype)
        if getattr(leaf, "is_fully_addressable", True):
            arr = np.asarray(jax.device_get(leaf))
        else:
            from jax.experimental import multihost_utils

            arr = np.asarray(multihost_utils.process_allgather(
                leaf, tiled=True))
        flat[sharded.path_str(kp)] = arr
    path = os.path.join(save_dir, output_file)
    if jax.process_index() == 0:
        with open(path, "wb") as f:
            pickle.dump(flat, f)
    log_dist(f"save_16bit_model: {len(flat)} tensors -> {path}", ranks=[0])
    return path


def _ingest_fused_opt_state(engine, path: str) -> bool:
    """Feed a fused-optimizer checkpoint's Adam ``mu``/``nu`` records
    into the engine's swapped-moment tier (``import_moments``) — the
    cross-format half of tier-portable resumes."""
    r = sharded._Reader(path)
    try:
        opt = [p for p in r.paths() if p.startswith("optimizer/")]

        def by(marker):
            # namedtuple fields render as ".mu"/".nu" in record paths
            return {p.split(marker, 1)[1]: p for p in opt if marker in p}

        mu = by("/.mu/") or by("/mu/")
        nu = by("/.nu/") or by("/nu/")
        if not mu or set(mu) != set(nu):
            return False
        count = 0
        for p in opt:
            if p.endswith(".count") or p.endswith("/count"):
                shape, _ = r.meta(p)
                count = int(np.asarray(r.read_slice(
                    p, tuple(slice(0, d) for d in shape))))
                break

        def fetch(key):
            mp, np_ = mu.get(key), nu.get(key)
            if mp is None:
                return None
            shape, _ = r.meta(mp)
            idx = tuple(slice(0, d) for d in shape)
            return r.read_slice(mp, idx), r.read_slice(np_, idx)

        n = engine.nvme_swapper.import_moments(fetch, count)
        if n:
            log_dist(f"ingested {n} Adam moment tensors from a "
                     "fused-optimizer checkpoint into the swapped tier "
                     f"(count={count})", ranks=[0])
        return n > 0
    finally:
        r.close()
