"""Checkpoint save/load.

Covers the reference's engine checkpoint path (``engine.py:3213
save_checkpoint`` / ``:2867 load_checkpoint`` +
``runtime/checkpoint_engine/torch_checkpoint_engine.py``), redesigned for
TPU: the canonical on-disk layout is **topology-independent** ("universal by
default", SURVEY §5 checkpoint notes) — full unsharded host arrays keyed by
pytree path, so a checkpoint written on any (dp, tp, pp) mesh loads onto any
other; resharding happens on ``device_put`` against the destination
topology's sharding plan.  The directory layout mirrors the reference
(``<dir>/<tag>/...`` + a ``latest`` file).

Async save (Nebula-equivalent) and tensorstore/OCDBT streaming for
beyond-host-memory models are planned extensions of this module.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.train_state import TrainState
from deepspeed_tpu.utils.logging import log_dist, logger

MODEL_FILE = "model_states.pt"
META_FILE = "ds_meta.json"
LATEST_FILE = "latest"


def _tag_of(engine, tag: Optional[str]) -> str:
    return tag if tag is not None else f"global_step{engine.global_steps}"


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None,
                    save_latest: bool = True) -> str:
    tag = _tag_of(engine, tag)
    path = os.path.join(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    # single-writer: process 0 owns the canonical full-state file.  On
    # multi-host meshes, sharded leaves span non-addressable devices; gather
    # them to fully-replicated before the host transfer.
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        host_state: TrainState = multihost_utils.process_allgather(
            engine.state)
    else:
        host_state = jax.device_get(engine.state)
    ckpt = {
        "module": host_state.params,
        "optimizer": host_state.opt_state,
        "loss_scale": host_state.scale,
        "step": host_state.step,
        "rng": host_state.rng,
        "skipped_steps": host_state.skipped_steps,
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "client_state": client_state or {},
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, MODEL_FILE), "wb") as f:
            pickle.dump(ckpt, f)
        with open(os.path.join(path, META_FILE), "w") as f:
            json.dump({
                "tag": tag,
                "zero_stage": engine.zero_stage,
                "world_size": engine.topology.world_size,
                "mesh": engine.topology.shape,
                "dtype": str(engine.compute_dtype.__name__),
            }, f, indent=2)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(tag)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True
                    ) -> Tuple[Optional[str], Optional[Dict]]:
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
            return None, None
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.join(load_dir, tag)
    model_file = os.path.join(path, MODEL_FILE)
    if not os.path.exists(model_file):
        logger.warning(f"checkpoint file {model_file} missing; nothing loaded")
        return None, None

    with open(model_file, "rb") as f:
        ckpt = pickle.load(f)

    shardings = engine._state_shardings
    params = jax.tree_util.tree_map(jax.device_put, ckpt["module"],
                                    shardings.params)
    if load_optimizer_states:
        opt_state = jax.tree_util.tree_map(jax.device_put, ckpt["optimizer"],
                                           shardings.opt_state)
    else:
        opt_state = engine.state.opt_state

    scale = jax.device_put(ckpt["loss_scale"])
    engine.state = TrainState(
        step=jnp.asarray(ckpt["step"], jnp.int32),
        params=params,
        opt_state=opt_state,
        scale=scale,
        rng=jnp.asarray(ckpt["rng"]),
        skipped_steps=jnp.asarray(ckpt["skipped_steps"], jnp.int32))
    engine.global_steps = int(ckpt["global_steps"])
    engine.global_samples = int(ckpt.get("global_samples", 0))
    if load_lr_scheduler_states and engine.lr_scheduler is not None:
        engine.lr_scheduler.load_state_dict(ckpt["lr_scheduler"])
    log_dist(f"loaded checkpoint {path} (global_steps="
             f"{engine.global_steps})", ranks=[0])
    return path, ckpt.get("client_state")


def zero_to_fp32(checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Consolidated fp32 state dict from a checkpoint directory (the
    reference's offline ``deepspeed/utils/zero_to_fp32.py:188``; trivial here
    because the canonical format is already consolidated and
    topology-independent)."""
    if tag is None:
        with open(os.path.join(checkpoint_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    with open(os.path.join(checkpoint_dir, tag, MODEL_FILE), "rb") as f:
        ckpt = pickle.load(f)
    flat = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(ckpt["module"])[0]:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in kp)
        flat[key] = np.asarray(leaf, dtype=np.float32)
    return flat
