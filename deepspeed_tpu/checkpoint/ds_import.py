"""Ingest reference (torch-DeepSpeed) ZeRO checkpoints.

The migration path the reference provides as ``ds_to_universal.py`` /
``zero_to_fp32.py`` (``deepspeed/checkpoint/ds_to_universal.py:112,232``,
``deepspeed/utils/zero_to_fp32.py``): a torch-DeepSpeed training run
leaves per-rank files

- ``mp_rank_00_model_states.pt`` — module state dict (possibly 16-bit) +
  ``param_shapes`` (ordered {name: shape} per optimizer group),
- ``zero_pp_rank_{dp}_mp_rank_{mp}_optim_states.pt`` — the rank's flat
  fp32 partition(s): ``single_partition_of_fp32_groups`` (stage 1/2) or
  ``fp32_flat_groups`` (stage 3).

:func:`consolidate_reference_zero_checkpoint` reproduces the reference
consolidation: concatenate each group's per-rank flat partitions, strip
the stage-3 round-robin padding, and split by ``param_shapes`` into a
named fp32 state dict.  mp_size>1 (Megatron-style tensor-parallel)
checkpoints are consolidated per mp rank and the TP slices merged per
param class (reference ``ds_to_universal.py:232`` ``merge_tp_slices``:
replicated → first slice, column-parallel → cat dim 0, row-parallel →
cat dim 1) — classes come from explicit ``tp_merge_rules`` regexes, an
exact-equality probe (replicated), and Megatron/HF naming heuristics
for the row-parallel projections.  :func:`load_reference_checkpoint`
then feeds the merged dict through the HF-layout converters
(``module_inject/hf_loader.py``) into a flax params tree —
torch-DeepSpeed runs migrate without ever loading torch-DeepSpeed.
"""
from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.checkpoint.reshard import (gather_padded_partitions,
                                              padded_partition_size)
from deepspeed_tpu.utils.logging import logger

__all__ = ["consolidate_reference_zero_checkpoint",
           "load_reference_checkpoint", "merge_tp_state_dicts"]

# Megatron/HF decoder naming for ROW-parallel linears (sharded along the
# input dim → merge = cat axis 1); column-parallel is the 2-D default.
# (reference ds_to_universal reads these patterns from the checkpoint's
# UNIVERSAL_CHECKPOINT_INFO; torch-DS training checkpoints usually lack
# it, so the common layouts are encoded here and anything unusual goes
# through ``tp_merge_rules``.)
_ROW_PARALLEL_PATTERNS = (
    r".*attention\.dense\.weight$",          # megatron attn out-proj
    r".*self_attn\.o_proj\.weight$",         # llama-family
    r".*attn\.c_proj\.weight$",              # gpt2-family
    r".*mlp\.dense_4h_to_h\.weight$",        # megatron mlp down
    r".*mlp\.down_proj\.weight$",            # llama-family
    r".*mlp\.c_proj\.weight$",               # gpt2-family
    r".*\.fc2\.weight$",                     # opt-family
    r".*dense_4h_to_h\.weight$",
)


def merge_tp_state_dicts(per_mp: List[Dict[str, np.ndarray]],
                         tp_merge_rules: Optional[Dict[str, str]] = None
                         ) -> Dict[str, np.ndarray]:
    """Merge per-TP-rank consolidated state dicts into full tensors
    (reference ``ds_to_universal.py:232`` per-param-class rules).

    ``tp_merge_rules``: {regex: rule} with rule in {"replicate",
    "average", "cat0", "cat1"}; unmatched names fall back to: exact
    equality across ranks → replicate; 1-D → cat0 (column-parallel bias);
    2-D row-parallel names (``_ROW_PARALLEL_PATTERNS``) → cat1;
    remaining → cat0."""
    assert per_mp, "no TP ranks to merge"
    if len(per_mp) == 1:
        return per_mp[0]
    names = list(per_mp[0].keys())
    for r, sd in enumerate(per_mp[1:], 1):
        if set(sd.keys()) != set(names):
            raise ValueError(
                f"mp rank {r} holds different param names than rank 0 — "
                "not a tensor-parallel checkpoint family")
    rules = [(re.compile(pat), rule)
             for pat, rule in (tp_merge_rules or {}).items()]
    out: Dict[str, np.ndarray] = {}
    for name in names:
        slices = [sd[name] for sd in per_mp]
        rule = next((r for pat, r in rules if pat.match(name)), None)
        if rule is None:
            if all(np.array_equal(slices[0], s) for s in slices[1:]):
                rule = "replicate"
            elif slices[0].ndim <= 1:
                rule = "cat0"
            elif any(re.match(p, name) for p in _ROW_PARALLEL_PATTERNS):
                rule = "cat1"
            else:
                rule = "cat0"
        if rule == "replicate":
            out[name] = slices[0]
        elif rule == "average":
            out[name] = np.mean(slices, axis=0)
        elif rule == "cat0":
            out[name] = np.concatenate(slices, axis=0)
        elif rule == "cat1":
            out[name] = np.concatenate(slices, axis=1)
        else:
            raise ValueError(f"unknown tp merge rule {rule!r} for {name}")
    return out


def _torch_load(path: str):
    import torch

    return torch.load(path, map_location="cpu", weights_only=False)


def _to_np(t) -> np.ndarray:
    import torch

    if isinstance(t, torch.Tensor):
        return t.detach().to(torch.float32).cpu().numpy()
    return np.asarray(t, np.float32)


def _find_tag_dir(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
    if tag is not None:
        cand = os.path.join(ckpt_dir, tag)
        if os.path.isdir(cand):
            return cand
    if glob.glob(os.path.join(ckpt_dir, "*_model_states.pt")):
        return ckpt_dir
    raise FileNotFoundError(
        f"no reference DeepSpeed checkpoint under {ckpt_dir!r} "
        f"(tag={tag!r}): expected <dir>/<tag>/*_model_states.pt")


def _ordered_shapes(param_shapes) -> List[Dict[str, tuple]]:
    """``param_shapes`` is one ordered {name: shape} dict per optimizer
    group (newer checkpoints) or a single dict (older)."""
    if isinstance(param_shapes, dict):
        param_shapes = [param_shapes]
    return [{k: tuple(int(d) for d in v) for k, v in g.items()}
            for g in param_shapes]


def _mp_index(path: str) -> int:
    """TP rank from an ``mp_rank_XX`` / ``zero_pp_rank_D_mp_rank_XX``
    file name (0 when the name carries no mp marker)."""
    m = re.search(r"mp_rank_(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else 0


def consolidate_reference_zero_checkpoint(
        ckpt_dir: str, tag: Optional[str] = None,
        tp_merge_rules: Optional[Dict[str, str]] = None
        ) -> Dict[str, np.ndarray]:
    """Reference ``zero_to_fp32`` consolidation: named fp32 tensors from
    the per-rank flat partitions.  mp_size>1 checkpoints consolidate per
    TP rank and merge the slices (``merge_tp_state_dicts``)."""
    d = _find_tag_dir(ckpt_dir, tag)
    model_files = sorted(glob.glob(os.path.join(d, "*_model_states.pt")))
    assert model_files, f"no *_model_states.pt under {d}"
    optim_all = glob.glob(os.path.join(d, "*_optim_states.pt"))
    # stage 3 writes per-DP-rank zero_pp_rank_*_model_states.pt (all with
    # identical param_shapes per TP rank); stages 1/2 write one
    # mp_rank_XX file per TP rank.  Group everything by TP rank,
    # consolidate each, then merge the TP slices.
    plain_mp = [f for f in model_files
                if not os.path.basename(f).startswith("zero_pp_rank_")]
    mp_ranks = sorted({_mp_index(f) for f in (plain_mp or model_files)})
    per_mp = []
    for mp in mp_ranks:
        model_f = next(f for f in (plain_mp or model_files)
                       if _mp_index(f) == mp)
        optim_f = sorted(
            (f for f in optim_all if _mp_index(f) == mp),
            key=lambda p: [int(x) for x in re.findall(
                r"\d+", os.path.basename(p))])
        per_mp.append(_consolidate_one_mp(model_f, optim_f))
    merged = merge_tp_state_dicts(per_mp, tp_merge_rules)
    if len(per_mp) > 1:
        logger.info(f"merged {len(per_mp)} TP slices "
                    f"(reference mp_size={len(per_mp)} checkpoint)")
    return merged


def _consolidate_one_mp(model_file: str,
                        optim_files: List[str]) -> Dict[str, np.ndarray]:
    """One TP rank's consolidation across its DP partitions."""
    model_sd = _torch_load(model_file)
    if not optim_files:
        # no ZeRO shards: the module weights are already whole
        module = model_sd.get("module", model_sd)
        return {k: _to_np(v) for k, v in module.items()}

    param_shapes = _ordered_shapes(model_sd["param_shapes"])
    per_rank = [_torch_load(f)["optimizer_state_dict"]
                for f in optim_files]
    world = len(per_rank)

    stage3 = "fp32_flat_groups" in per_rank[0]
    out: Dict[str, np.ndarray] = {}
    if stage3:
        # stage 3: each rank holds ceil(numel/world) of EVERY param,
        # flattened group-wise with padding (reference zero_to_fp32
        # _merge_zero3); concatenating rank partitions per group yields
        # [world, group_pad] whose columns interleave per-param slices
        for gi, shapes in enumerate(param_shapes):
            flats = [_to_np(r["fp32_flat_groups"][gi]).reshape(-1)
                     for r in per_rank]
            offsets = [0] * world
            for name, shape in shapes.items():
                numel = int(np.prod(shape)) if shape else 1
                per = padded_partition_size(numel, world)
                parts = []
                for rk in range(world):
                    sl = flats[rk][offsets[rk]:offsets[rk] + per]
                    parts.append(sl)
                    offsets[rk] += per
                out[name] = gather_padded_partitions(
                    parts, numel).reshape(shape)
    else:
        # stage 1/2: each group's fp32 master is flat-partitioned across
        # ranks (reference single_partition_of_fp32_groups); concat then
        # split by shapes
        for gi, shapes in enumerate(param_shapes):
            key = ("single_partition_of_fp32_groups"
                   if "single_partition_of_fp32_groups" in per_rank[0]
                   else "fp32_flat_groups")
            flat = np.concatenate(
                [_to_np(r[key][gi]).reshape(-1) for r in per_rank])
            off = 0
            for name, shape in shapes.items():
                numel = int(np.prod(shape)) if shape else 1
                out[name] = flat[off:off + numel].reshape(shape)
                off += numel
            if off > flat.size:
                raise ValueError(
                    f"group {gi}: shapes need {off} elements, flat "
                    f"partitions hold {flat.size}")
    logger.info(f"consolidated reference ZeRO slice "
                f"(mp_rank {_mp_index(model_file)}): {len(out)} tensors "
                f"from {world} DP partition(s) "
                f"(stage {'3' if stage3 else '1/2'})")
    return out


def _strip_module_prefix(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    if sd and all(k.startswith("module.") for k in sd):
        return {k[len("module."):]: v for k, v in sd.items()}
    return sd


def load_reference_checkpoint(model: Any, ckpt_dir: str,
                              tag: Optional[str] = None,
                              tp_merge_rules: Optional[Dict[str, str]]
                              = None) -> Dict[str, Any]:
    """torch-DeepSpeed run -> flax params for our engines: consolidate
    the ZeRO shards (merging TP slices for mp_size>1), then map the
    named tensors through the HF-layout converter for ``model``'s
    family."""
    from deepspeed_tpu.module_inject import convert_hf_state_dict

    sd = _strip_module_prefix(
        consolidate_reference_zero_checkpoint(ckpt_dir, tag,
                                              tp_merge_rules))
    return convert_hf_state_dict(model, sd)
