"""Ingest reference (torch-DeepSpeed) ZeRO checkpoints.

The migration path the reference provides as ``ds_to_universal.py`` /
``zero_to_fp32.py`` (``deepspeed/checkpoint/ds_to_universal.py:112,232``,
``deepspeed/utils/zero_to_fp32.py``): a torch-DeepSpeed training run
leaves per-rank files

- ``mp_rank_00_model_states.pt`` — module state dict (possibly 16-bit) +
  ``param_shapes`` (ordered {name: shape} per optimizer group),
- ``zero_pp_rank_{dp}_mp_rank_{mp}_optim_states.pt`` — the rank's flat
  fp32 partition(s): ``single_partition_of_fp32_groups`` (stage 1/2) or
  ``fp32_flat_groups`` (stage 3).

:func:`consolidate_reference_zero_checkpoint` reproduces the reference
consolidation: concatenate each group's per-rank flat partitions, strip
the stage-3 round-robin padding, and split by ``param_shapes`` into a
named fp32 state dict.  :func:`load_reference_checkpoint` then feeds it
through the HF-layout converters (``module_inject/hf_loader.py``) into a
flax params tree — torch-DeepSpeed runs migrate without ever loading
torch-DeepSpeed.

Scope: mp_size 1 checkpoints (TP resharding of a torch checkpoint is the
reference's own ds_to_universal + load pipeline; our engines reshard
from the FULL tree at load time anyway, so consolidation is the part
that matters).
"""
from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

__all__ = ["consolidate_reference_zero_checkpoint",
           "load_reference_checkpoint"]


def _torch_load(path: str):
    import torch

    return torch.load(path, map_location="cpu", weights_only=False)


def _to_np(t) -> np.ndarray:
    import torch

    if isinstance(t, torch.Tensor):
        return t.detach().to(torch.float32).cpu().numpy()
    return np.asarray(t, np.float32)


def _find_tag_dir(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
    if tag is not None:
        cand = os.path.join(ckpt_dir, tag)
        if os.path.isdir(cand):
            return cand
    if glob.glob(os.path.join(ckpt_dir, "*_model_states.pt")):
        return ckpt_dir
    raise FileNotFoundError(
        f"no reference DeepSpeed checkpoint under {ckpt_dir!r} "
        f"(tag={tag!r}): expected <dir>/<tag>/*_model_states.pt")


def _ordered_shapes(param_shapes) -> List[Dict[str, tuple]]:
    """``param_shapes`` is one ordered {name: shape} dict per optimizer
    group (newer checkpoints) or a single dict (older)."""
    if isinstance(param_shapes, dict):
        param_shapes = [param_shapes]
    return [{k: tuple(int(d) for d in v) for k, v in g.items()}
            for g in param_shapes]


def consolidate_reference_zero_checkpoint(
        ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Reference ``zero_to_fp32`` consolidation: named fp32 tensors from
    the per-rank flat partitions."""
    d = _find_tag_dir(ckpt_dir, tag)
    model_files = sorted(glob.glob(os.path.join(d, "*_model_states.pt")))
    assert model_files, f"no *_model_states.pt under {d}"
    # stage 3 writes per-DP-rank zero_pp_rank_*_model_states.pt (all with
    # identical param_shapes); stages 1/2 write one mp_rank_XX file.  TP
    # ranks are the plain mp_rank files — only those gate the assert.
    plain_mp = [f for f in model_files
                if not os.path.basename(f).startswith("zero_pp_rank_")]
    assert len(plain_mp) <= 1, (
        "multi-TP reference checkpoints are not supported — run the "
        "reference's own ds_to_universal first, or consolidate per "
        "mp_rank")
    model_sd = _torch_load((plain_mp or model_files)[0])

    optim_files = sorted(
        glob.glob(os.path.join(d, "*_optim_states.pt")),
        key=lambda p: [int(x) for x in re.findall(r"\d+",
                                                  os.path.basename(p))])
    if not optim_files:
        # no ZeRO shards: the module weights are already whole
        module = model_sd.get("module", model_sd)
        return {k: _to_np(v) for k, v in module.items()}

    param_shapes = _ordered_shapes(model_sd["param_shapes"])
    per_rank = [_torch_load(f)["optimizer_state_dict"]
                for f in optim_files]
    world = len(per_rank)

    stage3 = "fp32_flat_groups" in per_rank[0]
    out: Dict[str, np.ndarray] = {}
    if stage3:
        # stage 3: each rank holds ceil(numel/world) of EVERY param,
        # flattened group-wise with padding (reference zero_to_fp32
        # _merge_zero3); concatenating rank partitions per group yields
        # [world, group_pad] whose columns interleave per-param slices
        for gi, shapes in enumerate(param_shapes):
            flats = [_to_np(r["fp32_flat_groups"][gi]).reshape(-1)
                     for r in per_rank]
            offsets = [0] * world
            for name, shape in shapes.items():
                numel = int(np.prod(shape)) if shape else 1
                per = -(-numel // world)            # padded per-rank slice
                parts = []
                for rk in range(world):
                    sl = flats[rk][offsets[rk]:offsets[rk] + per]
                    parts.append(sl)
                    offsets[rk] += per
                out[name] = np.concatenate(parts)[:numel].reshape(shape)
    else:
        # stage 1/2: each group's fp32 master is flat-partitioned across
        # ranks (reference single_partition_of_fp32_groups); concat then
        # split by shapes
        for gi, shapes in enumerate(param_shapes):
            key = ("single_partition_of_fp32_groups"
                   if "single_partition_of_fp32_groups" in per_rank[0]
                   else "fp32_flat_groups")
            flat = np.concatenate(
                [_to_np(r[key][gi]).reshape(-1) for r in per_rank])
            off = 0
            for name, shape in shapes.items():
                numel = int(np.prod(shape)) if shape else 1
                out[name] = flat[off:off + numel].reshape(shape)
                off += numel
            if off > flat.size:
                raise ValueError(
                    f"group {gi}: shapes need {off} elements, flat "
                    f"partitions hold {flat.size}")
    logger.info(f"consolidated reference ZeRO checkpoint: {len(out)} "
                f"tensors from {world} rank partition(s) "
                f"(stage {'3' if stage3 else '1/2'})")
    return out


def _strip_module_prefix(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    if sd and all(k.startswith("module.") for k in sd):
        return {k[len("module."):]: v for k, v in sd.items()}
    return sd


def load_reference_checkpoint(model: Any, ckpt_dir: str,
                              tag: Optional[str] = None) -> Dict[str, Any]:
    """torch-DeepSpeed run -> flax params for our engines: consolidate
    the ZeRO shards, then map the named tensors through the HF-layout
    converter for ``model``'s family."""
    from deepspeed_tpu.module_inject import convert_hf_state_dict

    sd = _strip_module_prefix(
        consolidate_reference_zero_checkpoint(ckpt_dir, tag))
    return convert_hf_state_dict(model, sd)
