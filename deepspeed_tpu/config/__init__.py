from deepspeed_tpu.config.config import (
    DeepSpeedConfig,
    FP16Config,
    BF16Config,
    ZeroConfig,
    OptimizerConfig,
    SchedulerConfig,
    TensorParallelConfig,
    PipelineParallelConfig,
    SequenceParallelConfig,
    ExpertParallelConfig,
    ActivationCheckpointingConfig,
    FlopsProfilerConfig,
    CommsLoggerConfig,
    MonitorConfig,
    CheckpointConfig,
    ElasticityConfig,
    load_config,
)
from deepspeed_tpu.config.config_utils import ConfigModel, AUTO

__all__ = [
    "DeepSpeedConfig", "FP16Config", "BF16Config", "ZeroConfig",
    "OptimizerConfig", "SchedulerConfig", "TensorParallelConfig",
    "PipelineParallelConfig", "SequenceParallelConfig", "ExpertParallelConfig",
    "ActivationCheckpointingConfig", "FlopsProfilerConfig", "CommsLoggerConfig",
    "MonitorConfig", "CheckpointConfig", "ElasticityConfig", "load_config",
    "ConfigModel", "AUTO",
]
